"""RGW-lite: bucket/object gateway semantics over RADOS.

The storage model of reference src/rgw's RGWRados (rgw_rados.h:400)
without the HTTP frontends: every bucket has an INDEX object whose omap
maps key -> entry metadata (the cls_rgw bucket-index pattern — the index
is maintained server-side so listing never scans data objects), object
data lives in per-key RADOS objects (striped above 4 MiB, the manifest
role), and user metadata + etag ride xattrs. S3-visible behaviors kept:
listing with prefix/marker/max_keys, etag as hex md5, copy, and
conditional puts.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import math
import random
import secrets
import time
import zlib
from contextlib import contextmanager

from ceph_tpu.common.compressor import get_compressor, list_compressors
from ceph_tpu.common.log import Dout
from ceph_tpu.common.tracing import Tracer, use_span

from ceph_tpu.client.rados import (IoCtx, ObjectOperation, RadosError,
                                   full_try)
from ceph_tpu.client.striper import RadosStriper, StripeLayout

rgw_log = Dout("rgw")

BUCKETS_OID = "rgw.buckets"          # omap: bucket name -> meta
STRIPE_THRESHOLD = 4 * 1024 * 1024


def _reclaims_space(fn):
    """Delete-flow methods run under CEPH_OSD_FLAG_FULL_TRY semantics:
    their sideband writes (bilog 'call' append, versioned delete-marker
    omap_set, GC-enqueue create+omap_set) must not bounce with EDQUOT on
    a quota-full pool, or users could never delete their way back under
    quota (the reference flags delete-class ops the same way)."""
    import functools

    @functools.wraps(fn)
    async def wrapper(*a, **kw):
        with full_try():
            return await fn(*a, **kw)
    return wrapper


# -- SSE-C (reference rgw_crypt.cc customer-key encryption) ---------------
# AES-256-CTR with a per-object random nonce: the keystream is seekable
# (counter = nonce + byte_offset/16), so ranged GETs decrypt any window
# without reading from zero — the role of the reference's chunk-aligned
# AES-CBC scheme.  The key is never stored; only its MD5 rides the index
# entry so GETs can validate the presented key (S3 SSE-C contract).

def manifest_window(sizes: list[int], start: int, end: int
                    ) -> list[tuple[int, int, int]]:
    """(segment index, offset-in-segment, length) triples covering the
    inclusive byte range [start, end] of the concatenation — the one
    overlap computation multipart reads, SLO and DLO all share."""
    out = []
    if end < start:
        return out
    pos = 0
    for i, psize in enumerate(sizes):
        pstart, pend = pos, pos + psize - 1
        pos += psize
        if psize <= 0 or pend < start:
            continue
        if pstart > end:
            break
        off = max(0, start - pstart)
        length = min(pend, end) - (pstart + off) + 1
        out.append((i, off, length))
    return out


def sse_begin(key: bytes) -> dict:
    if len(key) != 32:
        raise RGWError("InvalidArgument", "SSE-C key must be 32 bytes")
    return {
        "alg": "AES256",
        "key_md5": hashlib.md5(key).hexdigest(),
        "nonce": secrets.token_bytes(16).hex(),
    }


def sse_crypt(key: bytes, nonce: bytes, offset: int,
              data: bytes) -> bytes:
    """En/decrypt ``data`` as the CTR keystream window starting at byte
    ``offset`` of the object (CTR: encrypt == decrypt)."""
    from cryptography.hazmat.primitives.ciphers import (
        Cipher,
        algorithms,
        modes,
    )

    counter = (int.from_bytes(nonce, "big") + offset // 16) % (1 << 128)
    enc = Cipher(
        algorithms.AES(key),
        modes.CTR(counter.to_bytes(16, "big")),
    ).encryptor()
    skip = offset % 16
    if skip:
        enc.update(b"\0" * skip)        # discard partial-block keystream
    return enc.update(data)


def sse_check(entry: dict, key: bytes | None) -> None:
    """S3 semantics: an SSE-C object requires the matching key on every
    read; presenting a key for a plaintext object is an error too.
    KMS-managed entries (SSE-KMS / SSE-S3, marked by a wrapped data
    key) are server-decrypted — presenting an SSE-C key is an error."""
    sse = entry.get("sse")
    if sse is None:
        if key is not None:
            raise RGWError("InvalidRequest",
                           "object is not SSE-C encrypted")
        return
    if sse.get("wrapped") is not None:
        if key is not None:
            raise RGWError("InvalidRequest",
                           "object is KMS-encrypted, not SSE-C")
        return
    if key is None:
        raise RGWError("InvalidRequest",
                       "object is SSE-C encrypted; key required")
    if hashlib.md5(key).hexdigest() != sse["key_md5"]:
        raise RGWError("AccessDenied", "SSE-C key mismatch")


USERS_OID = "rgw.users"              # omap: uid -> user record json
KEYS_OID = "rgw.users.keys"          # omap: access key -> uid
STS_KEYS_OID = "rgw.users.sts"       # omap: temp access key -> record

_PERM_ORDER = {"READ": 0, "WRITE": 1, "FULL_CONTROL": 2}
_CANNED_ACLS = ("private", "public-read", "public-read-write",
                "authenticated-read")
ANONYMOUS = "anonymous"


class RGWUsers:
    """User database + S3-style key auth (the rgw_user / RGWUserCtl
    role, reference src/rgw/rgw_user.cc + radosgw-admin user ops):
    records in one omap object, an access-key index for login, per-user
    quota fields, and an HMAC check standing in for SigV4."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx

    async def create(self, uid: str, display_name: str = "",
                     max_size: int = 0, max_objects: int = 0) -> dict:
        try:
            kv = await self.ioctx.get_omap(USERS_OID, [uid])
        except RadosError as e:
            if e.rc != -2:
                raise
            kv = {}
        if uid in kv:
            raise RGWError("UserAlreadyExists", uid)
        rec = {
            "uid": uid, "display_name": display_name or uid,
            "access_key": secrets.token_hex(10).upper(),
            "secret_key": secrets.token_hex(20),
            "quota": {"max_size": int(max_size),
                      "max_objects": int(max_objects)},
            "suspended": False,
        }
        await self.ioctx.operate(USERS_OID, ObjectOperation()
                                 .create()
                                 .omap_set({uid: json.dumps(rec)
                                            .encode()}))
        await self.ioctx.operate(KEYS_OID, ObjectOperation()
                                 .create()
                                 .omap_set({rec["access_key"]:
                                            uid.encode()}))
        return rec

    async def _all(self) -> dict[str, dict]:
        try:
            return {
                uid: json.loads(raw) for uid, raw in
                (await self.ioctx.get_omap(USERS_OID)).items()
            }
        except RadosError as e:
            if e.rc == -2:
                return {}
            raise

    async def get(self, uid: str) -> dict:
        try:
            kv = await self.ioctx.get_omap(USERS_OID, [uid])
        except RadosError as e:
            if e.rc == -2:
                kv = {}
            else:
                raise
        if uid not in kv:
            raise RGWError("NoSuchUser", uid)
        return json.loads(kv[uid])

    async def list(self) -> list[str]:
        return sorted(await self._all())

    async def remove(self, uid: str) -> None:
        rec = await self.get(uid)
        await self.ioctx.rm_omap_keys(USERS_OID, [uid])
        await self.ioctx.rm_omap_keys(KEYS_OID, [rec["access_key"]])

    async def set_quota(self, uid: str, max_size: int = 0,
                        max_objects: int = 0) -> None:
        rec = await self.get(uid)
        rec["quota"] = {"max_size": int(max_size),
                        "max_objects": int(max_objects)}
        await self.ioctx.set_omap(USERS_OID,
                                  {uid: json.dumps(rec).encode()})

    async def set_swift_meta(self, uid: str,
                             meta: dict[str, str]) -> None:
        """Swift account metadata (X-Account-Meta-*), on the user
        record like the reference's RGWUserInfo attrs.  Re-reads the
        record and patches ONLY swift_meta: a client-driven account
        POST must not write a stale whole record over a concurrent
        admin mutation (e.g. set_suspended)."""
        rec = await self.get(uid)
        rec["swift_meta"] = {str(k): str(v) for k, v in meta.items()}
        await self.ioctx.set_omap(
            USERS_OID, {uid: json.dumps(rec).encode()})

    async def set_suspended(self, uid: str,
                            suspended: bool = True) -> None:
        """radosgw-admin user suspend/enable: a suspended user fails
        every auth path (library HMAC and the HTTP frontend's SigV4)."""
        rec = await self.get(uid)
        rec["suspended"] = bool(suspended)
        await self.ioctx.set_omap(USERS_OID,
                                  {uid: json.dumps(rec).encode()})

    # -- STS (rgw_sts.cc AssumeRole role, -lite) ---------------------------
    async def sts_assume(self, uid: str, ttl: int = 3600,
                         role: str = "assumed-role") -> dict:
        """Mint temporary credentials for ``uid`` (GetSessionToken /
        AssumeRole): a time-bounded access/secret pair plus a session
        token the frontend requires on every signed request."""
        rec = await self.get(uid)
        if rec.get("suspended"):
            raise RGWError("AccessDenied", f"{uid} suspended")
        if not 1 <= int(ttl) <= 12 * 3600:
            raise RGWError("InvalidArgument", "ttl out of range")
        creds = {
            "uid": uid, "role": str(role),
            "access_key": "STS" + secrets.token_hex(8).upper(),
            "secret_key": secrets.token_hex(20),
            "session_token": secrets.token_hex(24),
            "expiration": time.time() + int(ttl),
        }
        await self.ioctx.operate(STS_KEYS_OID, ObjectOperation()
                                 .create()
                                 .omap_set({creds["access_key"]:
                                            json.dumps(creds)
                                            .encode()}))
        return creds

    async def sts_get(self, access_key: str) -> dict | None:
        """The live temp-credential record, or None (absent/expired —
        expired records are reaped on lookup)."""
        try:
            kv = await self.ioctx.get_omap(STS_KEYS_OID, [access_key])
        except RadosError as e:
            if e.rc == -2:
                return None
            raise
        if access_key not in kv:
            return None
        rec = json.loads(kv[access_key])
        if rec["expiration"] < time.time():
            try:
                await self.ioctx.rm_omap_keys(STS_KEYS_OID,
                                              [access_key])
            except RadosError:
                pass
            return None
        return rec

    async def authenticate(self, access_key: str, signature: str,
                           string_to_sign: bytes) -> str:
        """hmac-sha256(secret, string_to_sign) == signature -> uid
        (the SigV4 role collapsed to one hmac)."""
        import hmac as _hmac

        try:
            kv = await self.ioctx.get_omap(KEYS_OID, [access_key])
        except RadosError as e:
            if e.rc == -2:
                kv = {}
            else:
                raise
        if access_key not in kv:
            raise RGWError("InvalidAccessKeyId", access_key)
        rec = await self.get(kv[access_key].decode())
        want = _hmac.new(rec["secret_key"].encode(), string_to_sign,
                         hashlib.sha256).hexdigest()
        if not _hmac.compare_digest(want, signature):
            raise RGWError("SignatureDoesNotMatch", access_key)
        if rec.get("suspended"):
            raise RGWError("AccessDenied", "user suspended")
        return rec["uid"]


COMP_BLOCK = 4 * 1024 * 1024


def deflate_if_smaller(data: bytes,
                       alg: str = "zlib") -> tuple[bytes, dict | None]:
    """Whole-body at-rest compression (rgw_compression.cc role for
    small objects) through the shared compressor registry
    (common/compressor, Compressor.h:33): kept only when it actually
    shrinks."""
    packed = get_compressor(alg).compress(data)
    if len(packed) < len(data):
        return packed, {"alg": alg, "stored_size": len(packed)}
    return data, None


def comp_window(blocks, start: int, end: int):
    """Map an inclusive INFLATED byte range onto independently-deflated
    blocks (the reference's compression block map, rgw_compression.h
    RGWCompressionInfo role): (stored_off, stored_len, skip, take)
    per intersecting block — inflate the block's stored bytes, then
    slice inflated[skip:skip+take].  The overlap math is
    manifest_window over the inflated block sizes; this only adds the
    stored-offset prefix sum."""
    stored_off = [0]
    for _, stored_len in blocks:
        stored_off.append(stored_off[-1] + stored_len)
    return [(stored_off[i], blocks[i][1], skip, take)
            for i, skip, take in manifest_window(
                [b[0] for b in blocks], start, end)]


class StreamingPut:
    """One chunked PUT in flight (rgw_putobj processor role): write()
    places each chunk at its running offset (striper for large bodies),
    md5/SSE state accumulate incrementally, complete() publishes the
    index entry, abort() removes whatever landed."""

    def __init__(self, rgw: "RGWLite", ctx: dict, length: int,
                 content_type: str, metadata: dict):
        self._rgw = rgw
        self._ctx = ctx
        self.length = length
        self._content_type = content_type
        self._metadata = metadata
        # SSE-C only via set_sse_key: an sse record without the key
        # would store plaintext under an entry claiming encryption
        self._sse: dict | None = None
        self._sse_key: bytes | None = None
        self._pos = 0
        self._md5 = hashlib.md5()
        self._striped = length > STRIPE_THRESHOLD
        self._buf = bytearray() if not self._striped else None
        # at-rest compression rides the stream: striped bodies deflate
        # per COMP_BLOCK into a block map so reads keep random access
        # and bounded memory; small ones stay buffered and compress at
        # complete() exactly like the buffered path
        self._comp_alg = (ctx.get("compression")
                          if ctx.get("compression") in list_compressors()
                          else None)
        self._cpos = 0
        self._blkbuf = bytearray() if self._striped else None
        self._blocks: list[list[int]] = []

    async def _handles(self):
        # the placement pool this object's storage class resolved to
        # (zone pool when ctx carries none)
        return await self._rgw._data_handles(self._ctx.get("pool"))

    def set_sse_key(self, key: bytes) -> None:
        if self._pos:
            raise RGWError("InvalidRequest",
                           "SSE-C key must be set before the first "
                           "body chunk")
        self._sse = sse_begin(key)
        self._sse_key = key
        # SSE-C excludes at-rest compression (ciphertext doesn't
        # deflate), matching the buffered put_object path
        self._comp_alg = None

    def set_sse_kms(self, data_key: bytes, sse_record: dict) -> None:
        """SSE-KMS / SSE-S3 streaming: encrypt under a KMS-wrapped
        data key (from RGWLite._kms_begin); the record (with the
        wrapped blob) rides the entry."""
        if self._pos:
            raise RGWError("InvalidRequest",
                           "encryption must start before the first "
                           "body chunk")
        self._sse = dict(sse_record)
        self._sse_key = data_key
        self._comp_alg = None

    async def write(self, chunk: bytes) -> None:
        if self._pos + len(chunk) > self.length:
            await self.abort()
            raise RGWError("InvalidArgument",
                           "body exceeds declared Content-Length")
        self._md5.update(chunk)
        if self._sse_key is not None:
            chunk = sse_crypt(self._sse_key,
                              bytes.fromhex(self._sse["nonce"]),
                              self._pos, chunk)
        if self._striped:
            if self._comp_alg is not None:
                self._blkbuf += chunk
                while len(self._blkbuf) >= COMP_BLOCK:
                    await self._emit_block(
                        bytes(self._blkbuf[:COMP_BLOCK]))
                    del self._blkbuf[:COMP_BLOCK]
            else:
                _, striper = await self._handles()
                await striper.write(self._ctx["oid"], chunk,
                                    offset=self._pos)
        else:
            self._buf += chunk
        self._pos += len(chunk)

    async def _emit_block(self, raw: bytes) -> None:
        # each block compresses independently (always kept: a streamed
        # body can't be un-written, and per-block framing overhead is
        # ~0.03% worst case) so reads seek straight to any block
        packed = get_compressor(self._comp_alg).compress(raw)
        _, striper = await self._handles()
        await striper.write(self._ctx["oid"], packed,
                            offset=self._cpos)
        self._blocks.append([len(raw), len(packed)])
        self._cpos += len(packed)

    async def complete(self) -> dict:
        if self._pos != self.length:
            await self.abort()
            raise RGWError("IncompleteBody",
                           f"{self._pos} of {self.length} bytes")
        comp = None
        if self._striped and self._comp_alg is not None:
            if self._blkbuf:
                await self._emit_block(bytes(self._blkbuf))
                self._blkbuf.clear()
            comp = {"alg": self._comp_alg, "stored_size": self._cpos,
                    "blocks": self._blocks}
        elif not self._striped:
            data = bytes(self._buf)
            if self._comp_alg is not None:
                data, comp = deflate_if_smaller(data, self._comp_alg)
            ioctx, _ = await self._handles()
            await ioctx.operate(
                self._ctx["oid"],
                ObjectOperation().write_full(data))
        # replaced object's data (and version-store adoption) happen
        # only now — with the new bytes fully down, just before the
        # index flips to them; an aborted stream never reaches here
        bucket, key = self._ctx["bucket"], self._ctx["key"]
        for action, arg in self._ctx.get("deferred_cleanup") or ():
            if action == "adopt":
                await self._rgw._adopt_null_version(bucket, key, arg)
            elif action == "null":
                await self._rgw._remove_null_version(bucket, key)
            else:
                await self._rgw._remove_entry_data(bucket, key, arg)
        return await self._rgw._finish_put(
            self._ctx, self.length, self._md5.hexdigest(),
            self._striped, self._content_type, self._metadata,
            self._sse, comp=comp)

    async def abort(self) -> None:
        """Drop any data already landed; the index was never touched."""
        try:
            ioctx, striper = await self._handles()
            if self._striped:
                await striper.remove(self._ctx["oid"])
            else:
                await ioctx.remove(self._ctx["oid"])
        except RadosError as e:
            if e.rc != -2:
                raise


class RGWError(IOError):
    def __init__(self, code: str, msg: str = ""):
        super().__init__(f"{code}: {msg}")
        self.code = code


class RGWLite:
    def __init__(self, ioctx: IoCtx, datalog: bool = True,
                 user: str | None = None,
                 users: "RGWUsers | None" = None,
                 gc_min_wait: float = 0.0,
                 auto_reshard_objs: int = 0,
                 kms=None, datalog_shards: int = 1):
        """``datalog``: append every mutation to the per-bucket data log
        (the cls_rgw bilog) so a multisite sync agent can tail it.
        ``user``: the acting identity for ACL/quota enforcement (None =
        system/admin context, every check bypassed — the pre-round-2
        behavior); ``users``: the user db backing quota lookups.
        ``gc_min_wait``: >0 defers data-object deletion to the GC queue
        for that many seconds (rgw_gc_obj_min_wait; 0 = delete inline).
        ``auto_reshard_objs``: >0 doubles a bucket's index shards when
        any one shard exceeds this many entries (rgw dynamic
        resharding's rgw_max_objs_per_shard; 0 = off)."""
        self.ioctx = ioctx
        self.datalog = datalog
        # bucket-datalog shard fan-out (rgw_data_log_num_shards role):
        # mutations hash by object key onto a shard log so multisite
        # replay and trim parallelise; shard 0 keeps the legacy oid so
        # a 1-shard config is byte-compatible with pre-shard logs
        self.datalog_shards = max(1, int(datalog_shards))
        self.user = user
        self.users = users
        self.gc_min_wait = gc_min_wait
        self.auto_reshard_objs = auto_reshard_objs
        # KMS backend for SSE-KMS / SSE-S3 (services.kms; rgw_kms.h)
        self.kms = kms
        # bucket -> (fetched_at, notification configs); shared across
        # as_user handles so invalidation is seen by every identity
        self._notif_cache: dict[str, tuple[float, list]] = {}
        # push-mode delivery state (rgw_notify.cc persistent topics):
        # topic -> (worker task, wake event); topic meta cache.  Shared
        # across as_user handles like _notif_cache.
        self._pushers: dict[str, tuple] = {}
        self._topics_cache: dict[str, tuple[float, dict | None]] = {}
        # front-door QoS admission telemetry (rgw_http sheds overload
        # with 503 Slow Down and counts here; shared across as_user
        # handles so one gateway keeps one ledger)
        self.qos_stats: dict[str, int] = {
            "admitted": 0, "shed_inflight": 0, "shed_session": 0}
        self.striper = RadosStriper(ioctx, StripeLayout(
            stripe_unit=512 * 1024, stripe_count=4,
            object_size=4 * 1024 * 1024,
        ))
        # per-storage-class data pool handles (zone placement targets):
        # pool name -> (IoCtx, RadosStriper).  Shared across as_user
        # handles like the caches above so one gateway keeps one handle
        # per tier pool.
        self._pool_handles: dict[str, tuple] = {}
        # request tracing (zipkin-lite): sampled S3 requests open the
        # root span here, so the whole rgw -> objecter -> OSD path
        # reassembles into one tree.  One ring per gateway, shared
        # across as_user handles like the caches above.
        self.tracer = Tracer("rgw")

    def as_user(self, user: str | None) -> "RGWLite":
        """A handle acting as ``user`` over the same pool."""
        child = RGWLite(self.ioctx, self.datalog, user, self.users,
                        self.gc_min_wait, self.auto_reshard_objs,
                        kms=self.kms,
                        datalog_shards=self.datalog_shards)
        child._notif_cache = self._notif_cache
        child._pushers = self._pushers
        child._topics_cache = self._topics_cache
        child._pool_handles = self._pool_handles
        child.qos_stats = self.qos_stats
        child.tracer = self.tracer
        return child

    @contextmanager
    def _trace_root(self, name: str, **tags):
        """Open a sampled root span for one S3 request and make it the
        ambient span — the objecter sees it via current_span() and
        parents every resulting RADOS op under the request (the
        rgw_trace/req_state->trace linkage).  Yields None unsampled."""
        try:
            prob = float(
                self.ioctx.rados.conf["trace_probability"] or 0.0)
        except (KeyError, TypeError, ValueError, AttributeError):
            prob = 0.0
        if not prob or random.random() >= prob:
            yield None
            return
        with self.tracer.span(name, **tags) as ctx:
            with use_span(ctx):
                yield ctx

    # -- storage classes / placement pools (rgw_placement_rule) -----------
    async def _data_handles(self, pool: str | None):
        """(IoCtx, RadosStriper) for the pool an object's tail lives
        in.  Falsy / zone-pool -> the gateway's own handles; anything
        else (a COLD/ARCHIVE class's EC pool) opens once and caches.
        Index omaps, version records, and multipart metadata always
        stay in the zone pool — only tails move."""
        if not pool or pool == self.ioctx.pool_name:
            return self.ioctx, self.striper
        got = self._pool_handles.get(pool)
        if got is None:
            rados = self.ioctx.rados
            try:
                ioctx = await rados.open_ioctx(pool)
            except RadosError as e:
                if e.rc != -2:
                    raise
                # our osdmap may lag a pool another client just
                # created; wait briefly, then retry once
                try:
                    await rados._wait_pool(pool, timeout=5.0)
                except Exception:
                    raise RGWError(
                        "InvalidStorageClass",
                        f"placement pool {pool!r} does not exist",
                    ) from None
                ioctx = await rados.open_ioctx(pool)
            got = (ioctx, RadosStriper(ioctx, StripeLayout(
                stripe_unit=512 * 1024, stripe_count=4,
                object_size=4 * 1024 * 1024,
            )))
            self._pool_handles[pool] = got
        return got

    async def _class_placement(self, storage_class: str) -> dict:
        """Resolve a storage class through the zone's placement target
        ({"pool", "compression"}); InvalidStorageClass for classes no
        placement defines — the error a PUT with a bogus
        x-amz-storage-class must surface."""
        from ceph_tpu.services.rgw_zone import ZonePlacement
        return await ZonePlacement(self.ioctx).resolve(storage_class)

    # -- SSE-KMS / SSE-S3 (rgw_kms.h + rgw_crypt.cc wiring) ---------------
    DEFAULT_KMS_KEY = "rgw/default"      # x-amz-...-aws-kms-key-id absent
    SSE_S3_KEY = "rgw/sse-s3"            # zone-managed SSE-S3 master key

    async def _kms_begin(self, alg: str, key_id: str | None
                         ) -> tuple[bytes, dict]:
        """Fresh per-object data key + the sse record to store (the
        wrapped blob rides the entry; the plaintext key never lands)."""
        if self.kms is None:
            raise RGWError("InvalidRequest",
                           "server-side encryption requires a KMS")
        if alg == "aws:kms":
            key_id = key_id or self.DEFAULT_KMS_KEY
        elif alg == "AES256":
            key_id = self.SSE_S3_KEY     # SSE-S3: zone-managed key
        else:
            raise RGWError("InvalidArgument",
                           f"bad server-side encryption {alg!r}")
        dk, wrapped = await self.kms.generate_data_key(key_id)
        return dk, {
            "alg": alg, "key_id": key_id, "wrapped": wrapped,
            "nonce": secrets.token_bytes(16).hex(),
        }

    async def _entry_sse_key(self, entry: dict,
                             sse_key: bytes | None) -> bytes | None:
        """Resolve the data key that decrypts ``entry`` — the
        presented SSE-C key, a KMS unwrap, or None for plaintext."""
        from ceph_tpu.services.kms import KMSError

        sse_check(entry, sse_key)
        sse = entry.get("sse")
        if sse is None:
            return None
        if sse.get("wrapped") is not None:
            if self.kms is None:
                raise RGWError("InvalidRequest",
                               "object is KMS-encrypted but no KMS "
                               "is configured")
            try:
                return await self.kms.unwrap_data_key(
                    sse["key_id"], sse["wrapped"])
            except KMSError as e:
                raise RGWError("AccessDenied", str(e)) from e
        return sse_key

    # -- ACL (rgw_acl.cc canned subset + explicit grants) ------------------
    async def _bucket_meta(self, bucket: str) -> dict:
        try:
            kv = await self.ioctx.get_omap(BUCKETS_OID, [bucket])
        except RadosError as e:
            if e.rc == -2:
                kv = {}
            else:
                raise
        if bucket not in kv:
            raise RGWError("NoSuchBucket", bucket)
        return json.loads(kv[bucket])

    async def _put_bucket_meta(self, bucket: str, meta: dict) -> None:
        await self.ioctx.set_omap(
            BUCKETS_OID, {bucket: json.dumps(meta).encode()}
        )

    def _acl_allows(self, owner: str, acl: dict, need: str) -> bool:
        if self.user is None:
            return True             # system context
        if self.user == owner:
            return True
        canned = acl.get("canned", "private")
        # canned publics grant data access only — never FULL_CONTROL
        # (ACL/quota/lifecycle administration stays with the owner and
        # explicit FULL_CONTROL grantees)
        if canned == "public-read-write" and need in ("READ", "WRITE"):
            return True
        if canned == "public-read" and need == "READ":
            return True
        if canned == "authenticated-read" and need == "READ" \
                and self.user != ANONYMOUS:
            return True
        for grant in acl.get("grants", ()):
            if grant.get("grantee") in (self.user, "*") and \
                    _PERM_ORDER.get(grant.get("perm"), -1) >= \
                    _PERM_ORDER[need]:
                return True
        return False

    async def _check_bucket(self, bucket: str, need: str,
                            action: str | None = None,
                            key: str | None = None) -> dict:
        """ACL + bucket-policy gate (the rgw_op.cc verify_permission
        order: policy Deny short-circuits, policy Allow grants, no
        match falls back to the ACL).

        Policy applies ONLY at call sites that name an IAM ``action``
        (the object data path).  Bucket administration and config ops
        pass no action and stay owner/ACL-gated: an object-data grant
        (s3:PutObject on bucket/*) must never open notification/
        versioning/ACL configuration, and the owner can always delete
        a bad policy (no lockout)."""
        meta = await self._bucket_meta(bucket)
        policy = meta.get("policy")
        if policy is not None and self.user is not None \
                and action is not None:
            from ceph_tpu.services import iam

            resource = f"{bucket}/{key}" if key is not None else bucket
            verdict = iam.evaluate(policy, self.user, action, resource)
            if verdict == "deny":
                raise RGWError("AccessDenied",
                               f"{bucket} ({action} denied by policy)")
            if verdict == "allow":
                return meta
        if not self._acl_allows(meta.get("owner", ""),
                                meta.get("acl", {}), need):
            raise RGWError("AccessDenied", f"{bucket} ({need})")
        return meta

    # -- bucket policy (rgw_iam_policy.cc) ---------------------------------
    async def put_bucket_policy(self, bucket: str,
                                policy: str | dict) -> None:
        from ceph_tpu.services import iam

        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        try:
            doc = iam.validate(policy)
        except iam.PolicyError as e:
            raise RGWError("MalformedPolicy", str(e)) from None
        meta["policy"] = doc
        await self._put_bucket_meta(bucket, meta)

    async def get_bucket_policy(self, bucket: str) -> dict:
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        policy = meta.get("policy")
        if policy is None:
            raise RGWError("NoSuchBucketPolicy", bucket)
        return policy

    async def delete_bucket_policy(self, bucket: str) -> None:
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        meta.pop("policy", None)
        await self._put_bucket_meta(bucket, meta)

    async def put_bucket_acl(self, bucket: str, canned: str = "private",
                             grants: list[dict] | None = None) -> None:
        if canned not in _CANNED_ACLS:
            raise RGWError("InvalidArgument", canned)
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        meta["acl"] = {"canned": canned, "grants": list(grants or ())}
        await self._put_bucket_meta(bucket, meta)

    async def get_bucket_acl(self, bucket: str) -> dict:
        """Owner / FULL_CONTROL grantees only (the READ_ACP gate):
        grant lists and ownership are not disclosed to mere readers."""
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        return {"owner": meta.get("owner", ""),
                "acl": meta.get("acl", {"canned": "private"})}

    # -- quota (rgw_quota.cc: user + bucket ceilings) ----------------------
    async def _bucket_usage(self, bucket: str,
                            meta: dict | None = None
                            ) -> tuple[int, int]:
        """(bytes, objects) from the bucket index — computed on demand
        (the reference keeps rolling stats in the index header; at our
        scale a scan is exact and race-free)."""
        index = await self._index_all(bucket, meta)
        entries = {k: json.loads(v) for k, v in index.items()}
        entries = {k: e for k, e in entries.items()
                   if not e.get("delete_marker")}
        total = sum(e["size"] for e in entries.values())
        count = len(entries)
        # non-current versions hold real bytes too.  Current versions
        # are keyed by (object key, version id): the id alone is
        # ambiguous — every adopted pre-versioning object is 'null'
        current = {(k, e.get("version_id"))
                   for k, e in entries.items()}
        try:
            vomap = await self.ioctx.get_omap(
                self._versions_oid(bucket))
        except RadosError as e:
            if e.rc != -2:
                raise
            vomap = {}
        for vk, raw in vomap.items():
            key, _, vid = vk.partition("\x00")
            v = json.loads(raw)
            if v.get("delete_marker") or (key, vid) in current:
                continue
            total += int(v.get("size", 0))
            count += 1
        return total, count

    async def set_bucket_quota(self, bucket: str, max_size: int = 0,
                               max_objects: int = 0) -> None:
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        meta["quota"] = {"max_size": int(max_size),
                         "max_objects": int(max_objects)}
        await self._put_bucket_meta(bucket, meta)

    async def _check_quota(self, bucket: str, meta: dict,
                           incoming: int, replaced_size: int,
                           is_replace: bool) -> None:
        bq = meta.get("quota") or {}
        uq = {}
        owner = meta.get("owner", "")
        if self.users is not None and owner:
            try:
                uq = (await self.users.get(owner)).get("quota") or {}
            except RGWError:
                uq = {}
        if not bq.get("max_size") and not bq.get("max_objects") \
                and not uq.get("max_size") and not uq.get("max_objects"):
            return
        used_bytes, used_objs = await self._bucket_usage(bucket, meta)
        new_bytes = used_bytes - replaced_size + incoming
        new_objs = used_objs + (0 if is_replace else 1)
        if bq.get("max_size") and new_bytes > bq["max_size"]:
            raise RGWError("QuotaExceeded", f"bucket {bucket} size")
        if bq.get("max_objects") and new_objs > bq["max_objects"]:
            raise RGWError("QuotaExceeded", f"bucket {bucket} objects")
        if uq.get("max_size") or uq.get("max_objects"):
            total_bytes = total_objs = 0
            for b in await self.list_buckets():
                try:
                    if (await self._bucket_meta(b)).get("owner") \
                            != owner:
                        continue
                except RGWError:
                    continue
                bb, bo = await self._bucket_usage(b)
                if b == bucket:
                    bb, bo = new_bytes, new_objs
                total_bytes += bb
                total_objs += bo
            if uq.get("max_size") and total_bytes > uq["max_size"]:
                raise RGWError("QuotaExceeded", f"user {owner} size")
            if uq.get("max_objects") and total_objs > uq["max_objects"]:
                raise RGWError("QuotaExceeded", f"user {owner} objects")

    # -- object versioning (rgw_rados versioned-bucket model) -------------
    @staticmethod
    def _versions_oid(bucket: str) -> str:
        return f"rgw.bucket.versions.{bucket}"

    @staticmethod
    def _vkey(key: str, version_id: str) -> str:
        return f"{key}\x00{version_id}"

    # -- object tagging (rgw_tag.cc / rgw_obj_tags) ------------------------
    @staticmethod
    def validate_tags(tags: dict[str, str]) -> None:
        """One validator for every tag ingestion path (?tagging body,
        x-amz-tagging header, library calls)."""
        if len(tags) > 10:
            raise RGWError("InvalidTag", "at most 10 tags")
        for k, v in tags.items():
            if not k or len(k) > 128 or len(str(v)) > 256:
                raise RGWError("InvalidTag", k)

    async def _tag_update(self, bucket: str, meta: dict, key: str,
                          tags: dict[str, str] | None,
                          expect_etag: str | None = None) -> bool:
        """Atomic tag patch on the index entry (and the matching
        versions-omap record, so ?versionId reads and later history
        agree) via the rgw cls — a client-side read-modify-write
        could silently revert a concurrent PUT's entry."""
        self._index_writable(meta)
        payload = {"key": key, "tags": tags or {},
                   "expect_object": True}
        if expect_etag is not None:
            payload["expect_etag"] = expect_etag
        try:
            out = json.loads(await self.ioctx.exec(
                self._index_oid_for(bucket, meta, key), "rgw",
                "tag_update", json.dumps(payload).encode()))
        except RadosError as e:
            if e.rc == -2:
                raise RGWError("NoSuchKey", f"{bucket}/{key}")
            raise
        if not out.get("applied"):
            return False
        # mirror onto the version record of the entry the cls ACTUALLY
        # patched (its reply carries the version_id — re-reading the
        # index here could see a racing writer's entry and mis-tag it)
        vid = out.get("version_id")
        if vid:
            try:
                await self.ioctx.exec(
                    self._versions_oid(bucket), "rgw",
                    "tag_update", json.dumps({
                        "key": self._vkey(key, vid),
                        "tags": tags or {}}).encode())
            except RadosError as e:
                if e.rc != -2:
                    raise
        # a bilog entry so multisite sync replicates the tag change
        # (a DISTINCT op: ObjectCreated subscribers must not see a
        # creation event for a tag write; the sync tailer's reconcile
        # branch converges unknown ops on source state, tags included)
        kv = await self._index_get(bucket, key, meta)
        if key in kv:
            await self._log(bucket, "put-tagging", key,
                            json.loads(kv[key]).get("etag", ""))
        return True

    async def _tag_update_version(self, bucket: str, meta: dict,
                                  key: str, version_id: str,
                                  tags: dict | None) -> None:
        """Tag a SPECIFIC version's record; when that version is also
        current, the index entry follows, etag-guarded so a racing
        overwrite's entry never inherits the old version's tags."""
        self._index_writable(meta)     # BEFORE any write: a 503 must
        # not leave version and index records disagreeing
        try:
            await self.ioctx.exec(
                self._versions_oid(bucket), "rgw", "tag_update",
                json.dumps({"key": self._vkey(key, version_id),
                            "tags": tags or {},
                            "expect_object": True}).encode())
        except RadosError as e:
            if e.rc == -2:
                raise RGWError("NoSuchVersion",
                               f"{key}@{version_id}")
            raise
        kv = await self._index_get(bucket, key, meta)
        if key in kv:
            cur = json.loads(kv[key])
            if cur.get("version_id") == version_id:
                await self._tag_update(bucket, meta, key, tags,
                                       expect_etag=cur.get("etag"))

    async def put_object_tagging(self, bucket: str, key: str,
                                 tags: dict[str, str],
                                 version_id: str | None = None
                                 ) -> None:
        """S3 PutObjectTagging (?versionId targets that version)."""
        meta = await self._check_bucket(
            bucket, "WRITE", action="s3:PutObjectTagging", key=key)
        self.validate_tags(tags)
        if version_id:
            await self._tag_update_version(bucket, meta, key,
                                           version_id, dict(tags))
        else:
            await self._tag_update(bucket, meta, key, dict(tags))

    async def get_object_tagging(self, bucket: str, key: str,
                                 version_id: str | None = None
                                 ) -> dict[str, str]:
        if version_id:
            await self._check_bucket(
                bucket, "READ", action="s3:GetObjectTagging",
                key=key)
            entry = await self._lookup_version_entry(bucket, key,
                                                     version_id)
        else:
            entry = await self._entry(bucket, key,
                                      action="s3:GetObjectTagging")
        return dict(entry.get("tags") or {})

    async def delete_object_tagging(self, bucket: str, key: str,
                                    version_id: str | None = None
                                    ) -> None:
        meta = await self._check_bucket(
            bucket, "WRITE", action="s3:DeleteObjectTagging", key=key)
        if version_id:
            await self._tag_update_version(bucket, meta, key,
                                           version_id, None)
        else:
            await self._tag_update(bucket, meta, key, None)

    # -- CORS (rgw_cors.cc) ------------------------------------------------
    async def put_bucket_cors(self, bucket: str,
                              rules: list[dict]) -> None:
        """rules: [{allowed_origins, allowed_methods,
        allowed_headers?, expose_headers?, max_age_seconds?}] —
        origins may carry one ``*`` wildcard, as S3 allows."""
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        if not rules:
            # S3 rejects a rule-less document (MalformedXML): an
            # empty config must not shadow NoSuchCORSConfiguration
            raise RGWError("InvalidArgument",
                           "CORSConfiguration needs at least one rule")
        for r in rules:
            if not r.get("allowed_origins") \
                    or not r.get("allowed_methods"):
                raise RGWError("InvalidArgument",
                               "rule needs origins + methods")
            bad = [m for m in r["allowed_methods"]
                   if m not in ("GET", "PUT", "POST", "DELETE",
                                "HEAD")]
            if bad:
                raise RGWError("InvalidArgument",
                               f"unsupported methods {bad}")
            multi = [p for p in r["allowed_origins"]
                     if p.count("*") > 1]
            multi += [p for p in r.get("allowed_headers", ())
                      if p.count("*") > 1]
            if multi:
                raise RGWError("InvalidRequest",
                               f"origins/headers allow at most one "
                               f"'*': {multi}")
        meta["cors"] = [dict(r) for r in rules]
        await self._put_bucket_meta(bucket, meta)

    async def get_bucket_cors(self, bucket: str) -> list[dict]:
        # a config document: owner-gated like policy/notification
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        cors = meta.get("cors")
        if cors is None:
            raise RGWError("NoSuchCORSConfiguration", bucket)
        return cors

    async def delete_bucket_cors(self, bucket: str) -> None:
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        meta.pop("cors", None)
        await self._put_bucket_meta(bucket, meta)

    @staticmethod
    def _cors_pattern_ok(pat: str, value: str) -> bool:
        """One-'*'-wildcard match (rgw_cors.cc host_name_matches),
        shared by origin and AllowedHeader evaluation."""
        if pat == "*":
            return True
        head, star, tail = pat.partition("*")
        if not star:
            return pat == value
        return (value.startswith(head) and value.endswith(tail)
                and len(value) >= len(head) + len(tail))

    @staticmethod
    def cors_match(rules: list[dict], origin: str,
                   method: str) -> dict | None:
        """First rule matching (origin, method)."""
        for r in rules:
            if method in r.get("allowed_methods", ()) and any(
                    RGWLite._cors_pattern_ok(p, origin)
                    for p in r.get("allowed_origins", ())):
                return r
        return None

    @staticmethod
    def cors_header_grant(rule: dict,
                          requested: list[str]) -> list[str] | None:
        """The requested headers when EVERY one is allowed by the
        rule (wildcard patterns included), else None — a preflight
        with any disallowed header must fail, not silently grant a
        subset the browser will reject anyway."""
        allowed = [h.lower() for h in rule.get("allowed_headers", ())]
        for h in requested:
            if not any(RGWLite._cors_pattern_ok(p, h.lower())
                       for p in allowed):
                return None
        return requested

    async def put_bucket_compression(self, bucket: str,
                                     alg: str | None = "zlib") -> None:
        """Per-bucket at-rest compression (rgw_compression.cc role):
        object PUTs store compressed bytes through the shared registry
        (common/compressor — zlib/zstd/lzma/bz2); S3-visible size/etag
        stay the ORIGINAL object's.  ``None`` disables (existing
        objects stay as stored, each entry remembering its alg)."""
        if alg is not None and alg not in list_compressors():
            raise RGWError("InvalidArgument", f"unknown algorithm {alg}")
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        if alg is None:
            meta.pop("compression", None)
        else:
            meta["compression"] = alg
        await self._put_bucket_meta(bucket, meta)

    async def get_bucket_compression(self, bucket: str) -> str | None:
        meta = await self._check_bucket(bucket, "READ")
        return meta.get("compression")

    async def put_bucket_versioning(self, bucket: str,
                                    enabled: bool) -> None:
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        if not enabled and (meta.get("object_lock")
                            or {}).get("enabled"):
            # suspension would let the implicit-null overwrite path
            # destroy WORM-protected data (S3 forbids it too)
            raise RGWError("InvalidBucketState",
                           "object-lock buckets cannot suspend "
                           "versioning")
        meta["versioning"] = "enabled" if enabled else "suspended"
        await self._put_bucket_meta(bucket, meta)

    async def get_bucket_versioning(self, bucket: str) -> str:
        meta = await self._check_bucket(bucket, "READ")
        return meta.get("versioning", "")

    async def _adopt_null_version(self, bucket: str, key: str,
                                  old: dict) -> None:
        """A current entry written BEFORE versioning was enabled has no
        version record; S3 keeps it as the 'null' version — without
        this, overwriting it would orphan its data forever."""
        if old.get("version_id") or old.get("delete_marker"):
            return
        adopted = dict(old)
        adopted["version_id"] = "null"
        adopted.setdefault("data_oid", self._data_oid(bucket, key))
        await self._record_version(bucket, key, adopted)

    async def _suspended_replaced(self, bucket: str, key: str,
                                  existing_raw) -> tuple[int, bool]:
        """(freed_bytes, replaces_a_counted_object) for a suspended-
        state overwrite: the stored 'null' version is what dies; a
        non-null current entry survives as history and frees nothing."""
        try:
            recs = await self.ioctx.get_omap(
                self._versions_oid(bucket),
                [self._vkey(key, "null")])
        except RadosError as e:
            if e.rc != -2:
                raise
            recs = {}
        if recs:
            rec = json.loads(next(iter(recs.values())))
            if rec.get("delete_marker"):
                return 0, False       # markers hold no counted bytes
            return int(rec.get("size", 0)), True
        if existing_raw is not None:
            old = json.loads(existing_raw)
            if not old.get("version_id") \
                    and not old.get("delete_marker"):
                return int(old.get("size", 0)), True
        return 0, False

    async def _remove_null_version(self, bucket: str,
                                   key: str) -> None:
        """Drop the existing 'null' version record and its data.
        Suspended-state PUT/DELETE *replace* the null version (S3
        suspended-bucket semantics) rather than stacking history."""
        vkey = self._vkey(key, "null")
        try:
            recs = await self.ioctx.get_omap(
                self._versions_oid(bucket), [vkey])
        except RadosError as e:
            if e.rc != -2:
                raise
            return
        if vkey not in recs:
            return
        await self._remove_entry_data(bucket, key,
                                      json.loads(recs[vkey]))
        await self.ioctx.rm_omap_keys(self._versions_oid(bucket),
                                      [vkey])

    async def _remove_entry_data(self, bucket: str, key: str,
                                 rec: dict) -> None:
        """Removal of an entry's data objects (plain, striped, or
        multipart); tolerant of already-gone objects.  With
        ``gc_min_wait`` > 0 the objects are queued for deferred GC
        deletion instead (rgw_gc tail deletion: the index entry dies
        now, the data dies after the grace window)."""
        items: list = []
        # items carry the tail's placement pool as a third element so
        # cold-tier tails die in their own pool (absent/None = zone
        # pool; 2-element entries from older GC queues still parse)
        pool = rec.get("pool")
        if rec.get("slo"):
            return                  # segments are independent objects
        if rec.get("multipart"):
            items += [["plain", p["oid"], pool]
                      for p in rec["multipart"]]
        elif rec.get("striped"):
            items.append(["striped",
                          rec.get("data_oid",
                                  self._data_oid(bucket, key)), pool])
        elif not rec.get("delete_marker"):
            items.append(["plain",
                          rec.get("data_oid",
                                  self._data_oid(bucket, key)), pool])
        if not items:
            return
        if self.gc_min_wait > 0:
            await self._gc_enqueue(items, bucket, key)
        else:
            await self._gc_delete(items)

    def _new_version_id(self) -> str:
        # time-ordered prefix so listing versions newest-first is a
        # reverse lexical sort
        return f"{int(time.time() * 1e6):016x}{secrets.token_hex(4)}"

    async def _record_version(self, bucket: str, key: str,
                              entry: dict) -> None:
        await self.ioctx.operate(
            self._versions_oid(bucket),
            ObjectOperation().create().omap_set({
                self._vkey(key, entry["version_id"]):
                json.dumps(entry).encode(),
            }),
        )

    async def list_object_versions(self, bucket: str,
                                   prefix: str = "") -> list[dict]:
        """Newest-first per key (S3 ListObjectVersions)."""
        await self._check_bucket(bucket, "READ",
                                 action="s3:ListBucketVersions")
        meta = await self._bucket_meta(bucket)
        try:
            omap = await self.ioctx.get_omap(self._versions_oid(bucket))
        except RadosError as e:
            if e.rc != -2:
                raise
            if not meta.get("versioning"):
                return []
            omap = {}
        current = await self._index_all(bucket, meta)
        current_entries = {k: json.loads(v)
                           for k, v in current.items()}
        current_vid = {k: e.get("version_id")
                       for k, e in current_entries.items()}
        out = []
        have = {tuple(vk.partition("\x00")[::2]) for vk in omap}
        for k, e in (current_entries.items()
                     if meta.get("versioning") else ()):
            # pre-versioning current: implicit, un-recorded 'null'
            if not k.startswith(prefix) or e.get("version_id") \
                    or e.get("delete_marker") or (k, "null") in have:
                continue
            item = {
                "key": k, "version_id": "null",
                "size": e.get("size", 0), "etag": e.get("etag", ""),
                "mtime": e.get("mtime", 0.0),
                "is_latest": True, "delete_marker": False,
            }
            if e.get("storage_class"):
                item["storage_class"] = e["storage_class"]
            out.append(item)
        for vk, raw in omap.items():
            key, _, vid = vk.partition("\x00")
            if not key.startswith(prefix):
                continue
            e = json.loads(raw)
            item = {
                "key": key, "version_id": vid,
                "size": e.get("size", 0), "etag": e.get("etag", ""),
                "mtime": e.get("mtime", 0.0),
                "is_latest": current_vid.get(key) == vid,
                "delete_marker": bool(e.get("delete_marker")),
                "tags": dict(e.get("tags") or {}),
            }
            if e.get("storage_class"):
                item["storage_class"] = e["storage_class"]
            out.append(item)
        # newest-first within each key, by write time: the adopted
        # 'null' version keeps its original (oldest) mtime while a
        # suspended-state 'null' PUT is genuinely newest — lexical
        # version-id order would missort 'null' ('n' > any hex digit)
        out.sort(key=lambda v: (
            v["mtime"],
            "" if v["version_id"] == "null" else v["version_id"],
        ), reverse=True)
        out.sort(key=lambda v: v["key"])      # stable: keys ascending
        return out

    async def _lookup_version_entry(self, bucket: str, key: str,
                                    version_id: str) -> dict:
        """The stored record for key@version_id ('null' falls back to
        an un-adopted pre-versioning current); raises on markers so
        GET and HEAD stay bit-identical in their semantics."""
        try:
            kv = await self.ioctx.get_omap(
                self._versions_oid(bucket),
                [self._vkey(key, version_id)],
            )
        except RadosError as e:
            if e.rc == -2:
                kv = {}
            else:
                raise
        if not kv and version_id == "null":
            cur = await self._index_get(bucket, key)
            if key in cur:
                e = json.loads(cur[key])
                if not e.get("version_id") \
                        and not e.get("delete_marker"):
                    kv = {key: cur[key]}
        if not kv:
            raise RGWError("NoSuchVersion", f"{key}@{version_id}")
        entry = json.loads(next(iter(kv.values())))
        if entry.get("delete_marker"):
            raise RGWError("MethodNotAllowed",
                           f"{key}@{version_id} is a delete marker")
        return entry

    async def get_object_version(self, bucket: str, key: str,
                                 version_id: str,
                                 sse_key: bytes | None = None) -> dict:
        """GET ?versionId= — any stored version, marker or not.
        ``sse_key``: SSE-C decryption, including multipart versions
        whose parts carry their own nonces."""
        await self._check_bucket(bucket, "READ",
                                 action="s3:GetObjectVersion", key=key)
        entry = await self._lookup_version_entry(bucket, key,
                                                 version_id)
        dk = await self._entry_sse_key(entry, sse_key)
        if entry.get("comp"):
            data = await self._inflate_read(entry, None)
        elif dk is not None and entry["sse"].get("multipart"):
            data = await self._read_manifest(
                entry["multipart"], int(entry["size"]), None,
                sse_key=dk, pool=entry.get("pool"))
        else:
            data = await self._read_entry_data(bucket, key, entry,
                                               None)
            if dk is not None:
                data = sse_crypt(
                    dk, bytes.fromhex(entry["sse"]["nonce"]),
                    0, data)
        return {"data": data, **entry}

    async def head_object_version(self, bucket: str, key: str,
                                  version_id: str) -> dict:
        """HEAD ?versionId=: the version's metadata without reading
        its (possibly huge) body."""
        await self._check_bucket(bucket, "READ",
                                 action="s3:GetObjectVersion", key=key)
        return await self._lookup_version_entry(bucket, key,
                                                version_id)

    @_reclaims_space
    async def delete_object_version(self, bucket: str, key: str,
                                    version_id: str,
                                    bypass_governance: bool = False
                                    ) -> None:
        """DELETE ?versionId=: permanently removes that version; when
        it was current, the next-newest version is promoted (markers
        included).  Object-lock retention and legal holds block this
        (markers never do — they destroy no data); GOVERNANCE yields
        to ``bypass_governance`` only when the caller also holds
        s3:BypassGovernanceRetention."""
        meta = await self._check_bucket(
            bucket, "WRITE", action="s3:DeleteObjectVersion", key=key)
        if bypass_governance:
            bypass_governance = await self._bypass_allowed(bucket,
                                                           key)
        vkey = self._vkey(key, version_id)
        try:
            kv = await self.ioctx.get_omap(self._versions_oid(bucket),
                                           [vkey])
        except RadosError as e:
            if e.rc == -2:
                kv = {}
            else:
                raise
        if not kv and version_id == "null":
            cur = await self._index_get(bucket, key, meta)
            if key in cur:
                e = json.loads(cur[key])
                if not e.get("version_id") \
                        and not e.get("delete_marker"):
                    why = self._lock_blocks_delete(
                        e, bypass_governance)
                    if why:
                        raise RGWError("AccessDenied", why)
                    await self._remove_entry_data(bucket, key, e)
                    await self._index_rm(bucket, meta, key)
                    await self._log(bucket, "del-version", key)
                    return
        if not kv:
            raise RGWError("NoSuchVersion", f"{key}@{version_id}")
        entry = json.loads(next(iter(kv.values())))
        if not entry.get("delete_marker"):
            why = self._lock_blocks_delete(entry, bypass_governance)
            if why:
                raise RGWError("AccessDenied", why)
        await self._remove_entry_data(bucket, key, entry)
        await self.ioctx.rm_omap_keys(self._versions_oid(bucket),
                                      [vkey])
        # promote the next-newest remaining version when the deleted
        # one was current
        current = await self._index_get(bucket, key, meta)
        if key in current and json.loads(current[key]).get(
                "version_id") == version_id:
            remaining = [
                v for v in await self.list_object_versions(
                    bucket, prefix=key)
                if v["key"] == key
            ]
            if remaining:
                vk = self._vkey(key, remaining[0]["version_id"])
                raw = (await self.ioctx.get_omap(
                    self._versions_oid(bucket), [vk]))[vk]
                await self._index_set(bucket, meta, key, raw)
            else:
                await self._index_rm(bucket, meta, key)
        await self._log(bucket, "del-version", key)

    # -- multipart upload (rgw_multi.cc: initiate/part/complete/abort) ----
    @staticmethod
    def _mp_meta_oid(bucket: str, key: str, upload_id: str) -> str:
        return f"rgw.multipart.{bucket}/{key}.{upload_id}"

    @staticmethod
    def _mp_part_oid(bucket: str, key: str, upload_id: str,
                     part: int) -> str:
        return f"rgw.part.{bucket}/{key}.{upload_id}.{part:05d}"

    async def initiate_multipart(self, bucket: str, key: str,
                                 content_type: str =
                                 "binary/octet-stream",
                                 metadata: dict | None = None,
                                 lock: dict | None = None,
                                 sse: str | None = None,
                                 kms_key_id: str | None = None,
                                 storage_class: str | None = None
                                 ) -> str:
        """S3 CreateMultipartUpload -> upload id.  ``lock``: object
        -lock headers ride the INITIATE (S3 applies them to the
        assembled object at complete).  ``sse``/``kms_key_id``:
        SSE-KMS / SSE-S3 — one data key is wrapped at initiate and
        every part encrypts under it (its own nonce per part).
        ``storage_class``: x-amz-storage-class from the initiate —
        every part inherits it, so part bodies land directly in the
        class's placement pool."""
        meta = await self._check_bucket(bucket, "WRITE",
                                       action="s3:PutObject", key=key)
        if lock:
            # validate now: a bad mode must fail the initiate, not
            # the complete after every part is uploaded
            self._stage_lock({"meta": meta}, lock)
        sclass = (storage_class or "").strip() or None
        pool = None
        if sclass and sclass != "STANDARD":
            pool = (await self._class_placement(sclass)).get("pool") \
                or None
        else:
            sclass = None
        sse_kms = None
        if sse is not None:
            _, rec = await self._kms_begin(sse, kms_key_id)
            sse_kms = {"alg": rec["alg"], "key_id": rec["key_id"],
                       "wrapped": rec["wrapped"]}
        upload_id = secrets.token_hex(8)
        await self.ioctx.operate(
            self._mp_meta_oid(bucket, key, upload_id),
            ObjectOperation().create().omap_set({
                "_meta": json.dumps({
                    "key": key, "initiated": time.time(),
                    "content_type": content_type,
                    "meta": dict(metadata or {}),
                    "owner": self.user or "",
                    "lock": lock,
                    "sse_kms": sse_kms,
                    "storage_class": sclass,
                    "pool": pool,
                }).encode(),
            }),
        )
        return upload_id

    async def _mp_meta(self, bucket: str, key: str,
                       upload_id: str) -> dict:
        try:
            omap = await self.ioctx.get_omap(
                self._mp_meta_oid(bucket, key, upload_id)
            )
        except RadosError as e:
            if e.rc == -2:
                raise RGWError("NoSuchUpload", upload_id) from e
            raise
        return omap

    async def upload_part(self, bucket: str, key: str, upload_id: str,
                          part_number: int, data: bytes,
                          sse_key: bytes | None = None) -> dict:
        """S3 UploadPart; re-uploading a part number replaces it.
        ``sse_key``: SSE-C — each part encrypts under its own nonce at
        part-relative offsets (rgw_crypt.cc multipart rule: the part
        boundary resets the counter, so the assembled read can seek)."""
        if not 1 <= part_number <= 10000:
            raise RGWError("InvalidArgument", "part number 1..10000")
        meta = await self._check_bucket(
            bucket, "WRITE", action="s3:PutObject", key=key)
        info = json.loads(
            (await self._mp_meta(bucket, key, upload_id))["_meta"])
        await self._check_quota(bucket, meta, len(data),
                                replaced_size=0, is_replace=False)
        etag = hashlib.md5(data).hexdigest()
        rec = {"etag": etag, "size": len(data)}
        if info.get("sse_kms") is not None:
            if sse_key is not None:
                raise RGWError("InvalidRequest",
                               "upload uses KMS encryption, not SSE-C")
            from ceph_tpu.services.kms import KMSError

            sk = info["sse_kms"]
            try:
                dk = await self.kms.unwrap_data_key(sk["key_id"],
                                                    sk["wrapped"])
            except (AttributeError, KMSError) as e:
                raise RGWError("InvalidRequest",
                               f"KMS unwrap failed: {e}") from e
            nonce = secrets.token_bytes(16).hex()
            data = sse_crypt(dk, bytes.fromhex(nonce), 0, data)
            rec["sse"] = {"nonce": nonce, "kms": True}
        elif sse_key is not None:
            sse = sse_begin(sse_key)
            data = sse_crypt(sse_key, bytes.fromhex(sse["nonce"]),
                             0, data)
            rec["sse"] = sse
        # part bodies land in the upload's storage class pool (the
        # meta omap stays in the zone pool)
        data_ioctx, _ = await self._data_handles(info.get("pool"))
        await data_ioctx.operate(
            self._mp_part_oid(bucket, key, upload_id, part_number),
            ObjectOperation().write_full(data),
        )
        await self.ioctx.set_omap(
            self._mp_meta_oid(bucket, key, upload_id), {
                f"part.{part_number:05d}": json.dumps(rec).encode(),
            },
        )
        return {"etag": etag, "part_number": part_number}

    async def upload_part_copy(self, bucket: str, key: str,
                               upload_id: str, part_number: int,
                               src_bucket: str, src_key: str,
                               src_range: tuple[int, int]
                               | None = None,
                               sse_key: bytes | None = None,
                               src_sse_key: bytes
                               | None = None) -> dict:
        """S3 UploadPartCopy: a part sourced from an existing object
        (optionally a byte range) — reads ride the normal authorized
        GET path, the part lands like any uploaded part.
        ``sse_key``/``src_sse_key``: destination-part / copy-source
        SSE-C customer keys."""
        if src_range is not None:
            a, b = src_range
            if a < 0 or b < a:
                raise RGWError("InvalidArgument",
                               f"bad copy range {src_range}")
        got = await self.get_object(src_bucket, src_key,
                                    range_=src_range,
                                    sse_key=src_sse_key)
        if src_range is not None and \
                len(got["data"]) != src_range[1] - src_range[0] + 1:
            # S3 rejects ranges past the source's end instead of
            # clamping: silent truncation would corrupt the assembly
            raise RGWError("InvalidArgument",
                           "copy range exceeds the source size")
        if not got["data"]:
            raise RGWError("InvalidRequest", "copy source is empty")
        return await self.upload_part(bucket, key, upload_id,
                                      part_number, got["data"],
                                      sse_key=sse_key)

    async def list_parts(self, bucket: str, key: str,
                         upload_id: str) -> list[dict]:
        omap = await self._mp_meta(bucket, key, upload_id)
        return [
            {"part_number": int(k.split(".", 1)[1]),
             **json.loads(v)}
            for k, v in sorted(omap.items())
            if k.startswith("part.")
        ]

    async def complete_multipart(self, bucket: str, key: str,
                                 upload_id: str,
                                 parts: list[tuple[int, str]]) -> dict:
        """S3 CompleteMultipartUpload: validates the client's part list
        (numbers ascending, etags matching), records a MANIFEST entry —
        the object body stays in the part objects, read through the
        manifest like the reference's RGWObjManifest."""
        await self._check_bucket(bucket, "WRITE",
                                 action="s3:PutObject", key=key)
        uploaded = {p["part_number"]: p
                    for p in await self.list_parts(bucket, key,
                                                   upload_id)}
        if not parts:
            raise RGWError("InvalidArgument", "empty part list")
        meta_omap = await self._mp_meta(bucket, key, upload_id)
        info = json.loads(meta_omap["_meta"])
        kms_mode = info.get("sse_kms") is not None
        manifest = []
        total = 0
        digest_md5 = hashlib.md5()
        sse_md5s: set = set()
        last = 0
        for num, etag in parts:
            if num <= last:
                raise RGWError("InvalidPartOrder", str(num))
            last = num
            have = uploaded.get(num)
            if have is None or have["etag"] != etag:
                raise RGWError("InvalidPart", str(num))
            item = {
                "oid": self._mp_part_oid(bucket, key, upload_id, num),
                "size": have["size"], "etag": etag,
            }
            psse = have.get("sse")
            if psse is not None:
                item["nonce"] = psse["nonce"]
            if kms_mode:
                if psse is None or not psse.get("kms"):
                    raise RGWError(
                        "InvalidRequest",
                        "plaintext part inside a KMS-encrypted upload")
            else:
                sse_md5s.add(psse.get("key_md5") if psse else None)
            manifest.append(item)
            total += have["size"]
            digest_md5.update(bytes.fromhex(etag))
        entry_sse = None
        if kms_mode:
            entry_sse = {**info["sse_kms"], "multipart": True}
        elif sse_md5s != {None}:
            # encrypted parts: every part must be under the SAME key,
            # and a plaintext part cannot hide inside an SSE-C object
            if None in sse_md5s or len(sse_md5s) != 1:
                raise RGWError(
                    "InvalidRequest",
                    "all parts must use the same SSE-C key")
            entry_sse = {"alg": "AES256", "key_md5": sse_md5s.pop(),
                         "multipart": True}
        # the assembled size is the real quota event (parts are not in
        # the bucket index, so per-part checks cannot see each other)
        bucket_meta = await self._bucket_meta(bucket)
        self._index_writable(bucket_meta)
        existing0 = await self._index_get(bucket, key, bucket_meta)
        versioned = bucket_meta.get("versioning") == "enabled"
        suspended = bucket_meta.get("versioning") == "suspended"
        if versioned:
            replaced, is_replace = 0, False
        elif suspended:
            replaced, is_replace = await self._suspended_replaced(
                bucket, key, existing0.get(key))
        else:
            replaced = (json.loads(existing0[key])["size"]
                        if key in existing0 else 0)
            is_replace = key in existing0
        await self._check_quota(bucket, bucket_meta, total,
                                replaced_size=replaced,
                                is_replace=is_replace)
        # the S3 multipart etag form: md5-of-part-md5s + part count
        etag = f"{digest_md5.hexdigest()}-{len(manifest)}"
        # drop uploaded-but-unused parts
        data_ioctx, _ = await self._data_handles(info.get("pool"))
        used = {m["oid"] for m in manifest}
        for num in uploaded:
            oid = self._mp_part_oid(bucket, key, upload_id, num)
            if oid not in used:
                try:
                    await data_ioctx.remove(oid)
                except RadosError as e:
                    if e.rc != -2:
                        raise
        # replacing an existing plain/multipart object: clean old data.
        # Re-read the index HERE: awaits since existing0 (quota check,
        # part cleanup) give concurrent PUT/DELETEs of the same key a
        # window — a stale snapshot would leak a racer's data objects
        existing = await self._index_get(bucket, key, bucket_meta)
        entry = {
            "size": total, "etag": etag, "mtime": time.time(),
            "content_type": info["content_type"], "striped": False,
            "meta": info["meta"], "multipart": manifest,
        }
        if info.get("storage_class"):
            entry["storage_class"] = info["storage_class"]
        if info.get("pool"):
            entry["pool"] = info["pool"]
        if entry_sse is not None:
            entry["sse"] = entry_sse
        # WORM state for the ASSEMBLED object: initiate-time headers
        # or the bucket default (the buffered/streaming paths stage
        # this in _prepare_put; multipart assembles its own entry)
        lock_ctx = {"meta": bucket_meta}
        self._stage_lock(lock_ctx, info.get("lock"),
                         validate=False)
        if lock_ctx.get("lock_retention"):
            entry["retention"] = lock_ctx["lock_retention"]
        if lock_ctx.get("lock_legal_hold"):
            entry["legal_hold"] = True
        if versioned:
            # the assembled object is a NEW version; prior current
            # (incl. pre-versioning 'null') survives as history
            if key in existing:
                await self._adopt_null_version(
                    bucket, key, json.loads(existing[key])
                )
            entry["version_id"] = self._new_version_id()
            await self._record_version(bucket, key, entry)
        elif suspended:
            # the assembled object REPLACES the 'null' version (same
            # rule as a suspended PUT); other versions survive
            await self._remove_null_version(bucket, key)
            if key in existing:
                old = json.loads(existing[key])
                if not old.get("version_id"):
                    await self._remove_entry_data(bucket, key, old)
            entry["version_id"] = "null"
            await self._record_version(bucket, key, entry)
        elif key in existing:
            await self.delete_object(bucket, key)
        await self._index_set(bucket, bucket_meta, key,
                              json.dumps(entry).encode())
        await self.ioctx.remove(
            self._mp_meta_oid(bucket, key, upload_id)
        )
        await self._log(bucket, "put", key, etag, size=total)
        await self._maybe_auto_reshard(bucket, bucket_meta, key)
        out = {"etag": etag, "size": total}
        if entry.get("version_id") and not suspended:
            out["version_id"] = entry["version_id"]
        return out

    @_reclaims_space
    async def abort_multipart(self, bucket: str, key: str,
                              upload_id: str) -> None:
        await self._check_bucket(
            bucket, "WRITE", action="s3:AbortMultipartUpload", key=key)
        omap = await self._mp_meta(bucket, key, upload_id)
        info = json.loads(omap["_meta"])
        data_ioctx, _ = await self._data_handles(info.get("pool"))
        for k in omap:
            if not k.startswith("part."):
                continue
            try:
                await data_ioctx.remove(self._mp_part_oid(
                    bucket, key, upload_id, int(k.split(".", 1)[1])
                ))
            except RadosError as e:
                if e.rc != -2:
                    raise
        await self.ioctx.remove(
            self._mp_meta_oid(bucket, key, upload_id)
        )

    async def list_multipart_uploads(self, bucket: str) -> list[dict]:
        await self._check_bucket(
            bucket, "READ", action="s3:ListBucketMultipartUploads")
        prefix = f"rgw.multipart.{bucket}/"
        out = []
        for oid in await self.ioctx.list_objects():
            if not oid.startswith(prefix):
                continue
            rest = oid[len(prefix):]
            key, _, upload_id = rest.rpartition(".")
            out.append({"key": key, "upload_id": upload_id})
        return sorted(out, key=lambda u: (u["key"], u["upload_id"]))

    # -- static website hosting (rgw_website.cc role) ---------------------
    async def put_bucket_website(self, bucket: str, index_doc: str,
                                 error_doc: str = "") -> None:
        """PutBucketWebsite: serve the bucket as a website for
        ANONYMOUS browsers — directory paths resolve to the index
        document, missing keys to the error document."""
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        if not index_doc or "/" in index_doc:
            raise RGWError("InvalidArgument",
                           f"bad index document {index_doc!r}")
        meta["website"] = {"index": index_doc, "error": error_doc}
        await self._put_bucket_meta(bucket, meta)

    async def get_bucket_website(self, bucket: str) -> dict:
        meta = await self._check_bucket(bucket, "READ")
        cfg = meta.get("website")
        if not cfg:
            raise RGWError("NoSuchWebsiteConfiguration", bucket)
        return dict(cfg)

    async def delete_bucket_website(self, bucket: str) -> None:
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        meta.pop("website", None)
        await self._put_bucket_meta(bucket, meta)

    # -- S3 Object Lock (rgw_object_lock.cc: WORM retention) --------------
    _LOCK_MODES = ("GOVERNANCE", "COMPLIANCE")

    async def put_object_lock_config(self, bucket: str,
                                     mode: str | None = None,
                                     days: int = 0,
                                     years: int = 0) -> None:
        """PutObjectLockConfiguration: the bucket DEFAULT retention
        new versions inherit.  Only valid on buckets created with
        object lock (S3's InvalidBucketState rule)."""
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        if not (meta.get("object_lock") or {}).get("enabled"):
            raise RGWError("InvalidBucketState",
                           "bucket was not created with object lock")
        cfg: dict = {"enabled": True}
        if mode is not None:
            if mode not in self._LOCK_MODES:
                raise RGWError("MalformedXML", f"bad mode {mode!r}")
            if bool(days) == bool(years):
                raise RGWError("MalformedXML",
                               "exactly one of days/years")
            if (days or years) <= 0:
                raise RGWError("InvalidArgument",
                               "retention period must be positive")
            cfg["mode"] = mode
            cfg["days"] = int(days)
            cfg["years"] = int(years)
        meta["object_lock"] = cfg
        await self._put_bucket_meta(bucket, meta)

    async def get_object_lock_config(self, bucket: str) -> dict:
        meta = await self._check_bucket(bucket, "READ")
        cfg = meta.get("object_lock")
        if not cfg:
            raise RGWError("ObjectLockConfigurationNotFoundError",
                           bucket)
        return dict(cfg)

    def _default_retention_until(self, meta: dict) -> dict | None:
        cfg = meta.get("object_lock") or {}
        if not cfg.get("mode"):
            return None
        period = (cfg.get("days", 0) * 86400
                  + cfg.get("years", 0) * 365 * 86400)
        return {"mode": cfg["mode"], "until": time.time() + period}

    async def _lock_entry(self, bucket: str, key: str,
                          version_id: str | None,
                          need: str = "WRITE",
                          action: str = "s3:PutObjectRetention"):
        """(entry, write-back) for the version a lock op targets:
        current index entry when no version_id, else the version
        record.  write-back persists a mutated entry to BOTH the
        version table and (when current) the index."""
        meta = await self._check_bucket(bucket, need, action=action,
                                       key=key)
        if not (meta.get("object_lock") or {}).get("enabled"):
            raise RGWError("InvalidRequest",
                           "bucket has no object lock")
        kv = await self._index_get(bucket, key, meta)
        cur = json.loads(kv[key]) if key in kv else None
        if version_id is None:
            if cur is None or cur.get("delete_marker"):
                raise RGWError("NoSuchKey", f"{bucket}/{key}")
            entry = cur
        else:
            try:
                recs = await self.ioctx.get_omap(
                    self._versions_oid(bucket),
                    [self._vkey(key, version_id)])
            except RadosError as e:
                if e.rc != -2:
                    raise
                recs = {}
            if not recs:
                raise RGWError("NoSuchVersion",
                               f"{key}@{version_id}")
            entry = json.loads(next(iter(recs.values())))
            if entry.get("delete_marker"):
                # S3 answers 405: a marker destroys no data, so
                # "protection" on one would be a lie nothing enforces
                raise RGWError("MethodNotAllowed",
                               "object lock on a delete marker")

        async def write_back(e: dict) -> None:
            vid = e.get("version_id")
            if vid:
                await self.ioctx.set_omap(
                    self._versions_oid(bucket),
                    {self._vkey(key, vid): json.dumps(e).encode()})
            if cur is not None and cur.get("version_id") \
                    == e.get("version_id"):
                await self._index_set(bucket, meta, key,
                                      json.dumps(e).encode())
        return entry, write_back

    async def put_object_retention(self, bucket: str, key: str,
                                   mode: str, until: float,
                                   version_id: str | None = None,
                                   bypass_governance: bool = False
                                   ) -> None:
        """PutObjectRetention.  COMPLIANCE can never be shortened or
        downgraded; GOVERNANCE changes that loosen protection need
        the bypass flag (s3:BypassGovernanceRetention role)."""
        if mode not in self._LOCK_MODES:
            raise RGWError("MalformedXML", f"bad mode {mode!r}")
        if until <= time.time():
            raise RGWError("InvalidArgument",
                           "retain-until must be in the future")
        entry, write_back = await self._lock_entry(bucket, key,
                                                   version_id)
        old = entry.get("retention")
        if old:
            loosens = (until < float(old["until"])
                       or (old["mode"] == "COMPLIANCE"
                           and mode != "COMPLIANCE"))
            if loosens and old["mode"] == "COMPLIANCE":
                raise RGWError("AccessDenied",
                               "COMPLIANCE retention cannot be "
                               "loosened")
            if loosens and not (
                    bypass_governance
                    and await self._bypass_allowed(bucket, key)):
                raise RGWError("AccessDenied",
                               "governance bypass required")
        entry["retention"] = {"mode": mode, "until": float(until)}
        await write_back(entry)

    async def get_object_retention(self, bucket: str, key: str,
                                   version_id: str | None = None
                                   ) -> dict:
        entry, _ = await self._lock_entry(
            bucket, key, version_id, need="READ",
            action="s3:GetObjectRetention")
        ret = entry.get("retention")
        if not ret:
            raise RGWError("NoSuchObjectLockConfiguration", key)
        return dict(ret)

    async def put_object_legal_hold(self, bucket: str, key: str,
                                    status: bool,
                                    version_id: str | None = None
                                    ) -> None:
        entry, write_back = await self._lock_entry(
            bucket, key, version_id,
            action="s3:PutObjectLegalHold")
        entry["legal_hold"] = bool(status)
        await write_back(entry)

    async def get_object_legal_hold(self, bucket: str, key: str,
                                    version_id: str | None = None
                                    ) -> str:
        entry, _ = await self._lock_entry(
            bucket, key, version_id, need="READ",
            action="s3:GetObjectLegalHold")
        return "ON" if entry.get("legal_hold") else "OFF"

    def _stage_lock(self, ctx: dict, lock: dict | None,
                    validate: bool = True) -> None:
        """Resolve the new version's lock state into the put ctx:
        explicit headers win, else the bucket default retention.
        Explicit lock state on a bucket without object lock is an
        InvalidRequest, as S3 refuses it.  ``validate=False`` replays
        values validated at an earlier request (multipart complete
        re-staging initiate-time headers: a retain-until date that
        lapsed DURING the upload must not strand the parts)."""
        meta = ctx.get("meta") or {}
        enabled = (meta.get("object_lock") or {}).get("enabled")
        if lock:
            if not enabled:
                raise RGWError("InvalidRequest",
                               "bucket has no object lock")
            if lock.get("mode"):
                until = float(lock.get("until", 0))
                if validate:
                    if lock["mode"] not in self._LOCK_MODES:
                        raise RGWError("InvalidArgument",
                                       f"bad mode {lock['mode']!r}")
                    if until <= time.time():
                        raise RGWError("InvalidArgument",
                                       "retain-until must be in the "
                                       "future")
                ctx["lock_retention"] = {"mode": lock["mode"],
                                         "until": until}
            if lock.get("legal_hold"):
                ctx["lock_legal_hold"] = True
        if enabled and "lock_retention" not in ctx:
            # the bucket default applies whenever no EXPLICIT
            # retention came with the put — a legal-hold header must
            # not suppress a COMPLIANCE default
            default = self._default_retention_until(meta)
            if default:
                ctx["lock_retention"] = default

    async def _bypass_allowed(self, bucket: str, key: str) -> bool:
        """A requested governance bypass only counts when the caller
        holds s3:BypassGovernanceRetention (owner ACL or policy) —
        otherwise the header is a no-op, as S3 treats it."""
        try:
            await self._check_bucket(
                bucket, "WRITE",
                action="s3:BypassGovernanceRetention", key=key)
            return True
        except RGWError:
            return False

    @staticmethod
    def _lock_blocks_delete(entry: dict,
                            bypass_governance: bool) -> str | None:
        """Why a permanent delete of this version is forbidden, or
        None.  Delete MARKERS are never blocked — they destroy no
        data (S3 semantics)."""
        if entry.get("legal_hold"):
            return "version is under legal hold"
        ret = entry.get("retention")
        if ret and float(ret["until"]) > time.time():
            if ret["mode"] == "COMPLIANCE":
                return "COMPLIANCE retention until " \
                    f"{ret['until']:.0f}"
            if not bypass_governance:
                return "GOVERNANCE retention until " \
                    f"{ret['until']:.0f} (bypass required)"
        return None

    # -- lifecycle (rgw_lc.cc: expiration rules + the LC worker) ----------
    _LC_ACTIONS = ("expiration_days", "expiration_seconds",
                   "noncurrent_days", "noncurrent_seconds",
                   "abort_mpu_days", "abort_mpu_seconds",
                   "transition_days", "transition_seconds",
                   "noncurrent_transition_days",
                   "noncurrent_transition_seconds")

    async def put_lifecycle(self, bucket: str,
                            rules: list[dict]) -> None:
        """rules: [{id, prefix, status} + at least one action:
        expiration_days/_seconds (current versions),
        noncurrent_days/_seconds (NoncurrentVersionExpiration),
        abort_mpu_days/_seconds (AbortIncompleteMultipartUpload
        DaysAfterInitiation), transition_days/_seconds +
        transition_class (S3 Transition: move current versions into a
        storage class), noncurrent_transition_days/_seconds +
        noncurrent_transition_class (NoncurrentVersionTransition)]."""
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        for r in rules:
            # a lone StorageClass counts as an (incomplete) action so
            # it reaches the both-or-neither check below instead of
            # reading as "no action at all"
            if not any(k in r for k in self._LC_ACTIONS
                       + ("transition_class",
                          "noncurrent_transition_class")):
                raise RGWError("InvalidArgument",
                               f"rule {r.get('id')}: no action")
            for k in self._LC_ACTIONS:
                if k not in r:
                    continue
                try:
                    val = float(r[k])
                except (TypeError, ValueError):
                    raise RGWError("InvalidArgument",
                                   f"rule {r.get('id')}: {k}="
                                   f"{r[k]!r} is not a number") \
                        from None
                if not math.isfinite(val) or val <= 0:
                    # an explicit 0 would expire the whole prefix on
                    # the next pass; S3 rejects non-positive Days
                    raise RGWError("InvalidArgument",
                                   f"rule {r.get('id')}: {k} must "
                                   f"be positive")
            if r.get("status", "Enabled") not in ("Enabled",
                                                 "Disabled"):
                raise RGWError("MalformedXML",
                               f"rule {r.get('id')}: bad status "
                               f"{r.get('status')!r}")
            if r.get("tags") and any(k.startswith("abort_mpu")
                                     for k in r):
                # S3 refuses Filter/Tag on AbortIncompleteMultipart-
                # Upload: uploads have no tags to match, so the rule
                # would abort everything the filter meant to protect
                raise RGWError("InvalidArgument",
                               f"rule {r.get('id')}: tag filters "
                               f"cannot scope multipart aborts")
            for kind in ("transition", "noncurrent_transition"):
                limit = self._lc_limit(r, kind)
                cls = r.get(f"{kind}_class")
                if limit is None and cls is None:
                    continue
                if limit is None or not cls:
                    raise RGWError(
                        "MalformedXML",
                        f"rule {r.get('id')}: {kind} needs both a "
                        f"time and a StorageClass")
                if cls == "STANDARD":
                    # objects start in STANDARD: a transition into it
                    # is a transition to the same class
                    raise RGWError(
                        "InvalidArgument",
                        f"rule {r.get('id')}: cannot transition to "
                        f"STANDARD")
                # the class must resolve NOW: a rule naming a class no
                # placement defines would stall the LC worker later
                await self._class_placement(cls)
                # expiration-vs-transition precedence: within a rule
                # the expiration must outlive the transition or the
                # move is a wasted write on a doomed object (S3
                # rejects this combination outright)
                exp_kind = ("expiration" if kind == "transition"
                            else "noncurrent")
                exp = self._lc_limit(r, exp_kind)
                if exp is not None and exp <= limit:
                    raise RGWError(
                        "InvalidArgument",
                        f"rule {r.get('id')}: {exp_kind} expiration "
                        f"must be later than the {kind}")
        meta["lifecycle"] = [dict(r) for r in rules]
        await self._put_bucket_meta(bucket, meta)

    async def get_lifecycle(self, bucket: str) -> list[dict]:
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        return meta.get("lifecycle", [])

    async def delete_lifecycle(self, bucket: str) -> None:
        meta = await self._check_bucket(bucket, "FULL_CONTROL")
        meta.pop("lifecycle", None)
        await self._put_bucket_meta(bucket, meta)

    @staticmethod
    def _lc_limit(r: dict, kind: str) -> float | None:
        """The rule's threshold in seconds for one action kind
        ("expiration"/"noncurrent"/"abort_mpu"/"transition"/
        "noncurrent_transition"), or None."""
        if f"{kind}_seconds" in r:
            return float(r[f"{kind}_seconds"])
        if f"{kind}_days" in r:
            return float(r[f"{kind}_days"]) * 86400
        return None

    @_reclaims_space
    async def lc_process(self, now: float | None = None) -> dict:
        """One LC worker pass over every bucket (RGWLC::process):
        delete current versions whose age exceeds an Enabled rule's
        expiration, permanently delete NONCURRENT versions whose
        time-since-superseded exceeds a noncurrent rule (S3 measures
        from when the version became noncurrent — the successor's
        write time — not from its own), abort incomplete multipart
        uploads past DaysAfterInitiation, then TRANSITION current and
        noncurrent versions into their rules' target storage classes
        (the data-mover phase: bodies are re-written bit-identical
        into the class's placement pool — the EC cold pool for
        COLD-style classes — and the head repoints atomically).
        Expirations run first so a doomed object is never moved.
        Returns bucket -> [keys removed or transitioned ("k->CLASS",
        "k@vid->CLASS")]."""
        now = time.time() if now is None else now
        removed: dict[str, list[str]] = {}
        sys_self = self if self.user is None else self.as_user(None)
        for bucket in await self.list_buckets():
            try:
                rules = (await self._bucket_meta(bucket)) \
                    .get("lifecycle", [])
            except RGWError:
                continue
            active = [r for r in rules
                      if r.get("status", "Enabled") == "Enabled"]
            if not active:
                continue
            got = removed.setdefault(bucket, [])
            if any(self._lc_limit(r, "expiration") is not None
                   for r in active):
                await self._lc_expire_current(sys_self, bucket,
                                              active, now, got)
            if any(self._lc_limit(r, "noncurrent") is not None
                   for r in active):
                await self._lc_expire_noncurrent(sys_self, bucket,
                                                 active, now, got)
            if any(self._lc_limit(r, "abort_mpu") is not None
                   for r in active):
                await self._lc_abort_mpus(sys_self, bucket, active,
                                          now, got)
            if any(self._lc_limit(r, "transition") is not None
                   for r in active):
                await self._lc_transition_current(sys_self, bucket,
                                                  active, now, got)
            if any(self._lc_limit(r, "noncurrent_transition")
                   is not None for r in active):
                await self._lc_transition_noncurrent(
                    sys_self, bucket, active, now, got)
            if not got:
                del removed[bucket]
        return removed

    @staticmethod
    async def _lc_walk(sys_self, bucket: str, page: int = 1000):
        """Marker-paginated LC bucket walk: the worker sees every
        current object while holding at most one page in memory — a
        million-object bucket no longer materializes a single giant
        listing per pass."""
        marker = ""
        while True:
            listing = await sys_self.list_objects(bucket,
                                                  marker=marker,
                                                  max_keys=page)
            for obj in listing["contents"]:
                yield obj
            if not listing["is_truncated"] \
                    or not listing["next_marker"]:
                return
            marker = listing["next_marker"]

    async def _lc_expire_current(self, sys_self, bucket: str,
                                 active: list[dict], now: float,
                                 got: list[str]) -> None:
        async for obj in self._lc_walk(sys_self, bucket):
            age = now - float(obj["mtime"])
            for r in active:
                limit = self._lc_limit(r, "expiration")
                if limit is None:
                    continue
                if not obj["key"].startswith(r.get("prefix", "")):
                    continue
                want = r.get("tags") or {}
                if want:
                    # tag-filtered rule (S3 lifecycle Filter/Tag):
                    # tags ride the listing, so no per-object
                    # refetch and no race against deletions
                    have = obj.get("tags") or {}
                    if any(have.get(k) != v
                           for k, v in want.items()):
                        continue
                if age > limit:
                    await sys_self.delete_object(bucket, obj["key"])
                    got.append(obj["key"])
                    break

    async def _lc_expire_noncurrent(self, sys_self, bucket: str,
                                    active: list[dict], now: float,
                                    got: list[str]) -> None:
        """NoncurrentVersionExpiration (rgw_lc.cc
        LCOpAction_NonCurrentExpiration role)."""
        versions = await sys_self.list_object_versions(bucket)
        by_key: dict[str, list[dict]] = {}
        for v in versions:
            by_key.setdefault(v["key"], []).append(v)
        for key, vs in by_key.items():
            # is_latest is the PRIMARY sort key: a current version
            # whose mtime ties (or trails — an adopted pre-versioning
            # 'null' that got re-promoted) an older version must still
            # sort first, or the pairing below would treat it as
            # noncurrent and expire the live object
            vs.sort(key=lambda v: (not v["is_latest"],
                                   -float(v["mtime"])))
            # vs[0] is current; each older version became noncurrent
            # when its SUCCESSOR was written
            for succ, v in zip(vs, vs[1:]):
                if v["is_latest"]:
                    continue
                since = now - float(succ["mtime"])
                for r in active:
                    limit = self._lc_limit(r, "noncurrent")
                    if limit is None or not key.startswith(
                            r.get("prefix", "")):
                        continue
                    want = r.get("tags") or {}
                    if want:
                        # the filter evaluates each VERSION's own tag
                        # set (a dev-tagged version must survive a
                        # prod-scoped rule)
                        have = v.get("tags") or {}
                        if any(have.get(k) != t
                               for k, t in want.items()):
                            continue
                    if since > limit:
                        try:
                            await sys_self.delete_object_version(
                                bucket, key, v["version_id"])
                        except RGWError as err:
                            if err.code != "AccessDenied":
                                raise
                            break   # object-lock protected: skip
                        got.append(f"{key}@{v['version_id']}")
                        break

    async def _lc_abort_mpus(self, sys_self, bucket: str,
                             active: list[dict], now: float,
                             got: list[str]) -> None:
        """AbortIncompleteMultipartUpload (DaysAfterInitiation)."""
        for up in await sys_self.list_multipart_uploads(bucket):
            try:
                m = await sys_self._mp_meta(bucket, up["key"],
                                            up["upload_id"])
            except RGWError:
                continue        # completed/aborted underneath us
            info = json.loads(m["_meta"])
            age = now - float(info.get("initiated", now))
            for r in active:
                limit = self._lc_limit(r, "abort_mpu")
                if limit is None or not up["key"].startswith(
                        r.get("prefix", "")):
                    continue
                if age > limit:
                    await sys_self.abort_multipart(
                        bucket, up["key"], up["upload_id"])
                    got.append(f"{up['key']}+{up['upload_id']}")
                    break

    async def _lc_transition_current(self, sys_self, bucket: str,
                                     active: list[dict], now: float,
                                     got: list[str]) -> None:
        """Current-version transitions (rgw_lc.cc
        LCOpAction_Transition role): the expiration phases already ran
        this pass, so anything still listed is not doomed — move its
        bytes and repoint the head."""
        async for obj in self._lc_walk(sys_self, bucket):
            age = now - float(obj["mtime"])
            for r in active:
                limit = self._lc_limit(r, "transition")
                if limit is None:
                    continue
                if not obj["key"].startswith(r.get("prefix", "")):
                    continue
                want = r.get("tags") or {}
                if want:
                    have = obj.get("tags") or {}
                    if any(have.get(k) != v
                           for k, v in want.items()):
                        continue
                if age <= limit:
                    continue
                target = r["transition_class"]
                if obj.get("storage_class",
                           "STANDARD") == target:
                    continue
                try:
                    moved = await sys_self._transition_object(
                        bucket, obj["key"], None, target)
                except RGWError as err:
                    # SSE-C (no key available) or placement trouble:
                    # skip this object, keep the pass going
                    rgw_log.dout(5, "lc: transition %s/%s "
                                 "refused: %s", bucket, obj["key"],
                                 err)
                    break
                if moved:
                    got.append(f"{obj['key']}->{target}")
                break

    async def _lc_transition_noncurrent(self, sys_self, bucket: str,
                                        active: list[dict],
                                        now: float,
                                        got: list[str]) -> None:
        """NoncurrentVersionTransition: same successor-write-time
        clock as noncurrent expiration — a version's transition age
        starts when it STOPPED being current."""
        versions = await sys_self.list_object_versions(bucket)
        by_key: dict[str, list[dict]] = {}
        for v in versions:
            by_key.setdefault(v["key"], []).append(v)
        for key, vs in by_key.items():
            vs.sort(key=lambda v: (not v["is_latest"],
                                   -float(v["mtime"])))
            for succ, v in zip(vs, vs[1:]):
                if v["is_latest"] or v["delete_marker"]:
                    continue
                since = now - float(succ["mtime"])
                for r in active:
                    limit = self._lc_limit(r,
                                           "noncurrent_transition")
                    if limit is None or not key.startswith(
                            r.get("prefix", "")):
                        continue
                    want = r.get("tags") or {}
                    if want:
                        have = v.get("tags") or {}
                        if any(have.get(k) != t
                               for k, t in want.items()):
                            continue
                    if since <= limit:
                        continue
                    target = r["noncurrent_transition_class"]
                    if v.get("storage_class",
                             "STANDARD") == target:
                        continue
                    try:
                        moved = await sys_self._transition_object(
                            bucket, key, v["version_id"], target)
                    except RGWError as err:
                        rgw_log.dout(5, "lc: transition %s/%s@%s "
                                     "refused: %s", bucket, key,
                                     v["version_id"], err)
                        break
                    if moved:
                        got.append(
                            f"{key}@{v['version_id']}->{target}")
                    break

    async def _transition_object(self, bucket: str, key: str,
                                 version_id: str | None,
                                 target_class: str) -> bool:
        """Move one object/version's stored bytes into
        ``target_class``'s placement pool and atomically repoint its
        head (RGWLC::transition): the S3-visible identity — body
        bytes, etag, version-id, mtime, tags, lock state, SSE and
        compression envelopes — is preserved bit-for-bit; only
        storage_class/pool/data oids change.  The stored (possibly
        deflated/encrypted) bytes are copied VERBATIM through the
        normal write path — into an EC pool that means batched
        stripes through the Objecter→ECBackend encode pipeline — then
        the old tail is reclaimed through the usual GC path.  Returns
        False when there is nothing to move (already in the class,
        delete marker, SLO manifest); raises InvalidRequest for SSE-C
        objects — the lifecycle worker holds no customer key, the
        same conflict a PUT refuses."""
        place = await self._class_placement(target_class)
        pool = place.get("pool") or None
        meta = await self._bucket_meta(bucket)
        if version_id is None:
            kv = await self._index_get(bucket, key, meta)
            if key not in kv:
                raise RGWError("NoSuchKey", f"{bucket}/{key}")
            entry = json.loads(kv[key])
        else:
            vkey = self._vkey(key, version_id)
            try:
                kv = await self.ioctx.get_omap(
                    self._versions_oid(bucket), [vkey])
            except RadosError as e:
                if e.rc != -2:
                    raise
                kv = {}
            if vkey not in kv:
                raise RGWError("NoSuchVersion",
                               f"{key}@{version_id}")
            entry = json.loads(kv[vkey])
        if entry.get("delete_marker") or entry.get("slo"):
            return False
        if entry.get("storage_class", "STANDARD") == target_class:
            return False
        sse = entry.get("sse")
        if sse is not None and "wrapped" not in sse:
            # SSE-C: only the customer holds the key.  Re-placing the
            # ciphertext would work mechanically, but S3 (and our PUT
            # path) treat server-initiated handling of SSE-C objects
            # without the key as a conflict — refuse identically.
            raise RGWError("InvalidRequest",
                           f"{key}: SSE-C objects cannot transition "
                           f"without the customer key")
        old = dict(entry)
        src_ioctx, src_striper = await self._data_handles(
            entry.get("pool"))
        dst_ioctx, dst_striper = await self._data_handles(pool)
        # NEW unique tail oids (\x00t\x00 tag): in-place moves would
        # collide when source and target share a pool, and the GC
        # liveness check compares oids — a reused name would make the
        # old tail look live forever
        tag = secrets.token_hex(8)
        if entry.get("multipart"):
            new_manifest = []
            for p in entry["multipart"]:
                raw = await src_ioctx.read(p["oid"])
                new_oid = f"{p['oid']}\x00t\x00{tag}"
                await dst_ioctx.operate(
                    new_oid, ObjectOperation().write_full(raw))
                new_manifest.append({**p, "oid": new_oid})
            entry["multipart"] = new_manifest
        else:
            old_oid = entry.get("data_oid",
                                self._data_oid(bucket, key))
            new_oid = f"{self._data_oid(bucket, key)}\x00t\x00{tag}"
            if entry.get("striped"):
                raw = await src_striper.read(old_oid)
                await dst_striper.write(new_oid, raw)
            else:
                raw = await src_ioctx.read(old_oid)
                if entry.get("comp") is None and sse is None \
                        and place.get("compression") \
                        in list_compressors():
                    # the class's inline compression composes with
                    # the move: an uncompressed, unencrypted body
                    # deflates exactly as a fresh PUT into the class
                    # would (S3-visible size/etag unchanged)
                    raw, comp = deflate_if_smaller(
                        raw, place["compression"])
                    if comp is not None:
                        entry["comp"] = comp
                await dst_ioctx.operate(
                    new_oid, ObjectOperation().write_full(raw))
            entry["data_oid"] = new_oid
        entry["storage_class"] = target_class
        if pool:
            entry["pool"] = pool
        else:
            entry.pop("pool", None)
        raw_entry = json.dumps(entry).encode()
        # atomic repoint: flip the version record first (history
        # readers), then the bucket index when this record is the
        # current one — each flip is a single omap set, so readers
        # see either the old head or the new, never a mix
        if version_id is not None:
            await self.ioctx.set_omap(
                self._versions_oid(bucket), {vkey: raw_entry})
            cur = await self._index_get(bucket, key, meta)
            if key in cur and json.loads(cur[key]) \
                    .get("version_id") == version_id:
                await self._index_set(bucket, meta, key, raw_entry)
        else:
            if entry.get("version_id"):
                await self.ioctx.set_omap(
                    self._versions_oid(bucket), {
                        self._vkey(key, entry["version_id"]):
                        raw_entry,
                    })
            await self._index_set(bucket, meta, key, raw_entry)
        # reclaim the old tail (deferred through GC when configured)
        await self._remove_entry_data(bucket, key, old)
        return True

    # -- bucket index shards (cls_rgw index + rgw_reshard.cc role) ---------
    @staticmethod
    def _index_shard_oids(bucket: str, meta: dict) -> list[str]:
        """The bucket's index shard objects.  An unsharded gen-0 bucket
        keeps the legacy single-object name; sharded (or resharded)
        buckets spread keys over ``.g<gen>.<shard>`` objects — the
        generation bumps on every reshard so the old and new shard sets
        never collide (reference RGWBucketReshard new-instance ids)."""
        shards = max(1, int(meta.get("index_shards", 1)))
        gen = int(meta.get("index_gen", 0))
        if shards == 1 and gen == 0:
            return [f"rgw.bucket.index.{bucket}"]
        # NUL separators: bucket names may legally contain dots and
        # digits, so a dotted suffix would collide with the legacy oid
        # of a bucket literally named "<bucket>.g<gen>.<n>"
        return [f"rgw.bucket.index\x00{bucket}\x00g{gen}.{s}"
                for s in range(shards)]

    @staticmethod
    def _index_oid_for(bucket: str, meta: dict, key: str) -> str:
        """The shard object holding ``key`` (ceph_str_hash role)."""
        oids = RGWLite._index_shard_oids(bucket, meta)
        if len(oids) == 1:
            return oids[0]
        return oids[zlib.crc32(key.encode()) % len(oids)]

    async def _index_all(self, bucket: str,
                         meta: dict | None = None) -> dict:
        """Merged key -> raw entry across every index shard."""
        if meta is None:
            meta = await self._bucket_meta(bucket)

        async def one(oid: str) -> dict:
            try:
                return await self.ioctx.get_omap(oid)
            except RadosError as e:
                if e.rc != -2:
                    raise
                return {}

        out: dict[str, bytes] = {}
        for kv in await asyncio.gather(
                *(one(o) for o in self._index_shard_oids(bucket,
                                                         meta))):
            out.update(kv)
        return out

    async def _index_get(self, bucket: str, key: str,
                         meta: dict | None = None) -> dict:
        if meta is None:
            meta = await self._bucket_meta(bucket)
        try:
            return await self.ioctx.get_omap(
                self._index_oid_for(bucket, meta, key), [key])
        except RadosError as e:
            if e.rc != -2:
                raise
            return {}

    @staticmethod
    def _index_writable(meta: dict) -> None:
        """Index writes are blocked while a reshard copies entries
        (the reference blocks with a cls guard + retry; clients see a
        retryable 503)."""
        if meta.get("resharding"):
            raise RGWError("ServiceUnavailable",
                           "bucket index is resharding; retry")

    async def _index_set(self, bucket: str, meta: dict, key: str,
                         raw: bytes) -> None:
        self._index_writable(meta)
        await self.ioctx.set_omap(
            self._index_oid_for(bucket, meta, key), {key: raw})

    async def _index_rm(self, bucket: str, meta: dict,
                        key: str) -> None:
        self._index_writable(meta)
        await self.ioctx.rm_omap_keys(
            self._index_oid_for(bucket, meta, key), [key])

    async def reshard_bucket(self, bucket: str,
                             num_shards: int) -> dict:
        """Reshard the bucket index to ``num_shards`` shard objects
        (rgw_reshard.cc RGWBucketReshard::execute): flag the bucket,
        copy entries into a new generation of shard objects, flip the
        meta, drop the old set.  A second copy sweep picks up writers
        that raced the flag; the one-await window left open is the
        -lite stand-in for the reference's cls-guard retry protocol."""
        if not 1 <= num_shards <= 1024:
            raise RGWError("InvalidArgument",
                           f"num_shards {num_shards} not in [1,1024]")
        meta = await self._bucket_meta(bucket)
        if self.user is not None and self.user != meta.get("owner"):
            raise RGWError("AccessDenied", bucket)
        if meta.get("resharding"):
            raise RGWError("OperationAborted",
                           f"reshard of {bucket} already in progress")
        old_oids = self._index_shard_oids(bucket, meta)
        new_meta = {**meta, "index_shards": num_shards,
                    "index_gen": int(meta.get("index_gen", 0)) + 1}
        meta["resharding"] = True
        meta["reshard_target"] = num_shards
        await self._put_bucket_meta(bucket, meta)
        for oid in self._index_shard_oids(bucket, new_meta):
            await self.ioctx.operate(oid, ObjectOperation().create())
        moved: set[str] = set()
        placed: dict[str, str] = {}     # key -> new shard oid
        for sweep in range(2):
            merged: dict[str, bytes] = {}
            for old in old_oids:
                try:
                    merged.update(await self.ioctx.get_omap(old))
                except RadosError as e:
                    if e.rc != -2:
                        raise
            batches: dict[str, dict] = {}
            for k, v in merged.items():
                oid = self._index_oid_for(bucket, new_meta, k)
                batches.setdefault(oid, {})[k] = v
                placed[k] = oid
                moved.add(k)
            for oid, kvs in batches.items():
                await self.ioctx.set_omap(oid, kvs)
            if sweep == 1:
                # a DELETE that raced the flag dropped its key from an
                # old shard after sweep 0 copied it: the copy must
                # propagate removals too, or the flip resurrects an
                # index entry whose data is gone
                for k in set(placed) - set(merged):
                    await self.ioctx.rm_omap_keys(placed[k], [k])
                    moved.discard(k)
        final = dict(new_meta)
        final.pop("resharding", None)
        final.pop("reshard_target", None)
        await self._put_bucket_meta(bucket, final)
        for old in old_oids:
            try:
                await self.ioctx.remove(old)
            except RadosError as e:
                if e.rc != -2:
                    raise
        return {"bucket": bucket, "num_shards": num_shards,
                "objects": len(moved)}

    async def reshard_abort(self, bucket: str) -> None:
        """Clear a reshard wedged by a crash mid-copy: drop the
        half-written next-generation shard objects and unblock
        writes (radosgw-admin reshard cancel)."""
        meta = await self._bucket_meta(bucket)
        if not meta.get("resharding"):
            return
        target = int(meta.get("reshard_target", 1))
        next_meta = {**meta, "index_shards": target,
                     "index_gen": int(meta.get("index_gen", 0)) + 1}
        for oid in self._index_shard_oids(bucket, next_meta):
            try:
                await self.ioctx.remove(oid)
            except RadosError as e:
                if e.rc != -2:
                    raise
        meta.pop("resharding", None)
        meta.pop("reshard_target", None)
        await self._put_bucket_meta(bucket, meta)

    async def _maybe_auto_reshard(self, bucket: str, meta: dict,
                                  key: str) -> None:
        """Dynamic resharding (rgw_reshard.cc RGWReshard daemon role):
        after a put, when the target shard outgrows the per-shard
        object cap, double the shard count.  Checks only the one shard
        the put touched, so the cost is one omap read per put."""
        if self.auto_reshard_objs <= 0:
            return
        try:
            n = len(await self.ioctx.get_omap(
                self._index_oid_for(bucket, meta, key)))
        except RadosError as e:
            if e.rc != -2:
                raise
            return
        if n <= self.auto_reshard_objs:
            return
        shards = max(1, int(meta.get("index_shards", 1)))
        if shards * 2 > 1024:
            return                # at the cap: the put already landed
        try:
            await self.as_user(None).reshard_bucket(bucket, shards * 2)
        except RGWError as e:
            if e.code not in ("OperationAborted",
                              "ServiceUnavailable"):
                raise             # concurrent reshard already running

    # -- garbage collection (rgw_gc.cc deferred tail deletion) -------------
    GC_OID = "rgw.gc"

    async def _gc_enqueue(self, items: list, bucket: str,
                          key: str) -> None:
        """Queue data objects for deferred deletion; keys sort by
        expiry so gc_process stops at the first unexpired entry.
        ``bucket``/``key`` ride along for the reap-time liveness
        check: plain puts reuse the deterministic per-key oid, so a
        key re-created inside the grace window holds LIVE data at an
        oid a stale GC entry names (the reference avoids this with
        per-write tail tags; -lite checks liveness when reaping)."""
        expire = time.time() + self.gc_min_wait
        await self.ioctx.operate(
            self.GC_OID, ObjectOperation().create().omap_set({
                f"{expire:020.6f}.{secrets.token_hex(6)}":
                    json.dumps({"bucket": bucket, "key": key,
                                "items": items}).encode(),
            }))

    async def _live_oids(self, bucket: str, key: str) -> set[str]:
        """Every data oid the bucket CURRENTLY references for ``key``
        (index entry + all version records): a GC entry must never
        delete these — they belong to a re-created or overwritten
        object, not the dead one that was enqueued."""
        def oids_of(rec: dict) -> list[str]:
            if rec.get("delete_marker"):
                return []
            if rec.get("multipart"):
                return [p["oid"] for p in rec["multipart"]]
            return [rec.get("data_oid", self._data_oid(bucket, key))]

        live: set[str] = set()
        try:
            kv = await self._index_get(bucket, key)
        except RGWError:
            return live                   # bucket itself is gone
        if key in kv:
            live.update(oids_of(json.loads(kv[key])))
        try:
            vomap = await self.ioctx.get_omap(
                self._versions_oid(bucket))
        except RadosError as e:
            if e.rc != -2:
                raise
            vomap = {}
        prefix = key + "\x00"
        for vk, raw in vomap.items():
            if vk.startswith(prefix):
                live.update(oids_of(json.loads(raw)))
        return live

    async def _gc_delete(self, items: list) -> None:
        for it in items:
            kind, oid = it[0], it[1]
            try:
                ioctx, striper = await self._data_handles(
                    it[2] if len(it) > 2 else None)
                if kind == "striped":
                    await striper.remove(oid)
                else:
                    await ioctx.remove(oid)
            except (RadosError, RGWError) as e:
                # -2 / a deleted placement pool: the tail is already
                # gone either way
                if isinstance(e, RadosError) and e.rc != -2:
                    raise

    async def gc_list(self) -> list[dict]:
        try:
            omap = await self.ioctx.get_omap(self.GC_OID)
        except RadosError as e:
            if e.rc != -2:
                raise
            return []
        out = []
        for k, v in sorted(omap.items()):
            parts = k.rsplit(".", 2)
            ent = json.loads(v)
            out.append({"tag": k,
                        "expire": float(parts[0] + "." + parts[1]),
                        **ent})
        return out

    @_reclaims_space
    async def gc_process(self, now: float | None = None) -> int:
        """Reap expired GC entries (RGWGC::process); returns the
        number of queue entries deleted."""
        now = time.time() if now is None else now
        reaped = 0
        for ent in await self.gc_list():
            if ent["expire"] > now:
                break                     # sorted by expiry
            live = await self._live_oids(ent["bucket"], ent["key"])
            await self._gc_delete([it for it in ent["items"]
                                   if it[1] not in live])
            await self.ioctx.rm_omap_keys(self.GC_OID, [ent["tag"]])
            reaped += 1
        return reaped

    # -- buckets -----------------------------------------------------------
    @staticmethod
    def _index_oid(bucket: str) -> str:
        """Legacy unsharded index oid (gen-0 single shard only)."""
        return f"rgw.bucket.index.{bucket}"

    @staticmethod
    def _log_oid(bucket: str, shard: int = 0) -> str:
        """Datalog shard object.  Shard 0 keeps the legacy unsuffixed
        name so single-shard deployments (and their persisted sync
        markers) survive the sharding change unmodified; higher shards
        use a NUL separator for the same dotted-bucket-name reason as
        the index shards."""
        if shard == 0:
            return f"rgw.bucket.log.{bucket}"
        return f"rgw.bucket.log\x00{bucket}\x00{shard}"

    def _log_shard_of(self, key: str) -> int:
        """The datalog shard holding ``key``'s mutations (same
        crc32 placement as the index shards, so the mapping is a pure
        function both zones compute identically)."""
        if self.datalog_shards <= 1:
            return 0
        return zlib.crc32(key.encode()) % self.datalog_shards

    async def _log(self, bucket: str, op: str, key: str,
                   etag: str = "", event: str | None = None,
                   size: int = 0) -> None:
        """``event``: explicit S3 event name when the op name alone is
        ambiguous (a versioned DELETE logs 'del' but the S3 event is
        DeleteMarkerCreated).  ``size``: payload bytes for puts, so the
        sync agent's lag ledger can price unreplicated entries in bytes
        as well as entries."""
        if self.datalog:
            await self.ioctx.exec(
                self._log_oid(bucket, self._log_shard_of(key)),
                "rgw", "log_add",
                json.dumps({"op": op, "key": key, "etag": etag,
                            "mtime": time.time(),
                            "size": int(size)}).encode(),
            )
        await self._notify(bucket, op, key, etag, event)

    # -- bucket notifications / pubsub (rgw_pubsub.cc role) ---------------
    # Notification configs live in the bucket meta; events land in
    # per-topic queue objects (same seq-allocating rgw cls as the
    # datalog) and are consumed PULL-style (topic_pull/topic_trim — the
    # reference pubsub sync module's pull mode).
    _EVENT_OF_OP = {
        "put": "s3:ObjectCreated:Put",
        "put-tagging": "s3:ObjectTagging:Put",
        "del": "s3:ObjectRemoved:Delete",
        # permanent removal of a specific version IS a Delete; marker
        # creation passes an explicit event at the call site
        "del-version": "s3:ObjectRemoved:Delete",
    }

    @staticmethod
    def _topic_oid(topic: str) -> str:
        return f"rgw.pubsub.topic.{topic}"

    async def put_bucket_notification(
            self, bucket: str, topic: str,
            events: list[str] | None = None) -> None:
        meta = await self._check_bucket(bucket, "WRITE")
        cfgs = [c for c in meta.get("notifications", ())
                if c["topic"] != topic]
        cfgs.append({"topic": str(topic),
                     "events": list(events or ["s3:ObjectCreated:*",
                                               "s3:ObjectRemoved:*"])})
        meta["notifications"] = cfgs
        await self._put_bucket_meta(bucket, meta)
        self._notif_cache.pop(bucket, None)

    async def set_bucket_notifications(self, bucket: str,
                                       configs: list[dict]) -> None:
        """REPLACE the whole notification document (S3
        PutBucketNotificationConfiguration semantics — an empty list is
        how clients disable notifications; there is no DELETE API)."""
        meta = await self._check_bucket(bucket, "WRITE")
        meta["notifications"] = [
            {"topic": str(c["topic"]),
             "events": list(c.get("events")
                            or ["s3:ObjectCreated:*",
                                "s3:ObjectRemoved:*"])}
            for c in configs
        ]
        await self._put_bucket_meta(bucket, meta)
        self._notif_cache.pop(bucket, None)

    async def get_bucket_notification(self, bucket: str) -> list[dict]:
        meta = await self._check_bucket(bucket, "READ")
        return list(meta.get("notifications", ()))

    async def delete_bucket_notification(
            self, bucket: str, topic: str | None = None) -> None:
        meta = await self._check_bucket(bucket, "WRITE")
        meta["notifications"] = [
            c for c in meta.get("notifications", ())
            if topic is not None and c["topic"] != topic
        ]
        await self._put_bucket_meta(bucket, meta)
        self._notif_cache.pop(bucket, None)

    @staticmethod
    def _event_match(pattern: str, event: str) -> bool:
        return (pattern == event
                or (pattern.endswith("*")
                    and event.startswith(pattern[:-1])))

    async def _notify(self, bucket: str, op: str, key: str,
                      etag: str, event: str | None = None) -> None:
        event = event or self._EVENT_OF_OP.get(op)
        if event is None:
            return
        now = time.time()
        cached = self._notif_cache.get(bucket)
        if cached is None or now - cached[0] > 5.0:
            try:
                meta = await self._bucket_meta(bucket)
            except RGWError:
                return
            if len(self._notif_cache) > 4096:
                self._notif_cache.clear()
            cached = (now, list(meta.get("notifications", ())))
            self._notif_cache[bucket] = cached
        for cfg in cached[1]:
            if any(self._event_match(p, event)
                   for p in cfg.get("events", ())):
                await self.ioctx.exec(
                    self._topic_oid(cfg["topic"]), "rgw", "log_add",
                    json.dumps({
                        "op": "notify", "key": key, "etag": etag,
                        "mtime": now, "eventName": event,
                        "bucket": bucket, "eventTime": now,
                    }).encode(),
                )
                # push mode: wake (or revive after a restart) the
                # topic's delivery worker
                tmeta = await self._topic_meta(cfg["topic"])
                if tmeta is not None and tmeta.get("push_endpoint"):
                    self._ensure_pusher(cfg["topic"], tmeta)

    # -- persistent topics + push-mode delivery ---------------------------
    # rgw_pubsub_push.h:20 (RGWPubSubEndpoint) + rgw_notify.cc
    # persistent-topic semantics: events land in the per-topic queue
    # (the at-least-once source of truth) regardless of mode; a topic
    # with a push_endpoint gets a worker that delivers in order,
    # advances a DURABLE cursor xattr only after an ack (or after
    # parking an exhausted event in <topic>.deadletter), and backs off
    # exponentially between attempts.  A restart resumes from the
    # cursor: delivery is at-least-once, never lossy.
    TOPICS_OID = "rgw.pubsub.topics"

    async def create_topic(self, name: str,
                           push_endpoint: str | None = None,
                           ack_level: str = "broker",
                           max_retries: int = 5,
                           retry_sleep: float = 0.05,
                           opaque: str = "") -> dict:
        """Create/replace a topic (radosgw-admin topic create +
        attributes: push-endpoint URL, ack level, OpaqueData)."""
        if push_endpoint:
            from ceph_tpu.services.rgw_push import PushEndpoint

            PushEndpoint.make(push_endpoint, ack_level)  # validate now
        meta = {"name": str(name), "push_endpoint": push_endpoint,
                "ack_level": ack_level,
                "max_retries": int(max_retries),
                "retry_sleep": float(retry_sleep),
                "opaque": str(opaque), "created": time.time()}
        await self.ioctx.operate(
            self.TOPICS_OID, ObjectOperation().create()
            .omap_set({str(name): json.dumps(meta).encode()}))
        self._topics_cache.pop(str(name), None)
        # replace semantics: a live worker was built from the OLD meta
        # (endpoint/ack/retries) — stop it; the new one starts now or,
        # for a pull-only topic, never
        self._stop_pusher(str(name))
        if push_endpoint:
            self._ensure_pusher(str(name), meta)
        return meta

    async def get_topic(self, name: str) -> dict:
        t = await self._topic_meta(name)
        if t is None:
            raise RGWError("NoSuchTopic", name)
        return t

    async def list_topics(self) -> list[str]:
        try:
            return sorted(await self.ioctx.get_omap(self.TOPICS_OID))
        except RadosError as e:
            if e.rc != -2:
                raise
            return []

    async def delete_topic(self, name: str) -> None:
        self._stop_pusher(name)
        try:
            await self.ioctx.operate(
                self.TOPICS_OID, ObjectOperation().omap_rm([str(name)]))
        except RadosError as e:
            if e.rc != -2:
                raise
        self._topics_cache.pop(str(name), None)
        for oid in (self._topic_oid(name),
                    self._topic_oid(name) + ".deadletter"):
            try:
                await self.ioctx.remove(oid)
            except RadosError as e:
                if e.rc != -2:
                    raise

    async def _topic_meta(self, name: str) -> dict | None:
        now = time.time()
        cached = self._topics_cache.get(name)
        if cached is not None and now - cached[0] <= 5.0:
            return cached[1]
        try:
            kv = await self.ioctx.get_omap(self.TOPICS_OID, [str(name)])
            meta = json.loads(kv[str(name)]) if str(name) in kv else None
        except RadosError as e:
            if e.rc != -2:
                raise
            meta = None
        if len(self._topics_cache) > 4096:
            self._topics_cache.clear()
        self._topics_cache[name] = (now, meta)
        return meta

    def _ensure_pusher(self, topic: str, meta: dict) -> None:
        cur = self._pushers.get(topic)
        if cur is not None and not cur[0].done():
            cur[1].set()
            return
        ev = asyncio.Event()
        ev.set()
        task = asyncio.get_running_loop().create_task(
            self._push_loop(topic, meta, ev))
        self._pushers[topic] = (task, ev)

    def _stop_pusher(self, topic: str) -> None:
        cur = self._pushers.pop(topic, None)
        if cur is not None:
            cur[0].cancel()

    async def start_push(self) -> None:
        """Spawn delivery workers for every push topic (the restart
        hook: events queued before a process restart must not wait for
        new traffic on their topic — rgw_notify.cc starts its
        persistent-queue workers at init the same way)."""
        try:
            kv = await self.ioctx.get_omap(self.TOPICS_OID)
        except RadosError as e:
            if e.rc != -2:
                raise
            return
        for name, raw in kv.items():
            try:
                meta = json.loads(raw)
            except ValueError:
                continue
            if meta.get("push_endpoint"):
                self._ensure_pusher(name, meta)

    async def stop_push(self) -> None:
        """Cancel + drain every push worker (test/shutdown hook)."""
        tasks = [t for t, _ in self._pushers.values()]
        self._pushers.clear()
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    @staticmethod
    def _event_payload(topic: str, opaque: str, e: dict) -> bytes:
        """S3 notification record shape (what the reference's HTTP
        endpoint POSTs, rgw_pubsub.cc json_format_versioned_event)."""
        return json.dumps({"Records": [{
            "eventVersion": "2.2",
            "eventSource": "ceph:s3",
            "eventName": e.get("eventName", ""),
            "eventTime": e.get("eventTime", 0),
            "s3": {"bucket": {"name": e.get("bucket", "")},
                   "object": {"key": e.get("key", ""),
                              "eTag": e.get("etag", "")}},
            "opaqueData": opaque,
            "topic": topic,
        }]}).encode()

    async def _push_loop(self, topic: str, meta: dict,
                         ev: asyncio.Event) -> None:
        from ceph_tpu.services.rgw_push import DeliveryError, \
            PushEndpoint

        ep = PushEndpoint.make(meta["push_endpoint"],
                               meta.get("ack_level", "broker"))
        oid = self._topic_oid(topic)
        # cursor load rides the same backoff-retry as the batch loop:
        # a transient RadosError (failover while the worker spawns)
        # must neither kill the delivery worker nor reset the cursor
        # and mass-redeliver the whole queue
        while True:
            try:
                cursor = int(await self.ioctx.get_xattr(
                    oid, "push_cursor"))
                break
            except RadosError as e:
                if e.rc == -2:
                    cursor = 0     # topic never delivered before
                    break
                rgw_log.derr("push %s: cursor load error %s; backing "
                             "off", topic, e)
                await asyncio.sleep(1.0)
            except ValueError:
                cursor = 0
                break
        retries = int(meta.get("max_retries", 5))
        sleep0 = float(meta.get("retry_sleep", 0.05))
        down_sleep = sleep0                  # unreachable-endpoint backoff
        while True:
            try:
                # cross-handle reconfiguration: another gateway sharing
                # the pool may have replaced (or deleted) this topic —
                # re-read the (5s-cached) meta and respawn with fresh
                # attributes rather than pushing to a dead endpoint
                # forever
                fresh = await self._topic_meta(topic)
                if fresh is None:
                    return                    # topic deleted
                if fresh != meta:
                    if self._pushers.get(topic, (None,))[0] is \
                            asyncio.current_task():
                        self._pushers.pop(topic, None)
                    if fresh.get("push_endpoint"):
                        self._ensure_pusher(topic, fresh)
                    return
                batch = await self.topic_pull(topic, after=cursor)
                events = batch["events"]
                for e in events:
                    payload = self._event_payload(
                        topic, meta.get("opaque", ""), e)
                    delivered = False
                    rejected = False
                    for attempt in range(retries + 1):
                        try:
                            await ep.send(payload)
                            delivered = True
                            break
                        except DeliveryError as de:
                            rejected = de.connected
                            if not de.connected:
                                break   # dead endpoint: the outer
                                        # down_sleep paces reconnects
                            if attempt < retries:  # no backoff after
                                await asyncio.sleep(  # the last try
                                    min(sleep0 * (2 ** attempt), 2.0))
                    if not delivered and not rejected:
                        # UNREACHABLE endpoint (restart backlog before
                        # the consumer is up, network partition): the
                        # reference's persistent queues keep retrying
                        # within retention rather than discarding —
                        # hold position, back off, re-attempt later
                        rgw_log.dout(
                            5, "push %s: endpoint unreachable at seq "
                            "%s; retrying in %.1fs", topic, e["seq"],
                            down_sleep)
                        await asyncio.sleep(down_sleep)
                        down_sleep = min(down_sleep * 2, 5.0)
                        break
                    down_sleep = sleep0
                    if not delivered:
                        # the endpoint ANSWERED and rejected through
                        # every retry: dead-letter and move on so one
                        # rejecting consumer cannot wedge the topic.
                        # The DL log allocates its own seq — the
                        # original topic seq must not ride along or
                        # it would clobber deadletter_pull's cursor
                        rgw_log.derr(
                            "push %s: endpoint rejected event seq %s "
                            "%d times; dead-lettering", topic,
                            e["seq"], retries + 1)
                        parked = {k: v for k, v in e.items()
                                  if k != "seq"}
                        await self.ioctx.exec(
                            oid + ".deadletter", "rgw", "log_add",
                            json.dumps(parked).encode())
                    cursor = int(e["seq"])
                    # durable ack: a restarted worker resumes past
                    # this event (at-least-once — a crash between
                    # send and this write redelivers)
                    await self.ioctx.set_xattr(
                        oid, "push_cursor", str(cursor).encode())
            except RadosError as e:
                if e.rc != -2:
                    # transient cluster trouble (failover, timeout):
                    # the worker must survive it, not die with a
                    # backlog — back off, log, retry
                    rgw_log.derr("push %s: rados error %s; backing "
                                 "off", topic, e)
                    await asyncio.sleep(1.0)
                events = []            # rc=-2: queue not created yet
            if not events:
                ev.clear()
                try:
                    await asyncio.wait_for(ev.wait(), timeout=1.0)
                except asyncio.TimeoutError:
                    pass

    async def deadletter_pull(self, topic: str, after: int = 0,
                              max_events: int = 1000) -> dict:
        """Inspect events whose delivery exhausted max_retries."""
        try:
            out = json.loads(await self.ioctx.exec(
                self._topic_oid(topic) + ".deadletter", "rgw",
                "log_list",
                json.dumps({"after": after,
                            "max": max_events}).encode()))
        except RadosError as e:
            if e.rc != -2:
                raise
            return {"events": [], "last": after}
        entries = out.get("entries", [])
        return {"events": entries,
                "last": entries[-1]["seq"] if entries else after}

    async def topic_pull(self, topic: str, after: int = 0,
                         max_events: int = 1000) -> dict:
        """Consume queued events (pull mode): {'events': [...],
        'last': seq} — pass ``last`` back as ``after`` to resume."""
        out = json.loads(await self.ioctx.exec(
            self._topic_oid(topic), "rgw", "log_list",
            json.dumps({"after": after, "max": max_events}).encode(),
        ))
        entries = out.get("entries", [])
        last = entries[-1]["seq"] if entries else after
        return {"events": entries, "last": last}

    async def topic_trim(self, topic: str, upto: int) -> None:
        await self.ioctx.exec(
            self._topic_oid(topic), "rgw", "log_trim",
            json.dumps({"upto": upto}).encode(),
        )

    async def log_list(self, bucket: str, after: int = 0,
                       max_entries: int = 1000,
                       shard: int = 0) -> dict:
        out = await self.ioctx.exec(
            self._log_oid(bucket, shard), "rgw", "log_list",
            json.dumps({"after": after, "max": max_entries}).encode(),
        )
        return json.loads(out)

    async def log_trim(self, bucket: str, upto: int,
                       shard: int = 0) -> None:
        await self.ioctx.exec(
            self._log_oid(bucket, shard), "rgw", "log_trim",
            json.dumps({"upto": upto}).encode(),
        )

    async def create_bucket(self, bucket: str,
                            object_lock: bool = False) -> None:
        """``object_lock``: WORM bucket (rgw_object_lock role) —
        versioning is enabled atomically with it, as S3 requires;
        the flag cannot be added to an existing bucket."""
        if self.user == ANONYMOUS:
            raise RGWError("AccessDenied", "anonymous cannot create")
        if not bucket or any(ord(c) < 0x20 for c in bucket):
            raise RGWError("InvalidBucketName", repr(bucket))
        existing = await self.list_buckets()
        if bucket in existing:
            raise RGWError("BucketAlreadyExists", bucket)
        meta = {
            "created": time.time(),
            "owner": self.user or "",
            "acl": {"canned": "private"},
        }
        if object_lock:
            meta["object_lock"] = {"enabled": True}
            meta["versioning"] = "enabled"
        await self.ioctx.operate(BUCKETS_OID, ObjectOperation()
                                 .create()
                                 .omap_set({bucket: json.dumps(
                                     meta).encode()}))
        await self.ioctx.operate(self._index_oid(bucket),
                                 ObjectOperation().create())
        # a recreated name must not inherit the old bucket's configs
        self._notif_cache.pop(bucket, None)

    @_reclaims_space
    async def delete_bucket(self, bucket: str) -> None:
        meta = await self._bucket_meta(bucket)
        if self.user is not None and self.user != meta.get("owner"):
            raise RGWError("AccessDenied", bucket)
        index = await self._index_all(bucket, meta)
        if index:
            raise RGWError("BucketNotEmpty", bucket)
        try:
            if await self.ioctx.get_omap(self._versions_oid(bucket)):
                # ghost history must not leak into a recreated bucket
                raise RGWError("BucketNotEmpty",
                               f"{bucket} still has object versions")
            await self.ioctx.remove(self._versions_oid(bucket))
        except RadosError as e:
            if e.rc != -2:
                raise
        self._notif_cache.pop(bucket, None)
        for oid in self._index_shard_oids(bucket, meta):
            try:
                await self.ioctx.remove(oid)
            except RadosError as e:
                if e.rc != -2:
                    raise
        for shard in range(self.datalog_shards):
            try:
                await self.ioctx.remove(self._log_oid(bucket, shard))
            except RadosError as e:
                if e.rc != -2:
                    raise
        await self.ioctx.rm_omap_keys(BUCKETS_OID, [bucket])

    async def head_bucket(self, bucket: str) -> dict:
        """S3 HeadBucket: existence + access probe without pulling the
        whole bucket table or index (the rgw_file facade's per-call
        liveness check)."""
        return await self._check_bucket(bucket, "READ")

    async def list_buckets(self) -> list[str]:
        try:
            return sorted(await self.ioctx.get_omap(BUCKETS_OID))
        except RadosError as e:
            if e.rc == -2:
                return []
            raise

    # -- objects -----------------------------------------------------------
    @staticmethod
    def _data_oid(bucket: str, key: str) -> str:
        return f"rgw.obj.{bucket}/{key}"

    async def _prepare_put(self, bucket: str, key: str, length: int,
                           if_none_match: bool,
                           defer_cleanup: bool = False,
                           lock: dict | None = None,
                           storage_class: str | None = None) -> dict:
        """Everything a PUT decides BEFORE any body byte lands: ACL,
        preconditions, quota (against the declared length), versioning
        mode, target oid, and old-data cleanup.  Shared by the buffered
        and streaming paths.

        ``defer_cleanup`` (streaming): the replaced object's data must
        survive until complete() — an aborted stream (disconnect, hash
        mismatch) would otherwise have destroyed a durable object whose
        index entry still stands.  The stream writes to a UNIQUE oid
        and cleanup happens after the index flips to it."""
        meta = await self._check_bucket(bucket, "WRITE",
                                        action="s3:PutObject", key=key)
        self._index_writable(meta)
        index_oid = self._index_oid_for(bucket, meta, key)
        existing = await self._index_get(bucket, key, meta)
        if if_none_match and existing and \
                not json.loads(existing[key]).get("delete_marker"):
            raise RGWError("PreconditionFailed", key)
        versioned = meta.get("versioning") == "enabled"
        suspended = meta.get("versioning") == "suspended"
        if versioned:
            replaced, is_replace = 0, False
        elif suspended:
            replaced, is_replace = await self._suspended_replaced(
                bucket, key, existing.get(key))
        else:
            replaced = (json.loads(existing[key])["size"]
                        if key in existing else 0)
            is_replace = key in existing
        await self._check_quota(bucket, meta, length,
                                replaced_size=replaced,
                                is_replace=is_replace)
        oid = self._data_oid(bucket, key)
        version_id = None
        deferred: list[tuple] = []
        if versioned and defer_cleanup:
            version_id = self._new_version_id()
            oid = f"{oid}\x00v\x00{version_id}"
            if key in existing:
                # adopting the pre-versioning entry as 'null' must wait
                # for complete(): an aborted stream must leave the
                # version store untouched
                deferred.append(("adopt", json.loads(existing[key])))
        elif versioned:
            # every PUT is a NEW version: prior data objects survive
            # under their own version ids (rgw versioned-bucket model)
            version_id = self._new_version_id()
            oid = f"{oid}\x00v\x00{version_id}"
            if key in existing:
                await self._adopt_null_version(
                    bucket, key, json.loads(existing[key])
                )
        elif defer_cleanup:
            # unique data oid: an aborted stream removes only its own
            # bytes; the old object stays intact and indexed
            oid = f"{oid}\x00s\x00{secrets.token_hex(8)}"
            if key in existing:
                old = json.loads(existing[key])
                if suspended:
                    deferred.append(("null", None))
                if not old.get("version_id"):
                    deferred.append(("entry", old))
        elif key in existing:
            # drop the old data objects first: a smaller striped body
            # must not inherit the old size xattr / stale tail stripes
            old = json.loads(existing[key])
            if suspended:
                # a suspended-state PUT REPLACES the 'null' version;
                # every other version's data stays retrievable
                await self._remove_null_version(bucket, key)
            # data owned by a (non-null) version record stays
            # retrievable through the version API — never clean it
            if not old.get("version_id"):
                await self._remove_entry_data(bucket, key, old)
        if self.gc_min_wait > 0 and "\x00" not in oid:
            # deferred GC must NEVER share an oid with a later write:
            # an in-place striped overwrite would inherit the old size
            # xattr + tail stripes, and representation changes would
            # leak.  Unique per-write tail oids (the reference's tail
            # tag) make deferral safe for every shape.
            oid = f"{oid}\x00g\x00{secrets.token_hex(8)}"
        ctx = {"bucket": bucket, "key": key, "oid": oid,
               "index_oid": index_oid, "versioned": versioned,
               "suspended": suspended, "version_id": version_id,
               "deferred_cleanup": deferred, "meta": meta,
               "compression": meta.get("compression"),
               "storage_class": None, "pool": None}
        sclass = (storage_class or "").strip()
        if sclass and sclass != "STANDARD":
            # x-amz-storage-class routes the tail through the zone's
            # placement target for that class; the class's inline
            # compression overrides the bucket's
            place = await self._class_placement(sclass)
            ctx["storage_class"] = sclass
            ctx["pool"] = place.get("pool") or None
            if place.get("compression"):
                ctx["compression"] = place["compression"]
        # EVERY put shape flows through here — buffered, streaming,
        # multipart complete, SLO — so WORM state cannot be dodged
        # by picking a body size (the streaming-path hole)
        self._stage_lock(ctx, lock)
        return ctx

    async def put_slo_manifest(self, bucket: str, key: str,
                               segments: list[dict],
                               content_type: str =
                               "application/octet-stream",
                               metadata: dict | None = None) -> dict:
        """Swift Static Large Object manifest (rgw SLO support in
        rgw_rest_swift): ``segments`` are {"bucket", "key"} (+optional
        "etag"/"size_bytes" to validate); the stored entry reuses the
        multipart manifest read path, so plain GETs concatenate and
        range/stream like any multipart object.  Segments must be
        plain-stored (not striped/compressed/SSE-C/multipart) and stay
        independent objects — deleting the manifest leaves them."""
        if not segments:
            raise RGWError("InvalidArgument", "empty SLO manifest")
        manifest = []
        descr = []
        etags = hashlib.md5()
        total = 0
        for seg in segments:
            sb, sk = str(seg["bucket"]), str(seg["key"])
            entry = await self._entry(sb, sk)
            if entry.get("striped") or entry.get("multipart") \
                    or entry.get("sse") or entry.get("comp"):
                raise RGWError(
                    "InvalidArgument",
                    f"SLO segment {sb}/{sk} must be a plain object")
            if "etag" in seg and seg["etag"] and \
                    seg["etag"] != entry["etag"]:
                raise RGWError("InvalidArgument",
                               f"segment {sb}/{sk} etag mismatch")
            if "size_bytes" in seg and seg["size_bytes"] and \
                    int(seg["size_bytes"]) != int(entry["size"]):
                raise RGWError("InvalidArgument",
                               f"segment {sb}/{sk} size mismatch")
            oid = entry.get("data_oid", self._data_oid(sb, sk))
            manifest.append({"oid": oid, "size": int(entry["size"])})
            descr.append({"name": f"/{sb}/{sk}",
                          "etag": entry["etag"],
                          "bytes": int(entry["size"])})
            etags.update(entry["etag"].encode())
            total += int(entry["size"])
        # quota: the manifest stores no NEW bytes (segments already
        # paid); charge zero or every SLO byte would count twice
        ctx = await self._prepare_put(bucket, key, 0, False)
        meta = dict(metadata or {})
        meta["slo_segments"] = descr        # faithful manifest echo
        return await self._finish_put(
            ctx, total, f"{etags.hexdigest()}-{len(manifest)}",
            False, content_type, meta, None, multipart=manifest,
            slo=True)

    async def begin_put(self, bucket: str, key: str, length: int,
                        content_type: str = "binary/octet-stream",
                        metadata: dict[str, str] | None = None,
                        if_none_match: bool = False,
                        lock: dict | None = None,
                        storage_class: str | None = None
                        ) -> "StreamingPut":
        """Chunked S3 PUT session (the beast frontend's streaming body
        path): validation happens up front against the declared length,
        then body chunks land at their striper offsets without ever
        buffering the whole object."""
        ctx = await self._prepare_put(bucket, key, length,
                                      if_none_match,
                                      defer_cleanup=True, lock=lock,
                                      storage_class=storage_class)
        return StreamingPut(self, ctx, length, content_type,
                            dict(metadata or {}))

    async def put_object(self, bucket: str, key: str, data: bytes,
                         content_type: str = "binary/octet-stream",
                         metadata: dict[str, str] | None = None,
                         if_none_match: bool = False,
                         sse_key: bytes | None = None,
                         tags: dict[str, str] | None = None,
                         lock: dict | None = None,
                         sse: str | None = None,
                         kms_key_id: str | None = None,
                         storage_class: str | None = None) -> dict:
        """S3 PUT. ``if_none_match``: fail when the key exists ('*').
        ``sse_key``: SSE-C customer key (32 bytes, AES-256).
        ``sse``: server-managed encryption — "aws:kms" (SSE-KMS, key
        named by ``kms_key_id``) or "AES256" (SSE-S3, zone key); the
        x-amz-server-side-encryption header.
        ``tags``: object tags (the x-amz-tagging header).
        ``lock``: explicit object-lock state for the new version:
        {mode, until, legal_hold} (x-amz-object-lock-* headers).
        ``storage_class``: x-amz-storage-class — the tail lands in the
        class's placement pool (STANDARD/None = the zone pool)."""
        with self._trace_root("rgw:put", bucket=bucket, key=key,
                              size=len(data)):
            return await self._put_object_impl(
                bucket, key, data, content_type, metadata,
                if_none_match, sse_key, tags, lock, sse, kms_key_id,
                storage_class)

    async def _put_object_impl(self, bucket, key, data, content_type,
                               metadata, if_none_match, sse_key, tags,
                               lock, sse, kms_key_id,
                               storage_class) -> dict:
        if tags:
            self.validate_tags(tags)
        if sse is not None and sse_key is not None:
            raise RGWError("InvalidArgument",
                           "SSE-C and server-side encryption are "
                           "mutually exclusive")
        ctx = await self._prepare_put(bucket, key, len(data),
                                      if_none_match, lock=lock,
                                      storage_class=storage_class)
        etag = hashlib.md5(data).hexdigest()
        size = len(data)
        comp = None
        if ctx.get("compression") in list_compressors() \
                and sse_key is None and sse is None:
            # compress-at-rest (rgw_compression.cc): S3-visible
            # size/etag stay the original
            data, comp = deflate_if_smaller(data, ctx["compression"])
        if sse is not None:
            dk, kms_sse = await self._kms_begin(sse, kms_key_id)
            data = sse_crypt(dk, bytes.fromhex(kms_sse["nonce"]),
                             0, data)
            sse = kms_sse
        elif sse_key is not None:
            sse = sse_begin(sse_key)
            data = sse_crypt(sse_key, bytes.fromhex(sse["nonce"]),
                             0, data)
        oid = ctx["oid"]
        ioctx, striper = await self._data_handles(ctx.get("pool"))
        striped = len(data) > STRIPE_THRESHOLD
        if striped:
            await striper.write(oid, data)
        else:
            op = ObjectOperation().write_full(data)
            await ioctx.operate(oid, op)
        return await self._finish_put(ctx, size, etag, striped,
                                      content_type,
                                      dict(metadata or {}), sse,
                                      comp=comp, tags=tags)

    async def _finish_put(self, ctx: dict, size: int, etag: str,
                          striped: bool, content_type: str,
                          metadata: dict, sse: dict | None,
                          comp: dict | None = None,
                          multipart: list | None = None,
                          slo: bool = False,
                          tags: dict | None = None) -> dict:
        """Publish the index entry once the data is down (shared by
        buffered and streaming PUTs)."""
        bucket, key = ctx["bucket"], ctx["key"]
        versioned = ctx["versioned"]
        version_id = ctx["version_id"]
        entry = {
            "size": size, "etag": etag, "mtime": time.time(),
            "content_type": content_type, "striped": striped,
            "meta": metadata,
            "data_oid": ctx["oid"],
        }
        # storage class + tail pool ride the head record (the
        # RGWObjManifest's placement rule); absent = STANDARD in the
        # zone pool, so pre-tiering entries parse unchanged
        if ctx.get("storage_class"):
            entry["storage_class"] = ctx["storage_class"]
        if ctx.get("pool"):
            entry["pool"] = ctx["pool"]
        if sse is not None:
            entry["sse"] = sse
        if comp is not None:
            entry["comp"] = comp
        if multipart is not None:
            entry["multipart"] = multipart
        if slo:
            # Swift SLO: the manifest only REFERENCES independent
            # segment objects — deleting it must not delete them
            entry["slo"] = True
        if tags:
            entry["tags"] = {str(k): str(v) for k, v in tags.items()}
        if ctx.get("lock_retention"):
            entry["retention"] = ctx["lock_retention"]
        if ctx.get("lock_legal_hold"):
            entry["legal_hold"] = True
        if versioned:
            entry["version_id"] = version_id
            await self._record_version(bucket, key, entry)
        elif ctx["suspended"]:
            entry["version_id"] = "null"
            await self._record_version(bucket, key, entry)
        await self.ioctx.set_omap(ctx["index_oid"], {
            key: json.dumps(entry).encode(),
        })
        await self._log(bucket, "put", key, etag, size=size)
        await self._maybe_auto_reshard(bucket, ctx.get("meta", {}),
                                       key)
        out = {"etag": etag, "size": size}
        if versioned:
            out["version_id"] = version_id
        return out

    async def _entry(self, bucket: str, key: str,
                     need: str = "READ",
                     action: str = "s3:GetObject") -> dict:
        meta = await self._check_bucket(bucket, need,
                                        action=action, key=key)
        kv = await self._index_get(bucket, key, meta)
        if key not in kv:
            raise RGWError("NoSuchKey", f"{bucket}/{key}")
        entry = json.loads(kv[key])
        if entry.get("delete_marker"):
            raise RGWError("NoSuchKey", f"{bucket}/{key}")
        return entry

    async def get_object(self, bucket: str, key: str,
                         range_: tuple[int, int] | None = None,
                         sse_key: bytes | None = None) -> dict:
        """S3 GET (optionally a byte range, inclusive bounds).
        ``sse_key``: the SSE-C customer key for encrypted objects;
        SSE-KMS / SSE-S3 objects decrypt server-side via the KMS."""
        with self._trace_root("rgw:get", bucket=bucket, key=key):
            return await self._get_object_impl(bucket, key, range_,
                                               sse_key)

    async def _get_object_impl(self, bucket, key, range_,
                               sse_key) -> dict:
        entry = await self._entry(bucket, key)
        dk = await self._entry_sse_key(entry, sse_key)
        if entry.get("comp"):
            # compressed at rest: ranges slice the INFLATED bytes
            data = await self._inflate_read(entry, range_)
            return {"data": data, **entry}
        if dk is not None and entry["sse"].get("multipart"):
            data = await self._read_manifest(
                entry["multipart"], int(entry["size"]), range_,
                sse_key=dk, pool=entry.get("pool"))
            return {"data": data, **entry}
        data = await self._read_entry_data(bucket, key, entry, range_)
        if dk is not None:
            start = range_[0] if range_ is not None else 0
            data = sse_crypt(dk,
                             bytes.fromhex(entry["sse"]["nonce"]),
                             start, data)
        return {"data": data, **entry}

    async def _read_stored(self, entry: dict, off: int,
                           length: int) -> bytes:
        """Stored (possibly deflated) bytes by STORED offset — never
        clamped by the inflated size, which deflate can exceed."""
        oid = entry["data_oid"]
        ioctx, striper = await self._data_handles(entry.get("pool"))
        if entry["striped"]:
            return await striper.read(oid, length, off)
        return await ioctx.read(oid, length, off)

    async def _inflate_read(self, entry: dict,
                            range_: tuple[int, int] | None) -> bytes:
        """Read an at-rest-compressed entry's INFLATED bytes. Blocked
        objects (streamed PUTs) inflate only the blocks the range
        touches; legacy whole-body deflate inflates everything."""
        size = int(entry["size"])
        start, end = (0, size - 1) if range_ is None else range_
        end = min(end, size - 1)
        if end < start:
            return b""
        comp = get_compressor(entry["comp"].get("alg", "zlib"))
        blocks = entry["comp"].get("blocks")
        if blocks is None:
            raw = await self._read_stored(
                entry, 0, entry["comp"]["stored_size"])
            return comp.decompress(raw)[start:end + 1]
        async def one(soff, slen, skip, take):
            raw = await self._read_stored(entry, soff, slen)
            return comp.decompress(raw)[skip:skip + take]

        # the windows are independent stored ranges: fetch + inflate
        # them concurrently (the result is buffered whole either way)
        out = await asyncio.gather(*(
            one(*w) for w in comp_window(blocks, start, end)))
        return b"".join(out)

    async def _read_entry_data(self, bucket: str, key: str,
                               entry: dict,
                               range_: tuple[int, int] | None) -> bytes:
        oid = entry.get("data_oid", self._data_oid(bucket, key))
        if entry.get("multipart"):
            return await self._read_manifest(entry["multipart"],
                                             entry["size"], range_,
                                             pool=entry.get("pool"))
        ioctx, striper = await self._data_handles(entry.get("pool"))
        if range_ is not None:
            start, end = range_
            end = min(end, entry["size"] - 1)
            length = max(0, end - start + 1)
            if entry["striped"]:
                return await striper.read(oid, length, start)
            return await ioctx.read(oid, length, start)
        if entry["striped"]:
            return await striper.read(oid)
        return await ioctx.read(oid)

    async def stream_object(self, bucket: str, key: str,
                            range_: tuple[int, int] | None = None,
                            sse_key: bytes | None = None,
                            chunk: int = 1 << 20,
                            entry: dict | None = None):
        """Chunked S3 GET: returns (entry, async-generator) so the
        frontend never buffers the whole body (the beast frontend's
        streaming response path).  ``entry``: pass a just-fetched index
        entry to skip the re-read."""
        if entry is None:
            entry = await self._entry(bucket, key)
        sse_check(entry, sse_key)
        if entry.get("comp"):
            # read through the GIVEN entry so the headers the caller
            # already built and the body can never describe different
            # objects
            blocks = entry["comp"].get("blocks")
            if blocks is None:
                # legacy whole-body deflate (small buffered puts)
                data = await self._inflate_read(entry, range_)

                async def one():
                    yield data

                return entry, one()
            size = int(entry["size"])
            start, end = (0, size - 1) if range_ is None else range_
            end = min(end, size - 1)
            windows = comp_window(blocks, start, end)
            comp_dec = get_compressor(entry["comp"].get("alg", "zlib"))

            async def blocked():
                # one block in memory at a time: the block map keeps
                # streamed GETs of compressed objects bounded
                for soff, slen, skip, take in windows:
                    raw = await self._read_stored(entry, soff, slen)
                    yield comp_dec.decompress(raw)[skip:skip + take]

            return entry, blocked()
        size = int(entry["size"])
        start, end = (0, size - 1) if range_ is None else range_
        end = min(end, size - 1)
        if sse_key is not None and entry["sse"].get("multipart"):
            manifest = entry["multipart"]
            windows = manifest_window(
                [int(p["size"]) for p in manifest], start, end)
            mp_ioctx, _ = await self._data_handles(entry.get("pool"))

            async def gen_mp():
                # per-part nonces: decrypt at part-relative offsets,
                # chunk-bounded so huge parts never buffer whole
                for i, off, length in windows:
                    part = manifest[i]
                    pnonce = bytes.fromhex(part["nonce"])
                    pos, rem = off, length
                    while rem > 0:
                        n = min(chunk, rem)
                        data = await mp_ioctx.read(part["oid"], n,
                                                   pos)
                        yield sse_crypt(sse_key, pnonce, pos, data)
                        pos += n
                        rem -= n

            return entry, gen_mp()
        nonce = (bytes.fromhex(entry["sse"]["nonce"])
                 if sse_key is not None else b"")

        async def gen():
            pos = start
            while pos <= end:
                n = min(chunk, end - pos + 1)
                data = await self._read_entry_data(
                    bucket, key, entry, (pos, pos + n - 1))
                if sse_key is not None:
                    data = sse_crypt(sse_key, nonce, pos, data)
                yield data
                pos += n

        return entry, gen()

    async def _read_manifest(self, manifest: list[dict], size: int,
                             range_: tuple[int, int] | None,
                             sse_key: bytes | None = None,
                             pool: str | None = None) -> bytes:
        """Read through a multipart manifest (RGWObjManifest role):
        only the parts overlapping the requested range are fetched.
        ``sse_key``: decrypt SSE-C parts with their per-part nonce at
        part-relative offsets.  ``pool``: the placement pool the parts
        live in (zone pool when None)."""
        start, end = (0, size - 1) if range_ is None else range_
        end = min(end, size - 1)
        ioctx, _ = await self._data_handles(pool)
        chunks = []
        for i, off, length in manifest_window(
                [int(p["size"]) for p in manifest], start, end):
            raw = await ioctx.read(manifest[i]["oid"], length, off)
            if sse_key is not None and manifest[i].get("nonce"):
                raw = sse_crypt(
                    sse_key, bytes.fromhex(manifest[i]["nonce"]),
                    off, raw)
            chunks.append(raw)
        return b"".join(chunks)

    async def head_object(self, bucket: str, key: str) -> dict:
        return await self._entry(bucket, key)

    @_reclaims_space
    async def delete_object(self, bucket: str, key: str) -> None:
        meta = await self._check_bucket(
            bucket, "WRITE", action="s3:DeleteObject", key=key)
        state = meta.get("versioning", "")
        self._index_writable(meta)
        index_oid = self._index_oid_for(bucket, meta, key)
        kv = await self._index_get(bucket, key, meta)
        entry = json.loads(kv[key]) if key in kv else None
        if state == "enabled":
            # versioned DELETE always succeeds: data survives and a
            # delete MARKER becomes current — stacking on prior
            # markers and absent keys alike (S3 semantics)
            if entry is not None and not entry.get("delete_marker"):
                await self._adopt_null_version(bucket, key, entry)
            version_id = self._new_version_id()
            marker = {
                "size": 0, "etag": "", "mtime": time.time(),
                "delete_marker": True, "version_id": version_id,
                "striped": False, "meta": {},
            }
            await self._record_version(bucket, key, marker)
            await self.ioctx.set_omap(index_oid, {
                key: json.dumps(marker).encode(),
            })
            await self._log(bucket, "del", key,
                            event="s3:ObjectRemoved:DeleteMarkerCreated")
            return
        if state == "suspended":
            # suspended DELETE replaces the 'null' version with a null
            # delete marker; versioned history is untouched.  A
            # pre-versioning current entry IS the implicit null
            # version — its data dies with it, or it leaks forever
            await self._remove_null_version(bucket, key)
            if entry is not None and not entry.get("version_id") \
                    and not entry.get("delete_marker"):
                await self._remove_entry_data(bucket, key, entry)
            marker = {
                "size": 0, "etag": "", "mtime": time.time(),
                "delete_marker": True, "version_id": "null",
                "striped": False, "meta": {},
            }
            await self._record_version(bucket, key, marker)
            await self.ioctx.set_omap(index_oid, {
                key: json.dumps(marker).encode(),
            })
            await self._log(bucket, "del", key,
                            event="s3:ObjectRemoved:DeleteMarkerCreated")
            return
        if entry is None or entry.get("delete_marker"):
            raise RGWError("NoSuchKey", f"{bucket}/{key}")
        await self._remove_entry_data(bucket, key, entry)
        await self.ioctx.rm_omap_keys(index_oid, [key])
        await self._log(bucket, "del", key)

    async def copy_object(self, src_bucket: str, src_key: str,
                          dst_bucket: str, dst_key: str,
                          src_sse_key: bytes | None = None,
                          sse_key: bytes | None = None,
                          sse: str | None = None,
                          kms_key_id: str | None = None,
                          storage_class: str | None = None) -> dict:
        """S3 CopyObject.  A KMS-encrypted source decrypts server-side
        (no key needed); SSE-C sources need ``src_sse_key``.  The
        destination re-encrypts per ``sse``/``kms_key_id``/``sse_key``
        — copies never splice ciphertext, so source and destination
        keys are independent (rgw_crypt.cc copy rule).
        ``storage_class``: the DESTINATION's class (a copy is a fresh
        PUT; the source's class does not follow the bytes)."""
        got = await self.get_object(src_bucket, src_key,
                                    sse_key=src_sse_key)
        return await self.put_object(
            dst_bucket, dst_key, got["data"],
            content_type=got["content_type"], metadata=got["meta"],
            tags=got.get("tags") or None,
            sse_key=sse_key, sse=sse, kms_key_id=kms_key_id,
            storage_class=storage_class,
        )

    async def list_objects(self, bucket: str, prefix: str = "",
                           marker: str = "",
                           max_keys: int = 1000,
                           delimiter: str = "") -> dict:
        """S3 ListObjects: sorted, prefix-filtered, marker-paginated.
        ``delimiter`` rolls keys sharing prefix..delimiter up into
        common_prefixes (the folder-browsing view); common prefixes
        count toward max_keys, as S3 counts them."""
        meta = await self._check_bucket(bucket, "READ",
                                        action="s3:ListBucket")
        index = await self._index_all(bucket, meta)
        contents: list = []
        prefixes: list[str] = []
        seen_prefixes: set[str] = set()
        truncated = False
        last = ""
        # lazy parse: stop after filling the page + 1 (truncation
        # probe) instead of json-decoding the whole bucket per listing
        for k in sorted(index):
            if not k.startswith(prefix) or k <= marker:
                continue
            if delimiter:
                rest = k[len(prefix):]
                pos = rest.find(delimiter)
                if pos >= 0:
                    cp = prefix + rest[:pos + len(delimiter)]
                    if cp in seen_prefixes or cp == marker:
                        continue      # rolled up / prior page
                    # a marker STRICTLY inside the group (start-after
                    # on a member key) must not hide the group: keys
                    # past it still roll up, as S3 rolls them
                    if json.loads(index[k]).get("delete_marker"):
                        continue      # a dead member alone must not
                                      # surface a phantom prefix
                    if len(contents) + len(prefixes) == max_keys:
                        truncated = True
                        break
                    seen_prefixes.add(cp)
                    prefixes.append(cp)
                    last = cp
                    continue
            entry = json.loads(index[k])
            if entry.get("delete_marker"):
                continue
            if len(contents) + len(prefixes) == max_keys:
                truncated = True
                break
            item = {
                "key": k, "size": entry["size"], "etag": entry["etag"],
                "mtime": entry["mtime"],
            }
            if entry.get("tags"):
                item["tags"] = entry["tags"]
            if entry.get("storage_class"):
                item["storage_class"] = entry["storage_class"]
            contents.append(item)
            last = k
        return {
            "contents": contents,
            "common_prefixes": prefixes,
            "is_truncated": truncated,
            "next_marker": last if truncated else "",
        }
