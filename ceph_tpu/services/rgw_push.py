"""Push-mode bucket-notification endpoints.

The RGWPubSubEndpoint role (reference src/rgw/rgw_pubsub_push.h:20,
745-LoC impl in rgw_pubsub_push.cc): scheme-dispatched endpoint
objects that deliver one event and report success per their ack
level.  This image ships no AMQP/Kafka client libraries, so the
endpoint family is http(s) — the reference's RGWPubSubHTTPEndpoint —
delivered with a minimal asyncio HTTP/1.1 POST.  Retry / backoff /
dead-letter live in the caller (services/rgw.py's per-topic push
worker, the rgw_notify.cc persistent-topic semantics).

Ack levels (the reference's ack-level endpoint arg):
- "broker": a 2xx response is required (default);
- "none": fire-and-forget — the connection + request must succeed but
  any status acks.
"""

from __future__ import annotations

import asyncio
import ssl as ssl_mod
import urllib.parse


class DeliveryError(Exception):
    """One delivery attempt failed.  ``connected`` distinguishes an
    endpoint that ANSWERED and rejected (dead-letter material after
    retries) from one that was unreachable (keep retrying with backoff
    — the reference's persistent queues retry within the retention
    window rather than discarding while a consumer is down)."""

    def __init__(self, msg: str, connected: bool = False):
        super().__init__(msg)
        self.connected = connected


async def _http_post(url: str, body: bytes,
                     timeout: float = 5.0) -> int:
    u = urllib.parse.urlsplit(url)
    if u.scheme not in ("http", "https"):
        raise DeliveryError(f"unsupported scheme {u.scheme!r}")
    host = u.hostname or ""
    port = u.port or (443 if u.scheme == "https" else 80)
    ctx = ssl_mod.create_default_context() if u.scheme == "https" \
        else None
    writer = None
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port, ssl=ctx), timeout)
        path = u.path or "/"
        if u.query:
            path += "?" + u.query
        req = (f"POST {path} HTTP/1.1\r\n"
               f"Host: {host}\r\n"
               "Content-Type: application/json\r\n"
               f"Content-Length: {len(body)}\r\n"
               "Connection: close\r\n\r\n").encode() + body
        writer.write(req)
        await asyncio.wait_for(writer.drain(), timeout)
        status_line = await asyncio.wait_for(reader.readline(), timeout)
        parts = status_line.split()
        if len(parts) < 2 or not parts[0].startswith(b"HTTP/"):
            raise DeliveryError(f"bad status line {status_line!r}",
                                connected=True)
        return int(parts[1])
    except DeliveryError:
        raise
    except (OSError, ValueError, asyncio.TimeoutError) as e:
        raise DeliveryError(f"POST {url}: {e}") from e
    finally:
        if writer is not None:
            writer.close()
            try:
                await writer.wait_closed()
            except (OSError, ssl_mod.SSLError):
                pass


class PushEndpoint:
    """Scheme-dispatched endpoint (RGWPubSubEndpoint::create role)."""

    def __init__(self, url: str, ack_level: str = "broker",
                 timeout: float = 5.0):
        self.url = url
        self.ack_level = ack_level
        self.timeout = timeout

    @staticmethod
    def make(url: str, ack_level: str = "broker",
             timeout: float = 5.0) -> "PushEndpoint":
        scheme = urllib.parse.urlsplit(url).scheme
        if scheme in ("http", "https"):
            return HTTPPushEndpoint(url, ack_level, timeout)
        raise ValueError(
            f"unsupported push endpoint scheme {scheme!r} "
            "(http/https supported; amqp/kafka need client libraries "
            "this image does not ship)")

    async def send(self, payload: bytes) -> None:
        raise NotImplementedError


class HTTPPushEndpoint(PushEndpoint):
    async def send(self, payload: bytes) -> None:
        status = await _http_post(self.url, payload, self.timeout)
        if self.ack_level != "none" and not 200 <= status < 300:
            raise DeliveryError(f"endpoint answered {status}",
                                connected=True)
