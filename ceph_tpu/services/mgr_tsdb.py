"""TSDB mgr module: the retention layer of the observability stack.

``TSDBMonitor`` runs LAST in the module dispatch order, so each report
cycle it records what the cycle actually CONCLUDED — the SLO verdicts
the engine just rendered, the tenant-class burn pairs, the utilization
rates, the QoS defense-plane position, the delta-collect payload
accounting, the tracer health counters, and the per-signature device
kernel profile — into the bounded :class:`ceph_tpu.common.tsdb.TSDB`
ring store.  Everything downstream reads from here:

- ``Mgr.ts_query`` / the dashboard ``/api/ts`` endpoint / the ``ts
  query`` admin-socket command serve time-sliced series,
- the digest gains a bounded ``tsdb`` section (catalog stats, raw
  tails, kernel table, tracer rates) that rides mgr report to the mon
  so ``ceph-tpu top`` can render it from anywhere in the cluster,
- forensic bundles attach the last ten minutes of every relevant
  series (``forensics_contrib``), so a bundle shows the LEAD-UP to a
  violation, not just the moment of capture.

The module issues no collects of its own: it harvests the snapshot the
SLO module (which runs earlier the same cycle) already pulled.
"""

from __future__ import annotations

import time

from ceph_tpu.common.perf import hist_quantile
from ceph_tpu.services.mgr_modules import MgrModule

# series namespaces a forensic bundle attaches (the burn-rate /
# rebuild / class-histogram lead-up ISSUE's satellite 3 names)
FORENSIC_PREFIXES = ("slo.", "class.", "util.", "qos.", "tracer.",
                     "collect.", "kernel.")
FORENSIC_WINDOW_S = 600.0


class TSDBMonitor(MgrModule):
    name = "ts"

    def __init__(self, mgr):
        super().__init__(mgr)
        self.tsdb = None
        # tracer eviction RATE between our own cycles: the counter is
        # cumulative, the warning condition is "still evicting NOW"
        self._prev_evictions = 0.0
        self._prev_evict_t = 0.0
        self.last_tracer: dict = {}
        self.last_kernels: dict[str, dict] = {}

    def _ensure(self):
        # lazy like the SLO engine: conf overrides installed after
        # construction are honored
        if self.tsdb is None:
            from ceph_tpu.common.tsdb import TSDB

            self.tsdb = TSDB.from_conf(self.mgr.conf)
        return self.tsdb

    async def serve_once(self) -> None:
        db = self._ensure()
        now = time.time()
        feed: dict[str, float] = {}
        slo = self.mgr.modules.get("slo")
        if slo is not None:
            for rec in getattr(slo, "last_eval", None) or ():
                obj = rec.get("objective")
                feed[f"slo.{obj}.burn"] = rec.get("burn_rate", 0.0)
                if rec.get("value") is not None:
                    feed[f"slo.{obj}.value"] = rec["value"]
            for cls, rec in (getattr(slo, "class_eval", None)
                             or {}).items():
                feed[f"slo.class.{cls}.fast_burn"] = \
                    rec.get("fast_burn", 0.0)
                feed[f"slo.class.{cls}.slow_burn"] = \
                    rec.get("slow_burn", 0.0)
            for cls, h in (getattr(slo, "class_hists", None)
                           or {}).items():
                feed[f"class.{cls}.ops"] = h.get("count") or 0
                q = hist_quantile(h, 0.99)
                if q is not None:
                    feed[f"class.{cls}.p99_ms"] = q / 1000.0
            for key, val in (getattr(slo, "util", None) or {}).items():
                if isinstance(val, (int, float)):
                    feed[f"util.{key}"] = val
        qos = self.mgr.modules.get("qos")
        tick = getattr(qos, "last_tick", None) or {}
        if tick:
            feed["qos.burn"] = tick.get("burn", 0.0)
            feed["qos.burning"] = 1.0 if tick.get("burning") else 0.0
        cs = self.mgr.collect_stats
        feed["collect.payload_bytes"] = cs.get("last_payload_bytes", 0)
        feed["collect.resyncs"] = cs.get("resyncs", 0)
        self._harvest_daemons(feed, slo, now)
        db.observe_many(now, feed)

    def _harvest_daemons(self, feed: dict, slo, now: float) -> None:
        """Tracer health + device-kernel profile, summed across the
        per-daemon dumps the SLO module collected this cycle."""
        snap = getattr(slo, "last_snap", None) or {}
        evictions = orphans = 0.0
        kernels: dict[str, dict] = {}
        for dump in snap.values():
            evictions += float(dump.get("tracer_ring_evictions", 0)
                               or 0)
            orphans += float(dump.get("tracer_orphan_spans", 0) or 0)
            for sig, rec in (dump.get("ec_kernels") or {}).items():
                agg = kernels.setdefault(sig, {
                    "launches": 0, "stripes": 0, "wall_us": 0.0,
                    "hbm_bytes": 0})
                agg["launches"] += int(rec.get("launches", 0))
                agg["stripes"] += int(rec.get("stripes", 0))
                agg["wall_us"] += float(rec.get("wall_us", 0.0))
                agg["hbm_bytes"] += int(rec.get("hbm_bytes", 0))
        feed["tracer.ring_evictions"] = evictions
        feed["tracer.orphan_spans"] = orphans
        rate = 0.0
        if self._prev_evict_t:
            dt = max(1e-9, now - self._prev_evict_t)
            rate = max(0.0, evictions - self._prev_evictions) / dt
        feed["tracer.eviction_rate"] = rate
        self._prev_evictions = evictions
        self._prev_evict_t = now
        self.last_tracer = {
            "ring_evictions": int(evictions),
            "orphan_spans": int(orphans),
            "eviction_rate": round(rate, 4),
        }
        peak = float(self.mgr.conf["ec_hbm_peak_gibps"] or 0.0)
        for sig, agg in kernels.items():
            wall_s = agg["wall_us"] / 1e6
            agg["gibps"] = round(
                agg["hbm_bytes"] / (1 << 30) / wall_s, 3) \
                if wall_s > 0 else 0.0
            agg["roofline_pct"] = round(
                100.0 * agg["gibps"] / peak, 3) if peak > 0 else 0.0
            feed[f"kernel.{sig}.wall_us"] = agg["wall_us"]
            feed[f"kernel.{sig}.launches"] = agg["launches"]
            feed[f"kernel.{sig}.hbm_bytes"] = agg["hbm_bytes"]
            feed[f"kernel.{sig}.gibps"] = agg["gibps"]
        self.last_kernels = kernels

    # -- query surfaces ----------------------------------------------------
    def query(self, name: str = "", start: float | None = None,
              end: float | None = None, tier: str = "auto",
              prefix: str = "", max_points: int = 0) -> dict:
        """The one query entry point every surface delegates to
        (``Mgr.ts_query``, ``/api/ts``, the ``ts query`` asok)."""
        db = self._ensure()
        if prefix and not name:
            return {"stats": db.stats(),
                    "series": db.query_prefix(
                        prefix, start, end, tier,
                        int(max_points or 0))}
        if not name:
            return {"stats": db.stats(), "names": db.names()}
        return db.query(name, start, end, tier, int(max_points or 0))

    # -- mgr surfaces ------------------------------------------------------
    def digest_contrib(self) -> dict:
        db = self._ensure()
        cap = int(self.mgr.conf["tsdb_digest_points"])
        tails = {n: db.query(n, tier="raw",
                             max_points=cap)["points"]
                 for n in db.names()}
        return {"tsdb": {
            "stats": db.stats(),
            "tracer": dict(self.last_tracer),
            "kernels": {sig: dict(a)
                        for sig, a in self.last_kernels.items()},
            "collect": dict(self.mgr.collect_stats),
            "tails": tails,
        }}

    def forensics_contrib(self) -> dict:
        """The last ten minutes of every relevant series: the bundle
        must show the lead-up, not just the moment of capture."""
        db = self._ensure()
        start = time.time() - FORENSIC_WINDOW_S
        series: dict[str, dict] = {}
        for prefix in FORENSIC_PREFIXES:
            series.update(db.query_prefix(prefix, start=start))
        return {"window_s": FORENSIC_WINDOW_S,
                "stats": db.stats(), "series": series}

    def prom_metrics(self) -> dict[str, dict]:
        db = self._ensure()
        st = db.stats()
        return {
            "ceph_tsdb_series": {
                "help": "series retained by the mgr tsdb",
                "samples": [("", float(st["series"]))]},
            "ceph_tsdb_points": {
                "help": "points retained across all tsdb tiers",
                "samples": [("", float(st["points"]))]},
            "ceph_tsdb_evictions": {
                "help": "ring evictions across all tsdb series",
                "samples": [("", float(st["evictions"]))]},
            "ceph_tracer_eviction_rate": {
                "help": "cluster tracer span-ring evictions per "
                        "second (nonzero = traces being lost NOW)",
                "samples": [("", float(
                    self.last_tracer.get("eviction_rate", 0.0)))]},
        }
