"""Mgr perf/maintenance modules: osd_perf_query, rbd_support, iostat.

Reference counterparts:
- ``osd_perf_query`` (src/pybind/mgr/osd_perf_query/module.py:23):
  dynamic OSD perf queries — ``osd perf query add`` installs a grouped
  counter collector on every up OSD, ``osd perf counters get`` reads
  the merged results.
- ``rbd_support`` (src/pybind/mgr/rbd_support/module.py:14-16,148):
  trash purge schedules (cron-like deferred-trash reaping per pool)
  and ``rbd perf image iostat`` — per-image IO rates, fed by an
  rbd_image-grouped OSD perf query.
- ``iostat`` (src/pybind/mgr/iostat): whole-cluster IO rates derived
  from successive perf-counter samples.

Command plumbing follows the orchestrator module's contract: the
monitor stages specs in the config-key store (mon/mgr_stat.py command
handlers), these modules act on them each serve cycle, and results ride
the digest back to the monitor, where the CLI reads them.
"""

from __future__ import annotations

import json
import time

from ceph_tpu.common.log import Dout
from ceph_tpu.services.mgr_modules import MgrModule

log = Dout("mgr")

PQ_SPEC_PREFIX = "mgr/osd_perf_query/"       # config-key: qid -> spec
TRASH_SCHED_PREFIX = "mgr/rbd_support/trash_sched/"   # pool -> spec
RBD_IOSTAT_QID = 1_000_000   # reserved query id for rbd image iostat


class OSDPerfQuery(MgrModule):
    """Dynamic perf queries over every up OSD."""

    name = "osd_perf_query"

    def __init__(self, mgr):
        super().__init__(mgr)
        self._installed: dict[int, dict] = {}   # qid -> spec
        self._results: dict[int, dict] = {}     # qid -> merged counters

    async def _kv(self, prefix_cmd: str, **kw):
        return await self.mgr.monc.command(prefix_cmd, **kw)

    async def _specs(self) -> dict[int, dict]:
        r = await self._kv("config-key ls")
        specs: dict[int, dict] = {}
        for key in r.get("data", []):
            if not key.startswith(PQ_SPEC_PREFIX):
                continue
            g = await self._kv("config-key get", key=key)
            if g.get("rc"):
                continue
            try:
                specs[int(key[len(PQ_SPEC_PREFIX):])] = \
                    json.loads(g["data"])
            except ValueError:
                continue
        return specs

    async def _broadcast(self, mtype: str, **data) -> dict[int, dict]:
        """Send one control/dump message to every up OSD; returns
        osd -> reply data."""
        import asyncio

        osdmap = self.mgr.monc.osdmap
        if osdmap is None:
            return {}
        polls = {
            osd: self.mgr.osd_request(osd, info.addr, mtype, **data)
            for osd, info in osdmap.osds.items() if info.up
        }
        results = await asyncio.gather(*polls.values())
        return {osd: r for osd, r in zip(polls, results)
                if r is not None}

    async def install(self, qid: int, spec: dict) -> None:
        await self._broadcast("perf_query_add", qid=qid, spec=spec)
        self._installed[qid] = spec

    async def remove(self, qid: int) -> None:
        await self._broadcast("perf_query_rm", qid=qid)
        self._installed.pop(qid, None)
        self._results.pop(qid, None)

    async def dump(self, qid: int) -> dict:
        """Merged {group key -> counters} across OSDs."""
        merged: dict[str, dict] = {}
        for reply in (await self._broadcast("perf_query_dump",
                                            qid=qid)).values():
            for key, c in reply.get("counters", {}).items():
                m = merged.setdefault(key, {
                    "ops": 0, "read_ops": 0, "write_ops": 0,
                    "bytes_in": 0, "bytes_out": 0, "lat_sum": 0.0,
                })
                for k in m:
                    m[k] += c.get(k, 0)
        return merged

    async def serve_once(self) -> None:
        want = await self._specs()
        # qids >= RBD_IOSTAT_QID are module-owned (rbd_support), not
        # config-key driven: reconciliation must not uninstall them
        for qid in [q for q in self._installed
                    if q not in want and q < RBD_IOSTAT_QID]:
            await self.remove(qid)
        for qid, spec in want.items():
            if self._installed.get(qid) != spec:
                await self.install(qid, spec)
        for qid in [q for q in self._installed if q < RBD_IOSTAT_QID]:
            self._results[qid] = await self.dump(qid)

    def digest_contrib(self) -> dict:
        return {"osd_perf_query": {
            str(qid): {"spec": self._installed.get(qid, {}),
                       "counters": self._results.get(qid, {})}
            for qid in self._installed
        }}


class RBDSupport(MgrModule):
    """Trash purge schedules + per-image IO stats."""

    name = "rbd_support"

    def __init__(self, mgr, pq: OSDPerfQuery):
        super().__init__(mgr)
        self.pq = pq
        self._rados = None
        self._last_run: dict[str, float] = {}
        self._sched_status: dict[str, dict] = {}
        self._iostat: dict[str, dict] = {}
        self._iostat_prev: dict[str, dict] = {}
        self._iostat_t = 0.0
        self._iostat_installed = False

    async def _client(self):
        from ceph_tpu.client.rados import Rados

        if self._rados is None:
            self._rados = Rados(self.mgr.monc.monmap, self.mgr.conf,
                                name=self.mgr.name)
            await self._rados.connect(timeout=10.0)
        return self._rados

    async def stop(self) -> None:
        if self._rados is not None:
            await self._rados.shutdown()
            self._rados = None

    async def _schedules(self) -> dict[str, dict]:
        r = await self.mgr.monc.command("config-key ls")
        out: dict[str, dict] = {}
        for key in r.get("data", []):
            if not key.startswith(TRASH_SCHED_PREFIX):
                continue
            g = await self.mgr.monc.command("config-key get", key=key)
            if g.get("rc"):
                continue
            try:
                out[key[len(TRASH_SCHED_PREFIX):]] = \
                    json.loads(g["data"])
            except ValueError:
                continue
        return out

    async def _purge_pool(self, pool: str) -> int:
        """Reap every trash entry whose deferment expired (rbd trash
        purge semantics)."""
        from ceph_tpu.services.rbd import RBD, RBDError

        rados = await self._client()
        io = await rados.open_ioctx(pool)
        rbd = RBD(io)
        purged = 0
        now = time.time()
        for entry in await rbd.trash_list():
            if float(entry.get("deferment_end", 0)) > now:
                continue
            try:
                await rbd.trash_remove(entry["id"])
                purged += 1
            except RBDError as e:
                log.dout(5, "trash purge of %s/%s declined: %s",
                         pool, entry["id"], e)
        return purged

    async def _serve_schedules(self) -> None:
        scheds = await self._schedules()
        self._sched_status = {
            p: dict(s) for p, s in self._sched_status.items()
            if p in scheds
        }
        now = time.time()
        for pool, spec in scheds.items():
            interval = float(spec.get("interval", 900))
            last = self._last_run.get(pool, 0.0)
            if now - last < interval:
                continue
            self._last_run[pool] = now
            try:
                purged = await self._purge_pool(pool)
            except (IOError, ConnectionError) as e:
                self._sched_status[pool] = {
                    "interval": interval, "error": str(e),
                    "last_run": now,
                }
                continue
            st = self._sched_status.setdefault(pool, {
                "interval": interval, "purged_total": 0,
            })
            st["interval"] = interval
            st["last_run"] = now
            st["last_purged"] = purged
            st["purged_total"] = st.get("purged_total", 0) + purged

    async def _serve_iostat(self) -> None:
        """Per-image rates from the rbd_image-grouped OSD perf query
        (rbd perf image iostat)."""
        if not self._iostat_installed:
            await self.pq.install(RBD_IOSTAT_QID,
                                  {"type": "rbd_image"})
            self._iostat_installed = True
            self._iostat_t = time.time()
            return
        cur = await self.pq.dump(RBD_IOSTAT_QID)
        now = time.time()
        dt = max(now - self._iostat_t, 1e-6)
        out: dict[str, dict] = {}
        for image, c in cur.items():
            prev = self._iostat_prev.get(image, {})
            dops = c["ops"] - prev.get("ops", 0)
            out[image] = {
                "ops": c["ops"],
                "ops_per_sec": round(dops / dt, 3),
                "read_ops_per_sec": round(
                    (c["read_ops"] - prev.get("read_ops", 0)) / dt, 3),
                "write_ops_per_sec": round(
                    (c["write_ops"] - prev.get("write_ops", 0)) / dt,
                    3),
                "wr_bytes_per_sec": round(
                    (c["bytes_in"] - prev.get("bytes_in", 0)) / dt, 3),
                "rd_bytes_per_sec": round(
                    (c["bytes_out"] - prev.get("bytes_out", 0)) / dt,
                    3),
                "avg_lat_ms": round(
                    (c["lat_sum"] - prev.get("lat_sum", 0.0))
                    / max(dops, 1) * 1e3, 3),
            }
        self._iostat_prev = cur
        self._iostat_t = now
        self._iostat = out

    async def serve_once(self) -> None:
        await self._serve_schedules()
        await self._serve_iostat()

    def digest_contrib(self) -> dict:
        return {"rbd_support": {
            "trash_schedules": self._sched_status,
            "image_iostat": self._iostat,
        }}


class IOStat(MgrModule):
    """Cluster-wide IO rates from successive OSD perf samples."""

    name = "iostat"

    def __init__(self, mgr):
        super().__init__(mgr)
        self._prev: dict | None = None
        self._prev_t = 0.0
        self._rates = {"ops_per_sec": 0.0, "rd_bytes_per_sec": 0.0,
                       "wr_bytes_per_sec": 0.0}

    async def serve_once(self) -> None:
        snap = await self.mgr.collect()
        totals = {"op": 0, "op_in_bytes": 0, "op_out_bytes": 0}
        for counters in snap["osd_perf"].values():
            for k in totals:
                v = counters.get(k, 0)
                totals[k] += (v.get("sum", 0)
                              if isinstance(v, dict) else v)
        now = time.time()
        if self._prev is not None:
            dt = max(now - self._prev_t, 1e-6)
            self._rates = {
                "ops_per_sec": round(
                    (totals["op"] - self._prev["op"]) / dt, 3),
                "wr_bytes_per_sec": round(
                    (totals["op_in_bytes"]
                     - self._prev["op_in_bytes"]) / dt, 3),
                "rd_bytes_per_sec": round(
                    (totals["op_out_bytes"]
                     - self._prev["op_out_bytes"]) / dt, 3),
            }
        self._prev = totals
        self._prev_t = now

    def digest_contrib(self) -> dict:
        return {"iostat": dict(self._rates)}
