"""RBD-lite: block images over RADOS objects.

The reference's librbd v2 on-disk model (src/librbd; ImageCtx.h:70):
``rbd_id.<name>`` maps name -> image id, ``rbd_header.<id>`` carries the
image metadata (managed here by the ``rbd`` object class, the cls_rbd
role), ``rbd_directory`` lists images, and data lives in
``rbd_data.<id>.<objectno:%016x>`` objects of ``2^order`` bytes. IO maps
block extents onto data objects (the io/ImageRequest -> ObjectRequest
pipeline collapsed to direct extent math). Snapshots are tracked in the
header (create/list/remove); object-level COW clones are not implemented
in this round.
"""

from __future__ import annotations

import json
import secrets

from ceph_tpu.client.rados import IoCtx, ObjectOperation, RadosError

DIRECTORY_OID = "rbd_directory"
DEFAULT_ORDER = 22          # 4 MiB objects


class RBDError(IOError):
    pass


class RBD:
    """Image management (librbd rbd_create/rbd_remove/rbd_list)."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx

    async def create(self, name: str, size: int,
                     order: int = DEFAULT_ORDER) -> None:
        if not 12 <= order <= 26:
            raise RBDError(f"order {order} out of range")
        image_id = secrets.token_hex(8)
        id_oid = f"rbd_id.{name}"
        try:
            await self.ioctx.get_xattr(id_oid, "id")
            raise RBDError(f"image {name!r} exists")
        except RadosError as e:
            if e.rc != -2:
                raise
        await self.ioctx.operate(id_oid, ObjectOperation()
                                 .create().set_xattr("id",
                                                     image_id.encode()))
        await self.ioctx.exec(
            f"rbd_header.{image_id}", "rbd", "create",
            json.dumps({
                "size": size, "order": order,
                "object_prefix": f"rbd_data.{image_id}",
            }).encode(),
        )
        await self.ioctx.operate(DIRECTORY_OID, ObjectOperation()
                                 .create()
                                 .omap_set({name: image_id.encode()}))

    async def list(self) -> list[str]:
        try:
            return sorted(await self.ioctx.get_omap(DIRECTORY_OID))
        except RadosError as e:
            if e.rc == -2:
                return []
            raise

    async def remove(self, name: str) -> None:
        img = await self.open(name)
        data_objs = [
            o for o in await self.ioctx.list_objects()
            if o.startswith(img.object_prefix + ".")
        ]
        for oid in data_objs:
            await self.ioctx.remove(oid)
        await self.ioctx.remove(f"rbd_header.{img.image_id}")
        await self.ioctx.remove(f"rbd_id.{name}")
        await self.ioctx.rm_omap_keys(DIRECTORY_OID, [name])

    async def open(self, name: str) -> "Image":
        try:
            image_id = (await self.ioctx.get_xattr(
                f"rbd_id.{name}", "id"
            )).decode()
        except RadosError as e:
            if e.rc == -2:
                raise RBDError(f"no image {name!r}") from e
            raise
        img = Image(self.ioctx, name, image_id)
        await img.refresh()
        return img


class Image:
    """An open image handle (librbd rbd_image_t)."""

    def __init__(self, ioctx: IoCtx, name: str, image_id: str):
        # a PRIVATE io context: the image's snap context (set at refresh)
        # must not clobber the caller's ioctx or other open images
        # (librbd likewise keeps per-image state in ImageCtx)
        self.ioctx = IoCtx(ioctx.rados, ioctx.pool_id, ioctx.pool_name)
        self.name = name
        self.image_id = image_id
        self.size = 0
        self.order = DEFAULT_ORDER
        self.object_prefix = f"rbd_data.{image_id}"
        self.snaps: dict[str, dict] = {}

    @property
    def header_oid(self) -> str:
        return f"rbd_header.{self.image_id}"

    @property
    def obj_size(self) -> int:
        return 1 << self.order

    async def refresh(self) -> None:
        h = json.loads(await self.ioctx.exec(
            self.header_oid, "rbd", "get_header"
        ))
        self.size = h["size"]
        self.order = h["order"]
        self.object_prefix = h["object_prefix"]
        self.snaps = h["snaps"]
        # image writes carry the image's snap context so data objects
        # COW-clone on the first write after each snapshot
        ids = sorted(int(i["id"]) for i in self.snaps.values())
        if ids:
            self.ioctx.set_snap_context(max(ids), ids)

    def stat(self) -> dict:
        return {
            "size": self.size, "order": self.order,
            "object_size": self.obj_size,
            "num_objs": -(-self.size // self.obj_size),
            "id": self.image_id,
        }

    def _data_oid(self, objectno: int) -> str:
        return f"{self.object_prefix}.{objectno:016x}"

    def _extents(self, offset: int, length: int):
        pos = offset
        end = offset + length
        while pos < end:
            objectno = pos // self.obj_size
            obj_off = pos % self.obj_size
            run = min(self.obj_size - obj_off, end - pos)
            yield objectno, obj_off, run
            pos += run

    async def write(self, offset: int, data: bytes) -> None:
        if offset + len(data) > self.size:
            raise RBDError("write past end of image")
        pos = 0
        for objectno, obj_off, run in self._extents(offset, len(data)):
            await self.ioctx.write(
                self._data_oid(objectno), data[pos:pos + run], obj_off
            )
            pos += run

    async def read(self, offset: int, length: int) -> bytes:
        length = max(0, min(length, self.size - offset))
        out = bytearray(length)
        pos = 0
        for objectno, obj_off, run in self._extents(offset, length):
            try:
                frag = await self.ioctx.read(
                    self._data_oid(objectno), run, obj_off
                )
            except RadosError as e:
                if e.rc != -2:
                    raise
                frag = b""          # unwritten object: zeros
            out[pos:pos + len(frag)] = frag
            pos += run
        return bytes(out)

    async def resize(self, new_size: int) -> None:
        await self.ioctx.exec(
            self.header_oid, "rbd", "set_size",
            json.dumps({"size": new_size}).encode(),
        )
        if new_size < self.size:
            first_dead = -(-new_size // self.obj_size)
            last = -(-self.size // self.obj_size)
            for objectno in range(first_dead, last):
                try:
                    await self.ioctx.remove(self._data_oid(objectno))
                except RadosError as e:
                    if e.rc != -2:
                        raise
            boundary = new_size % self.obj_size
            if boundary:
                try:
                    await self.ioctx.truncate(
                        self._data_oid(new_size // self.obj_size), boundary
                    )
                except RadosError as e:
                    if e.rc != -2:
                        raise
        self.size = new_size

    # -- snapshots (self-managed snaps + object COW clones; the librbd
    # snap_create/snap_rollback model over the OSD snapshot machinery) --
    async def snap_create(self, snap_name: str) -> int:
        snapid = await self.ioctx.selfmanaged_snap_create()
        await self.ioctx.exec(
            self.header_oid, "rbd", "snap_add",
            json.dumps({"name": snap_name, "id": snapid}).encode(),
        )
        await self.refresh()
        return snapid

    async def snap_remove(self, snap_name: str) -> None:
        info = self.snaps.get(snap_name)
        if info is None:
            raise RBDError(f"no snap {snap_name!r}")
        await self.ioctx.exec(
            self.header_oid, "rbd", "snap_rm",
            json.dumps({"name": snap_name}).encode(),
        )
        await self.ioctx.selfmanaged_snap_remove(int(info["id"]))
        await self.refresh()

    def snap_list(self) -> list[dict]:
        return [
            {"name": name, **info}
            for name, info in sorted(self.snaps.items())
        ]

    async def read_at_snap(self, snap_name: str, offset: int,
                           length: int) -> bytes:
        """Read the image as of a snapshot (librbd snap_set + read)."""
        info = self.snaps.get(snap_name)
        if info is None:
            raise RBDError(f"no snap {snap_name!r}")
        snap_size = int(info["size"])
        length = max(0, min(length, snap_size - offset))
        out = bytearray(length)
        self.ioctx.snap_set_read(int(info["id"]))
        try:
            pos = 0
            for objectno, obj_off, run in self._extents(offset, length):
                try:
                    frag = await self.ioctx.read(
                        self._data_oid(objectno), run, obj_off
                    )
                except RadosError as e:
                    if e.rc != -2:
                        raise
                    frag = b""
                out[pos:pos + len(frag)] = frag
                pos += run
        finally:
            self.ioctx.snap_set_read(None)
        return bytes(out)

    async def snap_rollback(self, snap_name: str) -> None:
        """Restore the head image to a snapshot's content (librbd
        snap_rollback: copy the snap state over the head)."""
        info = self.snaps.get(snap_name)
        if info is None:
            raise RBDError(f"no snap {snap_name!r}")
        snap_size = int(info["size"])
        if self.size != snap_size:
            await self.resize(snap_size)
        nobjs = -(-snap_size // self.obj_size)
        for objectno in range(nobjs):
            want = min(self.obj_size, snap_size - objectno * self.obj_size)
            frag = await self.read_at_snap(
                snap_name, objectno * self.obj_size, want
            )
            await self.ioctx.operate(
                self._data_oid(objectno),
                ObjectOperation().write_full(frag),
            )
