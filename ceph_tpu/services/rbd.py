"""RBD-lite: block images over RADOS objects.

The reference's librbd v2 on-disk model (src/librbd; ImageCtx.h:70):
``rbd_id.<name>`` maps name -> image id, ``rbd_header.<id>`` carries the
image metadata (managed here by the ``rbd`` object class, the cls_rbd
role), ``rbd_directory`` lists images, and data lives in
``rbd_data.<id>.<objectno:%016x>`` objects of ``2^order`` bytes. IO maps
block extents onto data objects (the io/ImageRequest -> ObjectRequest
pipeline collapsed to direct extent math).

Round-2 feature depth:
- snapshot-based COW clones (librbd clone/flatten, cls_rbd parent
  links): a child reads through to its protected parent snap for
  unwritten extents (clipped to the overlap) and copies the parent
  block up on first write (the io/CopyupRequest role); ``rbd_children``
  tracks clones so unprotect refuses while children exist.
- object map (src/librbd/ObjectMap.h): a per-image existence bitmap in
  ``rbd_object_map.<id>``; reads skip the OSD round-trip for known-
  absent objects, rebuildable by scanning.
- optional write-back cache (client/object_cacher.py, the osdc
  ObjectCacher role) layered ABOVE copyup/object-map dispatch.
"""

from __future__ import annotations

import asyncio
import json
import secrets
import time

from ceph_tpu.client.rados import IoCtx, ObjectOperation, RadosError
from ceph_tpu.services.rbd_journal import (
    EV_RESIZE,
    EV_SNAP_CREATE,
    EV_SNAP_REMOVE,
    EV_SNAP_ROLLBACK,
    EV_WRITE,
    ImageJournal,
    replay_to_image,
)

DIRECTORY_OID = "rbd_directory"
CHILDREN_OID = "rbd_children"
TRASH_OID = "rbd_trash"
NAMESPACES_OID = "rbd_namespaces"   # default-ns omap: name -> meta
DEFAULT_ORDER = 22          # 4 MiB objects


class RBDError(IOError):
    pass


class RBD:
    """Image management (librbd rbd_create/rbd_remove/rbd_list).

    Namespaces (reference src/librbd/api/Namespace.cc): a handle whose
    IoCtx carries a rados namespace (``ioctx.set_namespace``) scopes
    every image object — directory, headers, data — to it, so listings
    and lookups are isolated per namespace and namespace-scoped OSD
    caps (``allow rw pool=p namespace=ns``) fence clients off at the
    OSD.  The namespace registry itself lives in the pool's DEFAULT
    namespace (the rbd_namespace object role)."""

    def __init__(self, ioctx: IoCtx):
        self.ioctx = ioctx

    def _default_io(self) -> IoCtx:
        """A default-namespace view of the same pool (the namespace
        registry must be visible from every namespace handle)."""
        if not self.ioctx.namespace:
            return self.ioctx
        return IoCtx(self.ioctx.rados, self.ioctx.pool_id,
                     self.ioctx.pool_name)

    # -- namespaces (librbd/api/Namespace.cc) ------------------------------
    async def namespace_create(self, name: str) -> None:
        if not name or "/" in name or "\x1d" in name:
            raise RBDError(f"bad namespace name {name!r}")
        io = self._default_io()
        existing = await self.namespace_list()
        if name in existing:
            raise RBDError(f"namespace {name!r} exists")
        await io.operate(NAMESPACES_OID, ObjectOperation()
                         .create()
                         .omap_set({name: json.dumps(
                             {"created_at": time.time()}).encode()}))

    async def namespace_list(self) -> list[str]:
        io = self._default_io()
        try:
            return sorted(await io.get_omap(NAMESPACES_OID))
        except RadosError as e:
            if e.rc == -2:
                return []
            raise

    async def namespace_exists(self, name: str) -> bool:
        return name in await self.namespace_list()

    async def namespace_remove(self, name: str) -> None:
        """Refuse while the namespace still holds images (reference
        Namespace::remove returns -EBUSY)."""
        io = self._default_io()
        if name not in await self.namespace_list():
            raise RBDError(f"no namespace {name!r}")
        ns_io = IoCtx(self.ioctx.rados, self.ioctx.pool_id,
                      self.ioctx.pool_name)
        ns_io.set_namespace(name)
        if await RBD(ns_io).list():
            raise RBDError(f"namespace {name!r} still has images")
        await io.rm_omap_keys(NAMESPACES_OID, [name])

    async def _check_namespace(self) -> None:
        if self.ioctx.namespace and not await self.namespace_exists(
                self.ioctx.namespace):
            raise RBDError(
                f"namespace {self.ioctx.namespace!r} does not exist"
            )

    async def create(self, name: str, size: int,
                     order: int = DEFAULT_ORDER,
                     object_map: bool = True) -> str:
        if not 12 <= order <= 26:
            raise RBDError(f"order {order} out of range")
        await self._check_namespace()
        image_id = secrets.token_hex(8)
        id_oid = f"rbd_id.{name}"
        try:
            await self.ioctx.get_xattr(id_oid, "id")
            raise RBDError(f"image {name!r} exists")
        except RadosError as e:
            if e.rc != -2:
                raise
        await self.ioctx.operate(id_oid, ObjectOperation()
                                 .create().set_xattr("id",
                                                     image_id.encode()))
        await self.ioctx.exec(
            f"rbd_header.{image_id}", "rbd", "create",
            json.dumps({
                "size": size, "order": order,
                "object_prefix": f"rbd_data.{image_id}",
            }).encode(),
        )
        if object_map:
            nbits = -(-size // (1 << order))
            await self.ioctx.operate(
                f"rbd_object_map.{image_id}",
                ObjectOperation().write_full(bytes(-(-nbits // 8))),
            )
        await self.ioctx.operate(DIRECTORY_OID, ObjectOperation()
                                 .create()
                                 .omap_set({name: image_id.encode()}))
        return image_id

    async def clone(self, parent_name: str, snap_name: str,
                    child_name: str, object_map: bool = True,
                    dest: "RBD | None" = None) -> None:
        """Snapshot-based COW clone (librbd rbd_clone): the child starts
        as a read-through view of parent@snap and diverges on write.
        ``dest`` places the child in another pool (cross-pool clone);
        the parent link records the parent's pool so reads route there.
        """
        dest = dest or self
        parent = await self.open(parent_name)
        info = parent.snaps.get(snap_name)
        if info is None:
            raise RBDError(f"no snap {snap_name!r}")
        if not info.get("protected"):
            raise RBDError(
                f"snap {snap_name!r} must be protected before cloning"
            )
        child_id = await dest.create(
            child_name, int(info["size"]), parent.order,
            object_map=object_map,
        )
        await dest.ioctx.exec(
            f"rbd_header.{child_id}", "rbd", "set_parent",
            json.dumps({
                "pool": self.ioctx.pool_name,
                "image_id": parent.image_id,
                "snap_id": int(info["id"]),
                "snap_name": snap_name,
                "overlap": int(info["size"]),
            }).encode(),
        )
        # the registry lives in the PARENT's pool: unprotect checks it
        label = (child_name if dest is self or
                 dest.ioctx.pool_name == self.ioctx.pool_name
                 else f"{dest.ioctx.pool_name}/{child_name}")
        await self.ioctx.operate(CHILDREN_OID, ObjectOperation()
                                 .create().omap_set({
                                     _child_key(parent.image_id,
                                                int(info["id"]),
                                                child_id):
                                     label.encode(),
                                 }))

    async def children(self, parent_name: str,
                       snap_name: str) -> list[str]:
        """Clone names hanging off parent@snap (rbd children)."""
        parent = await self.open(parent_name)
        info = parent.snaps.get(snap_name)
        if info is None:
            raise RBDError(f"no snap {snap_name!r}")
        return await _children_of(self.ioctx, parent.image_id,
                                  int(info["id"]))

    async def list(self) -> list[str]:
        try:
            return sorted(await self.ioctx.get_omap(DIRECTORY_OID))
        except RadosError as e:
            if e.rc == -2:
                return []
            raise

    async def remove(self, name: str) -> None:
        img = await self.open(name)
        try:
            await self.ioctx.get_xattr(f"rbd_header.{img.image_id}",
                                       "group")
            raise RBDError(
                f"image {name!r} belongs to a group; remove it from "
                "the group first"
            )
        except RadosError as e:
            if e.rc != -2:
                raise
        if img.snaps:
            raise RBDError(
                f"image {name!r} has snapshots; remove them first"
            )
        data_objs = [
            o for o in await self.ioctx.list_objects()
            if o.startswith(img.object_prefix + ".")
        ]
        for oid in data_objs:
            await self.ioctx.remove(oid)
        if img.parent is not None:
            # unlink from the registry in the PARENT's pool
            ppool = img.parent.get("pool", self.ioctx.pool_name)
            pio = (self.ioctx if ppool == self.ioctx.pool_name
                   else await self.ioctx.rados.open_ioctx(ppool))
            try:
                await pio.rm_omap_keys(CHILDREN_OID, [
                    _child_key(img.parent["image_id"],
                               int(img.parent["snap_id"]),
                               img.image_id),
                ])
            except RadosError as e:
                if e.rc != -2:
                    raise
        try:
            await self.ioctx.remove(f"rbd_object_map.{img.image_id}")
        except RadosError as e:
            if e.rc != -2:
                raise
        await self.ioctx.remove(f"rbd_header.{img.image_id}")
        await self.ioctx.remove(f"rbd_id.{name}")
        await self.ioctx.rm_omap_keys(DIRECTORY_OID, [name])

    # -- trash (librbd trash_move/restore/remove, cls_rbd trash) -----------
    async def trash_move(self, name: str, delay: float = 0.0) -> str:
        """Move an image to the trash (rbd trash mv): the name is
        freed immediately, the data survives until trash_remove —
        refused before ``delay`` seconds pass (--expires-at role).
        Images with clone children cannot leave the namespace."""
        img = await self.open(name)
        for snap_name, info in img.snaps.items():
            if info.get("protected") and await _children_of(
                    self.ioctx, img.image_id, int(info["id"])):
                raise RBDError(
                    f"image {name!r} has cloned children under "
                    f"snap {snap_name!r}")
        await self.ioctx.operate(TRASH_OID, ObjectOperation()
                                 .create().omap_set({
                                     img.image_id: json.dumps({
                                         "name": name,
                                         "deleted_at": time.time(),
                                         "deferment_end":
                                         time.time() + delay,
                                     }).encode()}))
        await self.ioctx.remove(f"rbd_id.{name}")
        await self.ioctx.rm_omap_keys(DIRECTORY_OID, [name])
        return img.image_id

    async def trash_list(self) -> list[dict]:
        try:
            omap = await self.ioctx.get_omap(TRASH_OID)
        except RadosError as e:
            if e.rc == -2:
                return []
            raise
        return sorted(({"id": k, **json.loads(v)}
                       for k, v in omap.items()),
                      key=lambda e: e["deleted_at"])

    async def _trash_entry(self, image_id: str) -> dict:
        try:
            kv = await self.ioctx.get_omap(TRASH_OID, [image_id])
        except RadosError as e:
            if e.rc == -2:
                kv = {}
            else:
                raise
        if image_id not in kv:
            raise RBDError(f"no trashed image {image_id!r}")
        return json.loads(kv[image_id])

    async def trash_restore(self, image_id: str,
                            new_name: str | None = None) -> str:
        """Bring a trashed image back (rbd trash restore), under its
        old name or a new one."""
        ent = await self._trash_entry(image_id)
        name = new_name or str(ent["name"])
        try:
            await self.ioctx.get_xattr(f"rbd_id.{name}", "id")
            raise RBDError(f"image {name!r} exists")
        except RadosError as e:
            if e.rc != -2:
                raise
        await self.ioctx.operate(
            f"rbd_id.{name}", ObjectOperation().create()
            .set_xattr("id", image_id.encode()))
        await self.ioctx.operate(DIRECTORY_OID, ObjectOperation()
                                 .create()
                                 .omap_set({name:
                                            image_id.encode()}))
        await self.ioctx.rm_omap_keys(TRASH_OID, [image_id])
        return name

    async def trash_remove(self, image_id: str,
                           force: bool = False) -> None:
        """Purge a trashed image's data for good; refused while the
        deferment window holds (unless forced).  The purge works on
        the image id directly — the image NEVER reappears in the live
        namespace, and a failure partway leaves it listed in the
        trash (header ops are name-independent, and the trash entry
        is removed last)."""
        ent = await self._trash_entry(image_id)
        if not force and time.time() < float(ent["deferment_end"]):
            raise RBDError(
                f"deferment expires in "
                f"{float(ent['deferment_end']) - time.time():.0f}s "
                f"(use force)")
        img = Image(self.ioctx, f"<trash:{image_id}>", image_id)
        await img.refresh()
        for snap_name in list(img.snaps):
            if img.snaps[snap_name].get("protected"):
                await img.snap_unprotect(snap_name)
            await img.snap_remove(snap_name)
        for oid in [o for o in await self.ioctx.list_objects()
                    if o.startswith(img.object_prefix + ".")]:
            await self.ioctx.remove(oid)
        if img.parent is not None:
            ppool = img.parent.get("pool", self.ioctx.pool_name)
            pio = (self.ioctx if ppool == self.ioctx.pool_name
                   else await self.ioctx.rados.open_ioctx(ppool))
            try:
                await pio.rm_omap_keys(CHILDREN_OID, [
                    _child_key(img.parent["image_id"],
                               int(img.parent["snap_id"]),
                               image_id),
                ])
            except RadosError as e:
                if e.rc != -2:
                    raise
        try:
            await self.ioctx.remove(f"rbd_object_map.{image_id}")
        except RadosError as e:
            if e.rc != -2:
                raise
        await self.ioctx.remove(img.header_oid)
        await self.ioctx.rm_omap_keys(TRASH_OID, [image_id])

    async def deep_copy(self, src_name: str, dst_name: str,
                        dest: "RBD | None" = None) -> None:
        """Full image copy INCLUDING the snapshot history (librbd
        deep-copy, src/librbd/deep_copy/): each source snapshot is
        replayed onto the destination in id order — copy the data as
        of the snap, snapshot the destination — then the head follows.
        The result is standalone (parent links are flattened away) and
        sparse regions stay sparse (all-zero object-size chunks are
        skipped)."""
        dest = dest or self
        src = await self.open(src_name)
        try:
            await dest.create(dst_name, src.size, src.order,
                              object_map=src._om is not None)
            dst = await dest.open(dst_name)
            zero = bytes(src.obj_size)
            import hashlib

            # objectno -> digest of the dst content as of the LAST
            # copied state: unchanged objects are skipped, so each
            # state writes only its delta (reference deep_copy's
            # snap-delta behavior) instead of re-COWing everything
            state: dict[int, bytes] = {}

            async def copy_state(size: int, reader) -> None:
                if dst.size != size:
                    await dst.resize(size)
                    nobj = -(-size // src.obj_size)
                    for k in [k for k in state if k >= nobj]:
                        del state[k]
                for objectno in range(-(-size // src.obj_size)):
                    off = objectno * src.obj_size
                    chunk = await reader(off,
                                         min(src.obj_size,
                                             size - off))
                    if not chunk or chunk == zero[:len(chunk)]:
                        if objectno in state:
                            # zeroed since an earlier state: the dst
                            # must not carry the stale bytes forward
                            # (COW keeps them in the prior snap)
                            await dst.write(off, zero[:len(chunk)])
                            del state[objectno]
                        continue
                    digest = hashlib.md5(chunk).digest()
                    if state.get(objectno) == digest:
                        continue            # unchanged since last state
                    await dst.write(off, chunk)
                    state[objectno] = digest

            for snap_name, info in sorted(
                    src.snaps.items(), key=lambda kv: int(kv[1]["id"])):
                await copy_state(
                    int(info["size"]),
                    lambda off, ln, s=snap_name:
                        src.read_at_snap(s, off, ln))
                await dst.snap_create(snap_name)
                if info.get("protected"):
                    await dst.snap_protect(snap_name)
            await copy_state(src.size, src.read)
            await dst.close()
        finally:
            await src.close()

    async def migrate(self, src_name: str, dst_name: str,
                      dest: "RBD | None" = None) -> None:
        """Move an image (rbd migration prepare/execute/commit run
        back to back, without the live-IO window): deep-copy, verify
        the destination opens, then remove the source — snapshots must
        be unprotected first, as for any remove."""
        dest = dest or self
        src = await self.open(src_name)
        protected = [n for n, i in src.snaps.items()
                     if i.get("protected")]
        await src.close()
        if protected:
            raise RBDError(
                f"unprotect snaps {protected} before migrating "
                f"(clones would lose their parent)")
        await self.deep_copy(src_name, dst_name, dest=dest)
        await (await dest.open(dst_name)).close()   # sanity
        img = await self.open(src_name)
        for snap_name in list(img.snaps):
            await img.snap_remove(snap_name)
        await img.close()
        await self.remove(src_name)

    async def image_id(self, name: str) -> str:
        """name -> image id (the rbd_id.<name> lookup); RBDError when
        absent.  Needs no open Image handle (journal-mode mirroring
        reads a dead primary's journal by id alone)."""
        try:
            return (await self.ioctx.get_xattr(
                f"rbd_id.{name}", "id"
            )).decode()
        except RadosError as e:
            if e.rc == -2:
                raise RBDError(f"no image {name!r}") from e
            raise

    async def image_header(self, image_id: str) -> dict:
        """Decoded rbd_header metadata for an image id."""
        return json.loads(await self.ioctx.exec(
            f"rbd_header.{image_id}", "rbd", "get_header"
        ))

    async def open(self, name: str, cache: bool = False,
                   journaled: bool = False,
                   exclusive: bool = False,
                   lock_duration: float = 30.0) -> "Image":
        """``journaled``: mutations append to the image journal before
        applying (librbd feature JOURNALING), and opening replays any
        entries a crashed writer appended but never applied.
        ``exclusive``: single-writer coordination (EXCLUSIVE_LOCK
        feature) — the first mutation acquires the image lock,
        contenders request a cooperative handoff, and a dead owner's
        lease expires after ``lock_duration``."""
        image_id = await self.image_id(name)
        img = Image(self.ioctx, name, image_id, cache=cache,
                    exclusive=exclusive, lock_duration=lock_duration)
        await img.refresh()
        if journaled:
            img._journal = ImageJournal(self.ioctx, image_id)
            await img._journal.register()
            await replay_to_image(img, img._journal)
        return img


def _child_key(parent_id: str, snap_id: int, child_id: str) -> str:
    return f"{parent_id}@{snap_id}/{child_id}"


async def _children_of(ioctx: IoCtx, parent_id: str,
                       snap_id: int) -> list[str]:
    """Clone names registered under parent@snap in rbd_children."""
    prefix = _child_key(parent_id, snap_id, "")
    try:
        omap = await ioctx.get_omap(CHILDREN_OID)
    except RadosError as e:
        if e.rc == -2:
            return []
        raise
    return sorted(v.decode() for k, v in omap.items()
                  if k.startswith(prefix))


class Image:
    """An open image handle (librbd rbd_image_t)."""

    def __init__(self, ioctx: IoCtx, name: str, image_id: str,
                 cache: bool = False, exclusive: bool = False,
                 lock_duration: float = 30.0):
        # a PRIVATE io context: the image's snap context (set at refresh)
        # must not clobber the caller's ioctx or other open images
        # (librbd likewise keeps per-image state in ImageCtx)
        self.ioctx = IoCtx(ioctx.rados, ioctx.pool_id, ioctx.pool_name)
        self.ioctx.set_namespace(ioctx.namespace)
        self.name = name
        self.image_id = image_id
        self.size = 0
        self.order = DEFAULT_ORDER
        self.object_prefix = f"rbd_data.{image_id}"
        self.snaps: dict[str, dict] = {}
        self.parent: dict | None = None
        self._parent_img: "Image | None" = None
        self._om: bytearray | None = None      # object map bitmap
        # The map's ABSENT answer is only trustworthy for the handle
        # that maintains it (the reference gates the object map behind
        # the exclusive lock; a non-owner's copy can go stale the moment
        # another client writes).  A handle becomes authoritative once
        # it mutates the map itself (write/rebuild).
        self._om_auth = False
        self._cache = None
        # image journal (librbd Journal.cc): set by RBD.open(journaled=)
        self._journal = None
        self._j_last = -1           # newest appended-and-applied tid
        self._j_uncommitted = 0
        # exclusive lock (librbd ExclusiveLock.cc / ManagedLock.cc
        # over cls_lock): single-writer coordination on the header.
        # -lite fencing is the LEASE — the owner renews at D/3 and
        # refuses local writes once its lease lapses, so a paused
        # owner cannot race whoever acquired after expiry (the
        # reference fences harder, via osd blocklisting).
        self._excl = exclusive
        self._lock_duration = lock_duration
        # instance id first so `rbd lock break --blocklist` can
        # fence the owner: "entity:nonce@img.<id>.<uniq>"
        self._locker_id = (f"{ioctx.rados.instance_id}@"
                           f"img.{image_id}.{secrets.token_hex(4)}")
        self._lock_owner = False
        self._lock_until = 0.0            # monotonic lease horizon
        self._lock_renew_task = None
        self._lock_watch = None
        self._releasing = False
        if cache:
            from ceph_tpu.client.object_cacher import ObjectCacher

            self._cache = ObjectCacher(self._cache_fetch,
                                       self._cache_writeback)

    @property
    def header_oid(self) -> str:
        return f"rbd_header.{self.image_id}"

    @property
    def obj_size(self) -> int:
        return 1 << self.order

    async def refresh(self) -> None:
        h = json.loads(await self.ioctx.exec(
            self.header_oid, "rbd", "get_header"
        ))
        self.size = h["size"]
        self.order = h["order"]
        self.object_prefix = h["object_prefix"]
        self.snaps = h["snaps"]
        self.parent = h.get("parent") or None
        self._parent_img = None
        # image writes carry the image's snap context so data objects
        # COW-clone on the first write after each snapshot
        ids = sorted(int(i["id"]) for i in self.snaps.values())
        if ids:
            self.ioctx.set_snap_context(max(ids), ids)
        try:
            self._om = bytearray(await self.ioctx.read(self._om_oid))
        except RadosError as e:
            if e.rc != -2:
                raise
            self._om = None         # object-map feature off

    async def close(self) -> None:
        if self._cache is not None:
            await self._cache.flush()
        await self._j_commit()
        if self._journal is not None:
            await self._journal.trim()
        if self._lock_renew_task is not None:
            self._lock_renew_task.cancel()
            self._lock_renew_task = None
        if self._lock_owner:
            await self.release_exclusive_lock()
        if self._lock_watch is not None:
            await self.ioctx.unwatch(self._lock_watch)
            self._lock_watch = None

    # -- object map (src/librbd/ObjectMap.h bitmap) -----------------------
    @property
    def _om_oid(self) -> str:
        return f"rbd_object_map.{self.image_id}"

    def _om_test(self, objectno: int) -> bool:
        """True = object may exist; False = definitely absent."""
        if self._om is None:
            return True
        byte = objectno >> 3
        if byte >= len(self._om):
            return False
        return bool(self._om[byte] & (1 << (objectno & 7)))

    async def _om_set(self, objectno: int) -> None:
        if self._om is None:
            return
        self._om_auth = True
        if self._om_test(objectno):
            return
        byte = objectno >> 3
        if byte >= len(self._om):
            self._om.extend(bytes(byte + 1 - len(self._om)))
        self._om[byte] |= 1 << (objectno & 7)
        # Persisted BEFORE the data write lands (may-exist is safe;
        # definitely-absent with data present would corrupt reads).
        # The merge happens SERVER-SIDE in one atomic class op
        # (cls bitmap.or), so two writer handles can never lose each
        # other's bits to a read-modify-write race; the reply is the
        # merged map, refreshing our view for free.
        import base64 as _b64

        merged = await self.ioctx.exec(
            self._om_oid, "bitmap", "or",
            json.dumps({
                "bits_b64": _b64.b64encode(bytes(self._om)).decode(),
            }).encode(),
        )
        self._om = bytearray(_b64.b64decode(merged))

    async def object_map_rebuild(self) -> None:
        """Rescan data objects into a fresh bitmap (rbd object-map
        rebuild)."""
        nobjs = -(-self.size // self.obj_size)
        om = bytearray(-(-nobjs // 8) or 1)
        for objectno in range(nobjs):
            try:
                await self.ioctx.stat(self._data_oid(objectno))
            except RadosError as e:
                if e.rc != -2:
                    raise
                continue
            om[objectno >> 3] |= 1 << (objectno & 7)
        self._om = om
        self._om_auth = True
        await self.ioctx.operate(
            self._om_oid, ObjectOperation().write_full(bytes(om))
        )

    # -- parent COW (librbd clone read-through + CopyupRequest) -----------
    async def _parent_image(self) -> "Image | None":
        if self.parent is None:
            return None
        if self._parent_img is None:
            pool = self.parent["pool"]
            pio = (self.ioctx if pool == self.ioctx.pool_name
                   else await self.ioctx.rados.open_ioctx(pool))
            img = Image(pio, "", self.parent["image_id"])
            await img.refresh()
            self._parent_img = img
        return self._parent_img

    async def _parent_range(self, img_off: int, want: int) -> bytes:
        """Parent bytes for [img_off, img_off+want), clipped to the
        overlap; shorter/empty result means zeros."""
        if self.parent is None or img_off >= self.parent["overlap"]:
            return b""
        want = min(want, self.parent["overlap"] - img_off)
        parent = await self._parent_image()
        return await parent._read_extents(
            img_off, want, snapid=int(self.parent["snap_id"])
        )

    def stat(self) -> dict:
        return {
            "size": self.size, "order": self.order,
            "object_size": self.obj_size,
            "num_objs": -(-self.size // self.obj_size),
            "id": self.image_id,
        }

    def _data_oid(self, objectno: int) -> str:
        return f"{self.object_prefix}.{objectno:016x}"

    def _extents(self, offset: int, length: int):
        pos = offset
        end = offset + length
        while pos < end:
            objectno = pos // self.obj_size
            obj_off = pos % self.obj_size
            run = min(self.obj_size - obj_off, end - pos)
            yield objectno, obj_off, run
            pos += run

    # -- object IO dispatch (the io/ObjectRequest layer: object map ->
    # parent COW -> OSD; the optional cache sits above all of it) ---------
    async def _obj_exists(self, objectno: int) -> bool:
        if self._om is not None and self._om_auth:
            return self._om_test(objectno)
        try:
            await self.ioctx.stat(self._data_oid(objectno))
            return True
        except RadosError as e:
            if e.rc != -2:
                raise
            return False

    async def _obj_read_direct(self, objectno: int, obj_off: int,
                               run: int, snapid: int | None = None
                               ) -> bytes:
        """One object's bytes with parent fallback; short = zeros."""
        frag = None
        if snapid is None and self._om is not None and self._om_auth \
                and not self._om_test(objectno):
            frag = b""              # known-absent: skip the round trip
        else:
            if snapid is not None:
                self.ioctx.snap_set_read(snapid)
            try:
                frag = await self.ioctx.read(
                    self._data_oid(objectno), run, obj_off
                )
                return frag
            except RadosError as e:
                if e.rc != -2:
                    raise
                frag = b""
            finally:
                if snapid is not None:
                    self.ioctx.snap_set_read(None)
        # absent from this image: a clone reads through to the parent
        if self.parent is not None:
            return await self._parent_range(
                objectno * self.obj_size + obj_off, run
            )
        return frag

    async def _obj_write(self, objectno: int, obj_off: int,
                         data: bytes) -> None:
        oid = self._data_oid(objectno)
        if self.parent is not None and \
                not await self._obj_exists(objectno):
            # copyup (io/CopyupRequest): materialize the parent block in
            # the child before the first write so reads never see a
            # half-diverged object
            base = bytearray(
                await self._parent_range(objectno * self.obj_size,
                                         self.obj_size)
            )
            end = obj_off + len(data)
            if len(base) < end:
                base.extend(bytes(end - len(base)))
            base[obj_off:end] = data
            await self._om_set(objectno)
            await self.ioctx.operate(
                oid, ObjectOperation().write_full(bytes(base))
            )
            return
        await self._om_set(objectno)
        await self.ioctx.write(oid, data, obj_off)

    # cache plumbing: fetch/writeback close over the dispatch above
    async def _cache_fetch(self, objectno: int) -> bytes:
        return await self._obj_read_direct(objectno, 0, self.obj_size)

    async def _cache_writeback(self, objectno: int,
                               data: bytes) -> None:
        await self._om_set(objectno)
        await self.ioctx.operate(
            self._data_oid(objectno),
            ObjectOperation().write_full(data),
        )

    async def _read_extents(self, offset: int, length: int,
                            snapid: int | None = None) -> bytes:
        out = bytearray(length)
        pos = 0
        for objectno, obj_off, run in self._extents(offset, length):
            if snapid is None and self._cache is not None:
                frag = await self._cache.read(objectno, obj_off, run)
            else:
                frag = await self._obj_read_direct(objectno, obj_off,
                                                   run, snapid)
            out[pos:pos + len(frag)] = frag
            pos += run
        return bytes(out)

    _COMMIT_BATCH = 16      # journal commit-position update cadence

    async def _j_append(self, event: int, args: dict) -> None:
        """Journal-first mutation ordering: the entry is durable before
        the image changes (the write is acked at journal-safe; a crash
        in between is covered by open-time replay)."""
        self._j_last = await self._journal.append(event, args)

    async def _j_applied(self) -> None:
        """Lazily advance the commit position (batched like the
        reference's commit interval, flushed on flush/close)."""
        self._j_uncommitted += 1
        if self._j_uncommitted >= self._COMMIT_BATCH:
            await self._j_commit()

    async def _j_commit(self) -> None:
        if self._journal is not None and self._j_uncommitted:
            if self._cache is not None:
                # an entry is only "applied" once its data is durable:
                # committing past writes still in the volatile cache
                # would make replay skip exactly the crash window the
                # journal exists to cover
                await self._cache.flush()
            await self._journal.commit(self._j_last)
            self._j_uncommitted = 0

    # -- image metadata (librbd metadata_set/get/list, cls_rbd) ------------
    _META_PREFIX = "meta."

    async def meta_set(self, key: str, value: str) -> None:
        """rbd image-meta set: free-form key/value on the header
        (the conf_* override namespace included)."""
        if not key:
            raise RBDError("empty metadata key")
        await self.ioctx.set_omap(
            self.header_oid,
            {self._META_PREFIX + key: str(value).encode()})

    async def meta_get(self, key: str) -> str:
        kv = await self.ioctx.get_omap(self.header_oid,
                                       [self._META_PREFIX + key])
        if self._META_PREFIX + key not in kv:
            raise RBDError(f"no metadata key {key!r}")
        return kv[self._META_PREFIX + key].decode()

    async def meta_list(self) -> dict[str, str]:
        omap = await self.ioctx.get_omap(self.header_oid)
        return {k[len(self._META_PREFIX):]: v.decode()
                for k, v in sorted(omap.items())
                if k.startswith(self._META_PREFIX)}

    async def meta_remove(self, key: str) -> None:
        kv = await self.ioctx.get_omap(self.header_oid,
                                       [self._META_PREFIX + key])
        if self._META_PREFIX + key not in kv:
            raise RBDError(f"no metadata key {key!r}")
        await self.ioctx.rm_omap_keys(self.header_oid,
                                      [self._META_PREFIX + key])

    # -- exclusive lock (ExclusiveLock.cc over cls_lock) -------------------
    RBD_LOCK_NAME = "rbd_lock"

    async def lock_info(self) -> dict:
        return json.loads(await self.ioctx.exec(
            self.header_oid, "lock", "get_info", b"{}"))

    async def _lock_try(self) -> bool:
        try:
            await self.ioctx.exec(
                self.header_oid, "lock", "lock",
                json.dumps({"name": self.RBD_LOCK_NAME,
                             "locker": self._locker_id,
                             "type": "exclusive",
                             "duration": self._lock_duration}).encode())
            return True
        except RadosError as e:
            if e.rc == -16:
                return False
            raise

    async def acquire_exclusive_lock(self,
                                     timeout: float = 10.0) -> None:
        """Become the image's single writer.  A live owner is asked to
        release (cooperative transition via a header notify); a dead
        owner's lease simply expires."""
        if self._lock_owner and time.monotonic() < self._lock_until:
            return
        deadline = time.monotonic() + timeout
        while True:
            before = time.monotonic()
            if await self._lock_try():
                self._lock_owner = True
                self._lock_until = before + self._lock_duration
                self._releasing = False
                if self._lock_watch is None:
                    self._lock_watch = await self.ioctx.watch(
                        self.header_oid, self._lock_notify)
                if self._lock_renew_task is None:
                    self._lock_renew_task = asyncio.create_task(
                        self._lock_renew_loop())
                # the image may have changed hands while we were not
                # the owner: adopt the current header — especially the
                # snap context, or our next write would overwrite a
                # snapshot another owner just took instead of COWing
                # (librbd refreshes after the lock acquires too)
                await self.refresh()
                return
            try:
                await self.ioctx.notify(
                    self.header_oid,
                    json.dumps({"op": "request_lock"}).encode(),
                    timeout=2.0)
            except RadosError:
                pass
            if time.monotonic() > deadline:
                info = await self.lock_info()
                raise RBDError(
                    f"image {self.name!r} is exclusively locked by "
                    f"{sorted(info.get('lockers', {}))}")
            await asyncio.sleep(0.1)

    async def _lock_notify(self, payload: bytes) -> bytes | None:
        try:
            msg = json.loads(payload or b"{}")
        except ValueError:
            return None
        if msg.get("op") == "request_lock" and self._lock_owner \
                and not self._releasing:
            # hand off at a quiescent point, not mid-notify-callback
            self._releasing = True
            asyncio.get_running_loop().create_task(
                self.release_exclusive_lock())
        return b"ack"

    async def _lock_renew_loop(self) -> None:
        while True:
            await asyncio.sleep(self._lock_duration / 3)
            if not self._lock_owner:
                continue
            before = time.monotonic()
            try:
                renewed = await self._lock_try()
            except RadosError:
                continue      # transient (PG unavailable): next tick
            if renewed:
                self._lock_until = before + self._lock_duration
            else:
                # lease lapsed and someone else owns the image now
                await self._fence_lost_lock()

    async def release_exclusive_lock(self) -> None:
        """Flush and give the lock up (the cooperative handoff)."""
        if not self._lock_owner:
            self._releasing = False
            return
        await self.flush()
        self._lock_owner = False
        self._releasing = False
        try:
            await self.ioctx.exec(
                self.header_oid, "lock", "unlock",
                json.dumps({"locker": self._locker_id}).encode())
        except RadosError:
            pass                 # already expired / broken: same end

    async def break_lock(self, locker: str,
                         blocklist: bool = False) -> None:
        """Force-remove another client's lock (rbd lock break): for
        owners that died without a lease (or an operator who cannot
        wait one out).  ``blocklist`` additionally fences the former
        owner's client instance at the OSDs FIRST — the reference's
        default for break: without it, the dead owner's in-flight
        writes can land after the new owner takes over.  The locker
        cookie carries the instance id ("entity:nonce") when the
        lock was taken by this stack's acquire_exclusive_lock."""
        if blocklist:
            if "@" not in locker:
                # nothing to fence: blocklisting the raw cookie would
                # report success while the dead owner's in-flight
                # writes still land — the exact window the flag
                # exists to close
                raise RBDError(
                    f"locker {locker!r} carries no instance id; "
                    f"break without --blocklist or fence manually")
            ent = locker.split("@", 1)[0]
            try:
                r = await self.ioctx.rados.mon_command(
                    "osd blocklist", action="add", entity=ent)
                if r.get("rc") != 0:
                    raise RBDError(
                        f"blocklist of {ent!r} refused: {r}")
            except RadosError as e:
                raise RBDError(f"blocklist of {ent!r} failed: "
                               f"{e}") from e
        try:
            await self.ioctx.exec(
                self.header_oid, "lock", "unlock",
                json.dumps({"locker": locker}).encode())
        except RadosError as e:
            if e.rc != -2:
                raise

    async def _fence_lost_lock(self) -> None:
        """The lease lapsed while we may hold dirty state: DISCARD the
        write-back cache rather than let a later flush overwrite
        whatever the next owner wrote in between (the reference fences
        via osd blocklisting; -lite drops the stale dirty blocks)."""
        self._lock_owner = False
        if self._cache is not None:
            for key in list(self._cache._objects):
                await self._cache.discard(key)
        self._om_auth = False      # the map may be stale too

    async def _ensure_lock(self) -> None:
        if not self._excl:
            return
        if not self._lock_owner:
            await self.acquire_exclusive_lock()
        elif time.monotonic() >= self._lock_until:
            await self._fence_lost_lock()
            await self.acquire_exclusive_lock()

    async def write(self, offset: int, data: bytes,
                    _journal: bool = True) -> None:
        await self._ensure_lock()
        if offset + len(data) > self.size:
            raise RBDError("write past end of image")
        if self._journal is not None and _journal:
            await self._j_append(EV_WRITE, {"off": offset, "data": data})
        pos = 0
        for objectno, obj_off, run in self._extents(offset, len(data)):
            chunk = data[pos:pos + run]
            if self._cache is not None:
                await self._cache.write(objectno, obj_off, chunk)
            else:
                await self._obj_write(objectno, obj_off, chunk)
            pos += run
        if self._journal is not None and _journal:
            await self._j_applied()

    async def read(self, offset: int, length: int) -> bytes:
        length = max(0, min(length, self.size - offset))
        return await self._read_extents(offset, length)

    async def flush(self) -> None:
        if self._cache is not None:
            await self._cache.flush()
        await self._j_commit()

    async def flatten(self) -> None:
        """Copy every still-inherited parent block into the child and
        sever the parent link (librbd flatten)."""
        await self._ensure_lock()
        if self.parent is None:
            raise RBDError("image has no parent")
        if self._cache is not None:
            await self._cache.flush()
        nobjs = -(-self.size // self.obj_size)
        for objectno in range(nobjs):
            if await self._obj_exists(objectno):
                continue
            block = await self._parent_range(
                objectno * self.obj_size, self.obj_size
            )
            if not block.rstrip(b"\x00"):
                continue            # all-zero: absent reads the same
            await self._om_set(objectno)
            await self.ioctx.operate(
                self._data_oid(objectno),
                ObjectOperation().write_full(block),
            )
        await self.ioctx.exec(self.header_oid, "rbd", "remove_parent",
                              b"{}")
        ppool = self.parent.get("pool", self.ioctx.pool_name)
        pio = (self.ioctx if ppool == self.ioctx.pool_name
               else await self.ioctx.rados.open_ioctx(ppool))
        try:
            await pio.rm_omap_keys(CHILDREN_OID, [
                _child_key(self.parent["image_id"],
                           int(self.parent["snap_id"]), self.image_id),
            ])
        except RadosError as e:
            if e.rc != -2:
                raise
        self.parent = None
        self._parent_img = None
        # cached blocks that hold parent-fallback data remain
        # byte-correct after the flatten copied those bytes up

    async def resize(self, new_size: int, _journal: bool = True) -> None:
        await self._ensure_lock()
        if self._cache is not None:
            await self._cache.flush()
        if self._journal is not None and _journal:
            await self._j_append(EV_RESIZE, {"size": new_size})
        await self.ioctx.exec(
            self.header_oid, "rbd", "set_size",
            json.dumps({"size": new_size}).encode(),
        )
        if new_size < self.size:
            first_dead = -(-new_size // self.obj_size)
            last = -(-self.size // self.obj_size)
            for objectno in range(first_dead, last):
                try:
                    await self.ioctx.remove(self._data_oid(objectno))
                except RadosError as e:
                    if e.rc != -2:
                        raise
                if self._om is not None \
                        and objectno >> 3 < len(self._om):
                    self._om[objectno >> 3] &= ~(1 << (objectno & 7))
                if self._cache is not None:
                    await self._cache.discard(objectno)
            boundary = new_size % self.obj_size
            if boundary:
                try:
                    await self.ioctx.truncate(
                        self._data_oid(new_size // self.obj_size), boundary
                    )
                except RadosError as e:
                    if e.rc != -2:
                        raise
                if self._cache is not None:
                    await self._cache.discard(new_size // self.obj_size)
            if self._om is not None:
                await self.ioctx.operate(
                    self._om_oid,
                    ObjectOperation().write_full(bytes(self._om)),
                )
            # a shrunk clone inherits less of its parent — persisted,
            # or a reopen/regrow would resurrect truncated parent data
            if self.parent is not None \
                    and self.parent["overlap"] > new_size:
                await self.ioctx.exec(
                    self.header_oid, "rbd", "set_parent_overlap",
                    json.dumps({"overlap": new_size}).encode(),
                )
                self.parent["overlap"] = new_size
        self.size = new_size
        if self._journal is not None and _journal:
            await self._j_applied()

    # -- snapshots (self-managed snaps + object COW clones; the librbd
    # snap_create/snap_rollback model over the OSD snapshot machinery) --
    async def snap_create(self, snap_name: str,
                          _journal: bool = True) -> int:
        await self._ensure_lock()
        if self._cache is not None:
            # the snapshot must capture every acked write (librbd
            # flushes its cache before snap_create)
            await self._cache.flush()
        if self._journal is not None and _journal:
            await self._j_append(EV_SNAP_CREATE, {"name": snap_name})
        snapid = await self.ioctx.selfmanaged_snap_create()
        await self.ioctx.exec(
            self.header_oid, "rbd", "snap_add",
            json.dumps({"name": snap_name, "id": snapid}).encode(),
        )
        await self.refresh()
        if self._journal is not None and _journal:
            await self._j_applied()
        return snapid

    async def snap_protect(self, snap_name: str) -> None:
        """Required before cloning (librbd snap_protect)."""
        if snap_name not in self.snaps:
            raise RBDError(f"no snap {snap_name!r}")
        await self.ioctx.exec(
            self.header_oid, "rbd", "snap_protect",
            json.dumps({"name": snap_name}).encode(),
        )
        await self.refresh()

    async def snap_unprotect(self, snap_name: str) -> None:
        """Refuses while clones exist (the reference walks every pool's
        rbd_children; ours is pool-local)."""
        info = self.snaps.get(snap_name)
        if info is None:
            raise RBDError(f"no snap {snap_name!r}")
        kids = await _children_of(self.ioctx, self.image_id,
                                  int(info["id"]))
        if kids:
            raise RBDError(
                f"snap {snap_name!r} has children: {kids}"
            )
        await self.ioctx.exec(
            self.header_oid, "rbd", "snap_unprotect",
            json.dumps({"name": snap_name}).encode(),
        )
        await self.refresh()

    async def snap_remove(self, snap_name: str,
                          _journal: bool = True) -> None:
        await self._ensure_lock()
        info = self.snaps.get(snap_name)
        if info is None:
            raise RBDError(f"no snap {snap_name!r}")
        if self._journal is not None and _journal:
            await self._j_append(EV_SNAP_REMOVE, {"name": snap_name})
        await self.ioctx.exec(
            self.header_oid, "rbd", "snap_rm",
            json.dumps({"name": snap_name}).encode(),
        )
        await self.ioctx.selfmanaged_snap_remove(int(info["id"]))
        await self.refresh()
        if self._journal is not None and _journal:
            await self._j_applied()

    def snap_list(self) -> list[dict]:
        return [
            {"name": name, **info}
            for name, info in sorted(self.snaps.items())
        ]

    async def read_at_snap(self, snap_name: str, offset: int,
                           length: int) -> bytes:
        """Read the image as of a snapshot (librbd snap_set + read).
        Clone objects not yet copied up at snap time read through to
        the parent, like head reads."""
        info = self.snaps.get(snap_name)
        if info is None:
            raise RBDError(f"no snap {snap_name!r}")
        if self._cache is not None:
            await self._cache.flush()
        snap_size = int(info["size"])
        length = max(0, min(length, snap_size - offset))
        return await self._read_extents(offset, length,
                                        snapid=int(info["id"]))

    async def snap_rollback(self, snap_name: str,
                            _journal: bool = True) -> None:
        """Restore the head image to a snapshot's content (librbd
        snap_rollback: copy the snap state over the head)."""
        await self._ensure_lock()
        info = self.snaps.get(snap_name)
        if info is None:
            raise RBDError(f"no snap {snap_name!r}")
        if self._journal is not None and _journal:
            await self._j_append(EV_SNAP_ROLLBACK, {"name": snap_name})
        snap_size = int(info["size"])
        if self.size != snap_size:
            await self.resize(snap_size, _journal=False)
        nobjs = -(-snap_size // self.obj_size)
        for objectno in range(nobjs):
            want = min(self.obj_size, snap_size - objectno * self.obj_size)
            frag = await self.read_at_snap(
                snap_name, objectno * self.obj_size, want
            )
            await self._om_set(objectno)
            await self.ioctx.operate(
                self._data_oid(objectno),
                ObjectOperation().write_full(frag),
            )
            if self._cache is not None:
                await self._cache.discard(objectno)
        if self._journal is not None and _journal:
            await self._j_applied()
