"""RBD consistency groups (reference src/librbd/api/Group.cc).

A group names a set of images whose snapshots must be MUTUALLY
consistent: ``group snap create`` quiesces every member (exclusive
lock on each, taken in sorted order so two concurrent group snaps
cannot deadlock), snapshots them all at that frozen point, then
releases.  On-disk model mirrors cls_rbd's group support:

- ``rbd_group_directory``      omap: group name -> group id
- ``rbd_group_header.<id>``    omap: ``image.<image_id>`` member
  records and ``snap.<snap_id>`` group-snapshot records
- each member's ``rbd_header.<image_id>`` carries a ``group`` xattr
  (one group per image — cls_rbd image_group_add semantics); image
  removal refuses while it is set.

Group snapshots are written as a PENDING record first and flipped to
``complete`` only after every member snap exists, so a crash mid-snap
leaves an identifiable partial record (``group snap list`` shows its
state; remove cleans it up) — never a silently inconsistent "complete"
snapshot.
"""

from __future__ import annotations

import json
import secrets
import time

from ceph_tpu.client.rados import ObjectOperation, RadosError
from ceph_tpu.services.rbd import RBD, RBDError

GROUP_DIR_OID = "rbd_group_directory"


class RBDGroups:
    """Group verbs over one pool/namespace handle."""

    def __init__(self, rbd: RBD):
        self.rbd = rbd
        self.ioctx = rbd.ioctx

    @staticmethod
    def _hdr(gid: str) -> str:
        return f"rbd_group_header.{gid}"

    # -- group directory --------------------------------------------------
    async def create(self, name: str) -> str:
        if not name or "/" in name or "@" in name:
            raise RBDError(f"bad group name {name!r}")
        try:
            existing = await self.ioctx.get_omap(GROUP_DIR_OID, [name])
        except RadosError as e:
            if e.rc != -2:
                raise
            existing = {}
        if name in existing:
            raise RBDError(f"group {name!r} exists")
        gid = secrets.token_hex(8)
        await self.ioctx.operate(
            self._hdr(gid), ObjectOperation().create()
        )
        await self.ioctx.operate(
            GROUP_DIR_OID,
            ObjectOperation().create().omap_set({name: gid.encode()}),
        )
        return gid

    async def list(self) -> list[str]:
        try:
            return sorted(await self.ioctx.get_omap(GROUP_DIR_OID))
        except RadosError as e:
            if e.rc == -2:
                return []
            raise

    async def _gid(self, name: str) -> str:
        try:
            kv = await self.ioctx.get_omap(GROUP_DIR_OID, [name])
        except RadosError as e:
            if e.rc == -2:
                kv = {}
            else:
                raise
        if name not in kv:
            raise RBDError(f"no group {name!r}")
        return kv[name].decode()

    async def rename(self, old: str, new: str) -> None:
        if not new or "/" in new or "@" in new:
            raise RBDError(f"bad group name {new!r}")
        gid = await self._gid(old)
        names = await self.list()
        if new in names:
            raise RBDError(f"group {new!r} exists")
        await self.ioctx.operate(
            GROUP_DIR_OID,
            ObjectOperation().omap_set({new: gid.encode()})
            .omap_rm([old]),
        )

    async def remove(self, name: str) -> None:
        """Remove the group: member images are unlinked (their data is
        untouched), group snapshot records die with the header — the
        per-image snaps they reference are removed too (Group.cc
        remove cleans member snaps)."""
        gid = await self._gid(name)
        hdr = await self._header(gid)
        for rec in hdr["snaps"].values():
            await self._remove_member_snaps(rec)
        for image_id in hdr["images"]:
            await self._clear_image_group(image_id)
        try:
            await self.ioctx.remove(self._hdr(gid))
        except RadosError as e:
            if e.rc != -2:
                raise
        await self.ioctx.rm_omap_keys(GROUP_DIR_OID, [name])

    async def _header(self, gid: str) -> dict:
        """Decoded header: {"images": {image_id: rec},
        "snaps": {snap_id: rec}}."""
        try:
            omap = await self.ioctx.get_omap(self._hdr(gid))
        except RadosError as e:
            if e.rc != -2:
                raise
            omap = {}
        out: dict = {"images": {}, "snaps": {}}
        for k, v in omap.items():
            kind, _, rest = k.partition(".")
            if kind == "image":
                out["images"][rest] = json.loads(v)
            elif kind == "snap":
                out["snaps"][rest] = json.loads(v)
        return out

    # -- membership -------------------------------------------------------
    async def image_add(self, group: str, image_name: str) -> None:
        gid = await self._gid(group)
        image_id = await self.rbd.image_id(image_name)
        hdr_oid = f"rbd_header.{image_id}"
        try:
            cur = await self.ioctx.get_xattr(hdr_oid, "group")
        except RadosError as e:
            if e.rc != -2:
                raise
            cur = None
        if cur is not None:
            if cur.decode() == gid:
                raise RBDError(f"image {image_name!r} already in group")
            raise RBDError(
                f"image {image_name!r} belongs to another group"
            )
        await self.ioctx.set_xattr(hdr_oid, "group", gid.encode())
        await self.ioctx.set_omap(self._hdr(gid), {
            f"image.{image_id}": json.dumps(
                {"name": image_name}).encode(),
        })

    async def image_remove(self, group: str, image_name: str) -> None:
        gid = await self._gid(group)
        image_id = await self.rbd.image_id(image_name)
        hdr = await self._header(gid)
        if image_id not in hdr["images"]:
            raise RBDError(f"image {image_name!r} not in {group!r}")
        await self._clear_image_group(image_id)
        await self.ioctx.rm_omap_keys(self._hdr(gid),
                                      [f"image.{image_id}"])

    async def _clear_image_group(self, image_id: str) -> None:
        try:
            await self.ioctx.rm_xattr(f"rbd_header.{image_id}", "group")
        except RadosError as e:
            if e.rc != -2:
                raise

    async def image_list(self, group: str) -> list[str]:
        gid = await self._gid(group)
        hdr = await self._header(gid)
        return sorted(rec["name"] for rec in hdr["images"].values())

    # -- group snapshots --------------------------------------------------
    async def snap_create(self, group: str, snap_name: str) -> str:
        """Crash-consistent snapshot of every member at one point.

        Quiesce: every member image is opened and exclusively locked
        (sorted by image id — a global order, so two concurrent group
        snaps over overlapping groups cannot deadlock); in-flight
        writers lose their lease/get fenced exactly as single-image
        exclusive lock transitions do.  Only when ALL locks are held
        are the snaps taken."""
        gid = await self._gid(group)
        hdr = await self._header(gid)
        if any(r.get("name") == snap_name
               for r in hdr["snaps"].values()):
            raise RBDError(f"group snap {snap_name!r} exists")
        if not hdr["images"]:
            raise RBDError(f"group {group!r} has no images")
        sid = secrets.token_hex(6)
        member_snap = f".group.{gid}.{sid}"
        members = sorted(
            (image_id, rec["name"])
            for image_id, rec in hdr["images"].items()
        )
        # pending record first: a crash below leaves a visibly
        # incomplete snapshot, never a fake-complete one
        rec = {
            "name": snap_name, "state": "pending",
            "created_at": time.time(), "member_snap": member_snap,
            "images": [{"id": i, "name": n} for i, n in members],
        }
        await self.ioctx.set_omap(self._hdr(gid), {
            f"snap.{sid}": json.dumps(rec).encode(),
        })
        images = []
        try:
            for _, name in members:
                img = await self.rbd.open(name, exclusive=True)
                images.append(img)
                await img.acquire_exclusive_lock()
            for img in images:
                await img.snap_create(member_snap)
        finally:
            for img in images:
                try:
                    await img.close()
                except (RBDError, RadosError):
                    pass
        rec["state"] = "complete"
        await self.ioctx.set_omap(self._hdr(gid), {
            f"snap.{sid}": json.dumps(rec).encode(),
        })
        return sid

    async def snap_list(self, group: str) -> list[dict]:
        gid = await self._gid(group)
        hdr = await self._header(gid)
        return sorted(
            ({"id": sid, **rec} for sid, rec in hdr["snaps"].items()),
            key=lambda r: r["created_at"],
        )

    async def _snap_rec(self, gid: str, snap_name: str
                        ) -> tuple[str, dict]:
        hdr = await self._header(gid)
        for sid, rec in hdr["snaps"].items():
            if rec.get("name") == snap_name:
                return sid, rec
        raise RBDError(f"no group snap {snap_name!r}")

    async def _remove_member_snaps(self, rec: dict) -> None:
        for m in rec.get("images", ()):
            try:
                img = await self.rbd.open(m["name"])
            except RBDError:
                continue            # member image is gone
            try:
                if rec["member_snap"] in img.snaps:
                    await img.snap_remove(rec["member_snap"])
            finally:
                await img.close()

    async def snap_remove(self, group: str, snap_name: str) -> None:
        gid = await self._gid(group)
        sid, rec = await self._snap_rec(gid, snap_name)
        await self._remove_member_snaps(rec)
        await self.ioctx.rm_omap_keys(self._hdr(gid), [f"snap.{sid}"])

    async def snap_rollback(self, group: str, snap_name: str) -> None:
        """Restore every member to the group snapshot's point — the
        mutually consistent state ``snap_create`` froze.  All members
        are locked first (same global order) so the restored set is
        itself consistent."""
        gid = await self._gid(group)
        sid, rec = await self._snap_rec(gid, snap_name)
        if rec.get("state") != "complete":
            raise RBDError(
                f"group snap {snap_name!r} is {rec.get('state')}"
            )
        images = []
        try:
            for m in sorted(rec["images"], key=lambda m: m["id"]):
                img = await self.rbd.open(m["name"], exclusive=True)
                images.append(img)
                await img.acquire_exclusive_lock()
            for img in images:
                await img.snap_rollback(rec["member_snap"])
        finally:
            for img in images:
                try:
                    await img.close()
                except (RBDError, RadosError):
                    pass
