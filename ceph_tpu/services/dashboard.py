"""Dashboard-lite: the mgr's operator-facing HTTP surface.

The core of reference src/pybind/mgr/dashboard (scope per its status +
management pages, not the 11 MB web app) plus the prometheus module's
exposition endpoint and the restful module's programmatic API
(src/pybind/mgr/restful/module.py:36 role), on one asyncio server:

- ``GET /api/status``  cluster status JSON: health checks, mon quorum,
  osd/pg/pool summaries, the OSD tree, MDS ranks, and the recent
  cluster log — assembled from the same mon commands the CLI uses.
- ``GET /api/osd`` / ``GET /api/pool``  resource listings (restful).
- ``GET /api/slo``     per-objective SLO verdicts (value / burn rate /
  worst daemon) + utilization telemetry rates from the slo mgr module.
- ``GET /api/qos``     QoS defense-plane state from the qos mgr module
  (AIMD recovery limit, pushed hedge timeouts, front-door sheds).
- ``GET /api/ts``      time-series query against the mgr's retention
  store (``?name=`` one series, ``?prefix=`` a namespace, ``start`` /
  ``end`` / ``tier=raw|1m|1h|auto`` / ``max_points``; no args lists
  the catalog).
- ``GET /metrics``     prometheus text exposition of the mgr's last
  digest (the pybind/mgr/prometheus serve role) plus the SLO burn-rate
  and utilization gauges.
- ``GET /``            one self-refreshing HTML page rendering the
  status for a browser, with an operations panel driving the API.

Management surface (token-gated; disabled unless an ``api_token`` is
configured — reads stay open):

- ``POST /api/pool``              {"pool", "pg_num", "size"?}
- ``DELETE /api/pool/<name>``
- ``POST /api/osd/<id>/out|in|down``
- ``POST /api/osd_flags``         {"flag", "set": bool}  (noout &c)
- ``POST /api/health/mute``       {"code", "ttl"?} / ``.../unmute``

Every write maps 1:1 onto an existing, paxos-audited mon command —
the dashboard adds reach, not new authority.

Object-gateway panels (shown when a vstart RGW attaches itself via
``attach_rgw``; the JSON routes ride the same token gate as the
management API because placement records and lifecycle policies name
internal pools):

- ``GET /api/rgw/placement``          zone placement targets: every
  storage class with its data pool / compression / EC profile.
- ``GET /api/rgw/lifecycle``          per-bucket lifecycle rules
  (expiration + transition); ``?bucket=<name>`` narrows to one.
"""

from __future__ import annotations

import asyncio
import hmac as hmac_mod
import html
import json
import time

from ceph_tpu.common.log import Dout

log = Dout("dashboard")


class Dashboard:
    def __init__(self, mgr, host: str = "127.0.0.1", port: int = 0,
                 api_token: str | None = None):
        self.mgr = mgr
        self.host = host
        self.port = port
        self.api_token = api_token
        self.rgw = None             # RGWLite, via attach_rgw()
        self._server: asyncio.AbstractServer | None = None
        self._metrics_cache: tuple[float, bytes] = (0.0, b"")

    def attach_rgw(self, gw) -> None:
        """Expose an RGWLite's placement + lifecycle state read-only."""
        self.rgw = gw

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.dout(1, "dashboard on %s:%d", self.host, self.port)
        return self.host, self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- http --------------------------------------------------------------
    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            head = await reader.readuntil(b"\r\n\r\n")
            headers = {}
            head_lines = head.decode("latin-1").split("\r\n")
            line = head_lines[0]
            for ln in head_lines[1:]:
                k, _, v = ln.partition(":")
                headers[k.strip().lower()] = v.strip()
            method, path, _ = (line.split(" ", 2) + ["", ""])[:3]
            path, _, rawq = path.partition("?")
            query: dict[str, str] = {}
            for pair in rawq.split("&"):
                if pair:
                    k, _, v = pair.partition("=")
                    query[k] = v
            req_body = b""
            clen = int(headers.get("content-length", 0) or 0)
            if clen:
                req_body = await reader.readexactly(min(clen, 1 << 20))
            if method in ("POST", "DELETE"):
                status, body = await self._mutate(method, path,
                                                  headers, req_body)
                ctype = "application/json"
            elif method != "GET":
                body, ctype, status = b"bad method", "text/plain", 405
            elif path == "/api/status":
                body = json.dumps(await self._status()).encode()
                ctype, status = "application/json", 200
            elif path == "/api/osd":
                body = json.dumps(await self._osd_list()).encode()
                ctype, status = "application/json", 200
            elif path == "/api/pool":
                body = json.dumps(await self._pool_list()).encode()
                ctype, status = "application/json", 200
            elif path in self._GET_MON_ROUTES:
                prefix, kw = self._GET_MON_ROUTES[path]
                data = await self._mon(prefix, **kw)
                if data is None:
                    # a mon outage/election must read as a failed
                    # poll, not a successful empty one
                    body = json.dumps(
                        {"error": "mon command failed"}).encode()
                    ctype, status = "application/json", 503
                else:
                    body = json.dumps(data).encode()
                    ctype, status = "application/json", 200
            elif path in ("/api/rgw/placement", "/api/rgw/lifecycle"):
                status, body = await self._rgw_get(path, headers, query)
                ctype = "application/json"
            elif path == "/api/trace":
                status, body = await self._trace_get(headers, query)
                ctype = "application/json"
            elif path == "/api/forensics":
                # bundle index from the mgr's flight recorder; ?id=
                # loads one full bundle (merged timeline + per-daemon
                # rings) back from disk
                bid = query.get("id", "")
                if bid:
                    bundle = self.mgr.forensics_bundle(bid)
                    if bundle is None:
                        body = json.dumps(
                            {"error": f"no bundle {bid!r}"}).encode()
                        ctype, status = "application/json", 404
                    else:
                        body = json.dumps(bundle).encode()
                        ctype, status = "application/json", 200
                else:
                    body = json.dumps({
                        "bundles": self.mgr.forensics_index(),
                    }).encode()
                    ctype, status = "application/json", 200
            elif path == "/api/slo":
                # SLO verdicts + utilization rates straight from the
                # mgr's last digest (the slo module's contribution)
                digest = self.mgr.last_digest or {}
                body = json.dumps({
                    "slo": digest.get("slo", {}),
                    "utilization": digest.get("utilization", {}),
                }).encode()
                ctype, status = "application/json", 200
            elif path == "/api/qos":
                # defense-plane state: controller AIMD position,
                # pushed hedge timeouts, front-door shed counts
                digest = self.mgr.last_digest or {}
                body = json.dumps({
                    "qos": digest.get("qos", {}),
                }).encode()
                ctype, status = "application/json", 200
            elif path == "/api/ts":
                # time-series query against the retention module; the
                # same planner the asok `ts query` command uses
                def _qf(k):
                    v = query.get(k, "")
                    return float(v) if v else None
                body = json.dumps(self.mgr.ts_query(
                    name=query.get("name", ""),
                    prefix=query.get("prefix", ""),
                    start=_qf("start"), end=_qf("end"),
                    tier=query.get("tier", "auto"),
                    max_points=int(query.get("max_points", "0") or 0),
                )).encode()
                ctype, status = "application/json", 200
            elif path == "/metrics":
                # collect() messages every OSD; cache briefly so an
                # aggressive scraper doesn't multiply cluster traffic
                ts, cached = self._metrics_cache
                if time.monotonic() - ts < 1.0:
                    body = cached
                else:
                    snap = await self.mgr.collect()
                    body = self.mgr.prometheus_text(
                        snap, self.mgr.prometheus_extra()).encode()
                    self._metrics_cache = (time.monotonic(), body)
                ctype, status = "text/plain; version=0.0.4", 200
            elif path == "/":
                body = (await self._html()).encode()
                ctype, status = "text/html; charset=utf-8", 200
            else:
                body, ctype, status = b"not found", "text/plain", 404
            writer.write(
                f"HTTP/1.1 {status} X\r\ncontent-type: {ctype}\r\n"
                f"content-length: {len(body)}\r\n"
                f"connection: close\r\n\r\n".encode() + body)
            await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except Exception as e:          # noqa: BLE001 — serve a 500
            try:
                msg = f"internal error: {type(e).__name__}".encode()
                writer.write(
                    b"HTTP/1.1 500 X\r\ncontent-type: text/plain\r\n"
                    + f"content-length: {len(msg)}\r\n\r\n".encode()
                    + msg)
                await writer.drain()
            except (ConnectionError, OSError):
                pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    # read-only resource routes (the restful module's GET surface):
    # each maps straight onto one paxos-consistent mon command
    _GET_MON_ROUTES = {
        "/api/health": ("health", {}),
        "/api/mon": ("mon dump", {}),
        "/api/quorum": ("quorum_status", {}),
        "/api/df": ("df", {}),
        "/api/osd_df": ("osd df", {}),
        "/api/pg": ("pg stat", {}),
        "/api/fs": ("fs status", {}),
        "/api/crush": ("osd tree", {}),
        "/api/log": ("log last", {"num": 100}),
    }

    # -- management API (restful module + dashboard write surface) ---------
    def _authorized(self, headers: dict) -> bool:
        if not self.api_token:
            return False            # writes disabled entirely
        auth = headers.get("authorization", "")
        tok = auth[len("Bearer "):] if auth.startswith("Bearer ") \
            else headers.get("x-auth-token", "")
        return hmac_mod.compare_digest(tok, self.api_token)

    async def _mutate(self, method: str, path: str, headers: dict,
                      raw: bytes) -> tuple[int, bytes]:
        def reply(status: int, **data) -> tuple[int, bytes]:
            return status, json.dumps(data).encode()

        if not self._authorized(headers):
            return reply(403, error="missing or bad api token")
        try:
            args = json.loads(raw) if raw else {}
            if not isinstance(args, dict):
                raise ValueError("body must be a JSON object")
        except ValueError as e:
            return reply(400, error=f"bad body: {e}")
        parts = [p for p in path.split("/") if p]
        if not parts or parts[0] != "api":
            return reply(404, error="unknown route")

        async def mon(prefix: str, **kw):
            r = await self.mgr.monc.command(prefix, **kw)
            if r.get("rc") != 0:
                return reply(409, error=r.get("outs", "refused"),
                             rc=r.get("rc"))
            return reply(200, ok=True, result=r.get("data"))

        route = parts[1:]
        if method == "POST" and route == ["pool"]:
            pool = str(args.get("pool", ""))
            if not pool:
                return reply(400, error="pool name required")
            return await mon(
                "osd pool create", pool=pool,
                pg_num=int(args.get("pg_num", 8)),
                size=int(args.get("size", 3)))
        if method == "DELETE" and len(route) == 2 \
                and route[0] == "pool":
            return await mon("osd pool delete", pool=route[1])
        if method == "POST" and len(route) == 3 \
                and route[0] == "osd" and route[2] in ("out", "in",
                                                       "down"):
            try:
                osd = int(route[1])
            except ValueError:
                return reply(400, error=f"bad osd id {route[1]!r}")
            return await mon(f"osd {route[2]}", ids=[osd])
        if method == "POST" and route == ["osd_flags"]:
            flag = str(args.get("flag", ""))
            if not flag:
                return reply(400, error="flag required")
            verb = "osd set" if args.get("set", True) else "osd unset"
            return await mon(verb, flag=flag)
        if method == "POST" and route == ["health", "mute"]:
            return await mon("health mute",
                             code=str(args.get("code", "")),
                             sticky=bool(args.get("sticky", False)))
        if method == "POST" and route == ["health", "unmute"]:
            return await mon("health unmute",
                             code=str(args.get("code", "")))
        return reply(404, error="unknown route")

    # -- tracing -----------------------------------------------------------
    async def _trace_get(self, headers: dict,
                         query: dict) -> tuple[int, bytes]:
        """``GET /api/trace?trace_id=<id>``: cluster-wide span
        reassembly via the mgr's dump_traces fan-out.  Token-gated —
        span tags carry object names and pool ids."""
        def reply(status: int, data) -> tuple[int, bytes]:
            return status, json.dumps(data).encode()

        if not self._authorized(headers):
            return reply(403, {"error": "missing or bad api token"})
        trace_id = query.get("trace_id", "")
        if not trace_id:
            return reply(400, {"error": "trace_id required"})
        tree = await self.mgr.collect_trace(trace_id)
        return reply(200, {"trace_id": trace_id, "spans": tree})

    # -- object gateway (placement targets + lifecycle) --------------------
    async def _rgw_get(self, path: str, headers: dict,
                       query: dict) -> tuple[int, bytes]:
        def reply(status: int, data) -> tuple[int, bytes]:
            return status, json.dumps(data).encode()

        # placement records name internal pools and lifecycle rules
        # reveal bucket names — gate like the management API
        if not self._authorized(headers):
            return reply(403, {"error": "missing or bad api token"})
        if self.rgw is None:
            return reply(503, {"error": "no rgw attached"})
        if path == "/api/rgw/placement":
            return reply(200, await self._rgw_placement())
        return reply(200, await self._rgw_lifecycle(
            query.get("bucket") or None))

    async def _rgw_placement(self) -> list[dict]:
        from ceph_tpu.services.rgw_zone import ZonePlacement
        return await ZonePlacement(self.rgw.ioctx).ls()

    async def _rgw_lifecycle(self, bucket: str | None = None) -> dict:
        out: dict = {}
        names = [bucket] if bucket else await self.rgw.list_buckets()
        for name in names:
            try:
                meta = await self.rgw._bucket_meta(name)
            except Exception:               # noqa: BLE001 — racing rm
                continue
            rules = meta.get("lifecycle") or []
            if rules:
                out[name] = rules
        return out

    async def _osd_list(self) -> list[dict]:
        dump = await self._mon("osd dump") or {}
        return [
            {"osd": int(oid), **info}
            for oid, info in sorted(
                (dump.get("osds") or {}).items(),
                key=lambda kv: int(kv[0]))
        ]

    async def _pool_list(self) -> list[dict]:
        dump = await self._mon("osd dump") or {}
        pools = dump.get("pools") or {}
        return [dict(p, pool_id=int(pid))
                for pid, p in sorted(pools.items(),
                                     key=lambda kv: str(kv[0]))]

    # -- data assembly -----------------------------------------------------
    async def _mon(self, prefix: str, **args):
        try:
            r = await self.mgr.monc.command(prefix, **args)
        except (ConnectionError, asyncio.TimeoutError):
            return None
        return r.get("data") if r.get("rc") == 0 else None

    async def _status(self) -> dict:
        out: dict = {"ts": time.time()}
        # seven mon reads, all independent: fetch concurrently ("df"
        # is NOT fetched — its payload is the mgr digest this process
        # already holds in last_digest)
        (out["status"], out["health"], out["osd_tree"], out["mds"],
         logs, out["fs"], out["quorum"]) = \
            await asyncio.gather(
            self._mon("status"), self._mon("health"),
            self._mon("osd tree"), self._mon("mds stat"),
            self._mon("log last", num=50),
            self._mon("fs status"), self._mon("quorum_status"))
        out["log"] = logs or []
        digest = getattr(self.mgr, "last_digest", None) or {}
        out["pgmap"] = {
            k: digest.get(k) for k in
            ("pgs_by_state", "num_pgs", "num_objects", "num_bytes",
             "degraded_objects", "pools", "osd_df")
            if k in digest
        }
        return out

    # -- html rendering ----------------------------------------------------
    async def _html(self) -> str:
        s = await self._status()
        esc = html.escape
        health = s.get("health") or {}
        checks = health.get("checks") or {}
        hstatus = health.get("status", "UNKNOWN")
        color = {"HEALTH_OK": "#2a2", "HEALTH_WARN": "#f90",
                 "HEALTH_ERR": "#d22"}.get(hstatus, "#888")
        rows: list[str] = []

        def section(title: str, inner: str) -> None:
            rows.append(f"<h2>{esc(title)}</h2>{inner}")

        def table(headers: list[str], body_rows: list[list[str]]) -> str:
            head = "".join(f"<th>{esc(h)}</th>" for h in headers)
            body = "".join(
                "<tr>" + "".join(f"<td>{c}</td>" for c in r) + "</tr>"
                for r in body_rows)
            return (f"<table><thead><tr>{head}</tr></thead>"
                    f"<tbody>{body}</tbody></table>")

        section("Health", (
            f'<p class="pill" style="background:{color}">'
            f"{esc(hstatus)}</p>"
            + (table(["check", "severity", "message"], [
                [esc(k), esc(v.get("severity", "")),
                 esc(v.get("message", ""))]
                for k, v in sorted(checks.items())
            ]) if checks else "<p>no active health checks</p>")))

        pg = s.get("pgmap") or {}
        states = pg.get("pgs_by_state") or {}
        section("PGs", table(["state", "count"], [
            [esc(k), str(v)] for k, v in sorted(states.items())
        ]) + f"<p>{pg.get('num_pgs', 0)} pgs, "
            f"{pg.get('num_objects', 0)} objects, "
            f"{pg.get('num_bytes', 0)} bytes, "
            f"{pg.get('degraded_objects', 0)} degraded</p>")

        pools = pg.get("pools") or {}
        section("Pools", table(
            ["pool", "pgs", "objects", "bytes", "degraded"], [
                [esc(str(p.get("name", pid))), str(p.get("num_pgs", 0)),
                 str(p.get("num_objects", 0)),
                 str(p.get("num_bytes", 0)), str(p.get("degraded", 0))]
                for pid, p in sorted(pools.items(),
                                     key=lambda kv: str(kv[0]))
            ]))

        section("Capacity",
                f"<p>{pg.get('num_bytes', 0)} bytes stored in "
                f"{pg.get('num_objects', 0)} objects</p>")

        digest = getattr(self.mgr, "last_digest", None) or {}
        slo = digest.get("slo") or {}
        objectives = slo.get("objectives") or []
        if objectives:
            def fmt_val(rec):
                v = rec.get("value")
                return "n/a" if v is None else \
                    f"{v:.4g} {rec.get('unit', '')}"

            section("Serving SLO", table(
                ["objective", "target", "value", "burn rate",
                 "worst daemon", "status"], [
                    [esc(r.get("objective", "")),
                     esc(f"{r.get('target', 0):g} {r.get('unit', '')}"),
                     esc(fmt_val(r)),
                     esc(f"{r.get('burn_rate', 0.0):.2f}x"),
                     esc(str(r.get("worst_daemon") or "-")),
                     ('<span style="color:#d22">VIOLATING</span>'
                      if r.get("violating") else
                      '<span style="color:#2a2">ok</span>')]
                    for r in objectives
                ]))

        util = digest.get("utilization") or {}
        if util:
            # the rebuild-vs-client-tail pair reads side by side: the
            # interference arxiv 1906.08602 names as THE tail driver
            section("Utilization", table(["series", "value"], [
                ["device GiB/s (EC launches)",
                 esc(f"{util.get('device_gibps', 0.0):g}")],
                ["HBM roofline %",
                 esc(f"{util.get('roofline_pct', 0.0):g}%")],
                ["coalesce occupancy (ops/launch)",
                 esc(f"{util.get('coalesce_occupancy', 0.0):g}")],
                ["coalesce wait p50/p99 µs",
                 esc(f"{util.get('coalesce_wait_p50_us', 0.0):g} / "
                     f"{util.get('coalesce_wait_p99_us', 0.0):g}")],
                ["resident cache hit rate",
                 esc(f"{util.get('resident_hit_rate', 0.0):g}")],
                ["rebuild GiB/s ⇄ client p99 ms",
                 esc(f"{util.get('rebuild_gibps', 0.0):g} ⇄ "
                     f"{util.get('client_p99_ms', 0.0):g}")],
                ["client p50/p99/p999 ms",
                 esc(f"{util.get('client_p50_ms', 0.0):g} / "
                     f"{util.get('client_p99_ms', 0.0):g} / "
                     f"{util.get('client_p999_ms', 0.0):g}")],
            ]))

        qos = digest.get("qos") or {}
        if qos.get("enabled"):
            hedges = qos.get("hedge_timeouts_ms") or {}
            hedge_s = ", ".join(f"{d}: {t:g}ms"
                                for d, t in sorted(hedges.items())) \
                or "none pushed"
            section("QoS defense plane", table(["series", "value"], [
                ["controller",
                 ('<span style="color:#d22">BACKING OFF</span>'
                  if qos.get("burning") else
                  '<span style="color:#2a2">steady</span>')],
                ["client latency burn",
                 esc(f"{qos.get('burn', 0.0):g}x")],
                ["recovery limit (ops/s)",
                 esc(f"{qos.get('recovery_limit', 0.0):g} "
                     f"(floor {qos.get('recovery_floor', 0.0):g}, "
                     f"ceiling {qos.get('recovery_ceiling', 0.0):g})")],
                ["mClock retunes", esc(str(qos.get("retunes", 0)))],
                ["adaptive hedge timeouts", esc(hedge_s)],
                ["recent RGW sheds (503)",
                 esc(str(qos.get("recent_sheds", 0)))],
            ]))

        fsmap = s.get("fs") or {}
        fs_rows = []
        for fsname, info in sorted(fsmap.items()):
            if not isinstance(info, dict):
                continue
            ranks = ", ".join(
                f"{r.get('rank')}:{r.get('name')}({r.get('state')})"
                for r in info.get("ranks", ()))
            fs_rows.append([esc(str(fsname)), esc(ranks),
                            esc(str(info.get("standbys", ""))),
                            esc(str(info.get("down", "")))])
        if fs_rows:
            section("Filesystems", table(
                ["fs", "ranks", "standbys", "down"], fs_rows))

        q = s.get("quorum") or {}
        if q:
            section("Monitors", table(["", ""], [
                [esc(k), esc(str(v))] for k, v in sorted(q.items())
            ]))

        tree = s.get("osd_tree") or {}
        tree_rows: list[list[str]] = []

        def walk(node: dict, depth: int) -> None:
            pad = "&nbsp;" * 4 * depth
            status = node.get("status", "")
            badge = (f'<span style="color:'
                     f'{"#2a2" if status == "up" else "#d22"}">'
                     f"{esc(status)}</span>" if status else "")
            tree_rows.append([
                pad + esc(node.get("name", "?")),
                esc(node.get("type", "")), badge,
                esc(f"{node.get('reweight', '')}"),
            ])
            for child in node.get("children", ()):
                walk(child, depth + 1)

        for root in tree.get("nodes", ()):
            walk(root, 0)
        section("OSD tree", table(["name", "type", "status", "reweight"],
                                  tree_rows))

        if self.rgw is not None:
            # object-gateway panels: where each storage class lands
            # and which buckets have tiering/expiration policies
            try:
                placements = await self._rgw_placement()
            except Exception:           # noqa: BLE001 — rgw racing
                placements = []
            pl_rows = []
            for rec in placements:
                classes = rec.get("storage_classes") or {}
                for cls, c in sorted(classes.items()):
                    pl_rows.append([
                        esc(rec.get("id", "")), esc(cls),
                        esc(c.get("pool", "") or "(zone pool)"),
                        esc(c.get("compression", "") or "-"),
                        esc(c.get("ec_profile", "") or "-")])
            section("RGW placement targets", table(
                ["placement", "class", "data pool", "compression",
                 "ec profile"], pl_rows)
                if pl_rows else "<p>no placement targets</p>")

            try:
                lc = await self._rgw_lifecycle()
            except Exception:           # noqa: BLE001
                lc = {}
            lc_rows = []
            for bname, rules in sorted(lc.items()):
                for r in rules:
                    acts = []
                    for kind, label in (
                            ("expiration", "expire"),
                            ("noncurrent", "expire-noncurrent"),
                            ("abort_mpu", "abort-mpu"),
                            ("transition", "transition"),
                            ("noncurrent_transition",
                             "transition-noncurrent")):
                        if f"{kind}_seconds" in r:
                            t = f"{r[f'{kind}_seconds']}s"
                        elif f"{kind}_days" in r:
                            t = f"{r[f'{kind}_days']}d"
                        else:
                            continue
                        cls = r.get(f"{kind}_class", "")
                        acts.append(f"{label} {t}"
                                    + (f" → {cls}" if cls else ""))
                    lc_rows.append([
                        esc(bname), esc(r.get("id", "")),
                        esc(r.get("prefix", "") or "-"),
                        esc(r.get("status", "")),
                        esc("; ".join(acts))])
            if lc_rows:
                section("RGW lifecycle", table(
                    ["bucket", "rule", "prefix", "status", "actions"],
                    lc_rows))

        if self.api_token:
            # operations panel: every button drives the token-gated
            # management API (the dashboard write surface)
            section("Operations", """
<p>api token: <input id="tok" type="password" size="24"></p>
<p>osd <input id="osdid" size="4" value="0">
 <button onclick="osd('out')">out</button>
 <button onclick="osd('in')">in</button>
 <button onclick="osd('down')">down</button></p>
<p>flag <input id="flag" size="10" value="noout">
 <button onclick="flags(true)">set</button>
 <button onclick="flags(false)">unset</button></p>
<p>pool <input id="pool" size="12">
 <button onclick="mkpool()">create</button>
 <button onclick="rmpool()">delete</button></p>
<p>mute <input id="code" size="14" value="OSD_DOWN">
 <button onclick="mute(true)">mute</button>
 <button onclick="mute(false)">unmute</button></p>
<pre id="out"></pre>
<script>
async function call(method, path, body) {
  const r = await fetch(path, {method: method,
    headers: {"authorization": "Bearer " +
              document.getElementById("tok").value},
    body: body ? JSON.stringify(body) : undefined});
  document.getElementById("out").textContent = await r.text();
}
function osd(verb) {
  call("POST", "/api/osd/" +
       document.getElementById("osdid").value + "/" + verb);
}
function flags(on) {
  call("POST", "/api/osd_flags",
       {flag: document.getElementById("flag").value, set: on});
}
function mkpool() {
  call("POST", "/api/pool",
       {pool: document.getElementById("pool").value});
}
function rmpool() {
  call("DELETE", "/api/pool/" +
       document.getElementById("pool").value);
}
function mute(on) {
  call("POST", "/api/health/" + (on ? "mute" : "unmute"),
       {code: document.getElementById("code").value});
}
</script>""")

        mds = s.get("mds") or {}
        mds_rows = []
        for fs, info in sorted((mds.get("filesystems") or {}).items()):
            for a in info.get("actives", ()):
                mds_rows.append([esc(fs), str(a.get("rank", 0)),
                                 esc(a.get("name", "")), "active"])
            for n in info.get("standby", ()):
                mds_rows.append([esc(fs), "-", esc(n), "standby"])
            for n in info.get("down", ()):
                mds_rows.append([esc(fs), "-", esc(n), "down"])
        if mds_rows:
            section("MDS", table(["fs", "rank", "name", "state"],
                                 mds_rows))

        logs = s.get("log") or []
        section("Cluster log", table(["when", "level", "who", "message"], [
            [esc(time.strftime("%H:%M:%S",
                               time.localtime(e.get("stamp", 0)))),
             esc(e.get("level", "")), esc(e.get("who", "")),
             esc(e.get("message", ""))]
            for e in logs[-25:][::-1]
        ]))

        return (
            "<!doctype html><html><head>"
            '<meta charset="utf-8">'
            '<meta http-equiv="refresh" content="5">'
            "<title>ceph_tpu dashboard</title><style>"
            "body{font-family:sans-serif;margin:2em;color:#223}"
            "table{border-collapse:collapse;margin:.5em 0}"
            "td,th{border:1px solid #ccd;padding:.25em .6em;"
            "text-align:left;font-size:.9em}"
            "th{background:#eef}h2{margin:.8em 0 .2em}"
            ".pill{display:inline-block;color:#fff;padding:.2em .8em;"
            "border-radius:1em;font-weight:bold}"
            "</style></head><body><h1>ceph_tpu</h1>"
            + "".join(rows) + "</body></html>"
        )
