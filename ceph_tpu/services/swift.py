"""Swift REST frontend for RGW-lite: the rgw_rest_swift.h role.

The reference serves the OpenStack Swift dialect off the same RGWRados
store as S3 (src/rgw/rgw_rest_swift.{h,cc}); this frontend serves the
Swift v1 core off the same :class:`RGWLite`, so a container created
over Swift is a bucket over S3 and vice versa:

- TempAuth handshake (``GET /auth/v1.0`` with ``X-Auth-User`` /
  ``X-Auth-Key``) returning ``X-Auth-Token`` + ``X-Storage-Url``; the
  token is self-validating (uid + expiry + HMAC over the user's secret
  key), so no server-side token table is needed.
- Account:   ``GET /v1/AUTH_<uid>``        container listing (JSON)
- Container: ``PUT/GET/HEAD/DELETE /v1/AUTH_<uid>/<container>``
- Object:    ``PUT/GET/HEAD/DELETE/POST /v1/AUTH_<uid>/<c>/<obj>``
  with ``X-Object-Meta-*`` metadata, Range reads, and POST metadata
  replacement (Swift semantics).

Authorization rides RGWLite ``as_user`` exactly like the S3 frontend,
so ACL/quota/versioning behavior is shared.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import json
import time
from email.utils import formatdate

from ceph_tpu.common.log import Dout
from ceph_tpu.services.rgw import RGWError, RGWLite, RGWUsers

log = Dout("rgw-http")

_MAX_BODY = 256 * 1024 * 1024
TOKEN_TTL = 24 * 3600

# RGWError -> Swift status
_STATUS = {
    "AccessDenied": 403,
    "NoSuchBucket": 404,
    "NoSuchKey": 404,
    "BucketNotEmpty": 409,
    "BucketAlreadyExists": 202,    # Swift PUT container is idempotent
    "QuotaExceeded": 413,
}


def _mint_token(uid: str, secret: str, now: float | None = None) -> str:
    exp = int((now or time.time()) + TOKEN_TTL)
    mac = hmac.new(secret.encode(), f"{uid}:{exp}".encode(),
                   hashlib.sha256).hexdigest()[:32]
    return f"AUTH_tk{uid}:{exp}:{mac}"


class SwiftFrontend:
    """One listening Swift endpoint over an RGWLite handle."""

    def __init__(self, rgw: RGWLite, users: RGWUsers | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.rgw = rgw
        self.users = users if users is not None else rgw.users
        self.host = host
        self.port = port
        self._server: asyncio.AbstractServer | None = None

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._client, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        log.dout(1, "swift frontend on %s:%d", self.host, self.port)
        return self.host, self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    # -- http plumbing -----------------------------------------------------
    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                head = await reader.readuntil(b"\r\n\r\n")
                lines = head.decode("latin-1").split("\r\n")
                try:
                    method, path, _ = lines[0].split(" ", 2)
                except ValueError:
                    break
                hdrs = {}
                for ln in lines[1:]:
                    if ln:
                        k, _, v = ln.partition(":")
                        hdrs[k.strip().lower()] = v.strip()
                try:
                    length = int(hdrs.get("content-length", "0") or 0)
                except ValueError:
                    length = -1
                if not 0 <= length <= _MAX_BODY:
                    # the unread body would desynchronize a reused
                    # connection: answer and close
                    status, rh, body = 400, {}, b"bad content-length"
                    hdrs["connection"] = "close"
                else:
                    data = await reader.readexactly(length) \
                        if length > 0 else b""
                    try:
                        status, rh, body = await self._route(
                            method.upper(), path, hdrs, data)
                    except RGWError as e:
                        status = _STATUS.get(e.code, 400)
                        rh, body = {}, str(e).encode()
                    except (ValueError, KeyError) as e:
                        status, rh, body = 400, {}, repr(e).encode()
                keep = hdrs.get("connection", "keep-alive") != "close"
                base = {"date": formatdate(usegmt=True),
                        "connection":
                            "keep-alive" if keep else "close"}
                base.update(rh)
                # handlers (e.g. HEAD object) may have set the entity
                # size already; only fill in the actual body length
                base.setdefault("content-length", str(len(body)))
                out = [f"HTTP/1.1 {status} S"]
                out += [f"{k}: {v}" for k, v in base.items()]
                payload = "\r\n".join(out).encode("latin-1") \
                    + b"\r\n\r\n"
                if method.upper() != "HEAD":
                    payload += body
                writer.write(payload)
                await writer.drain()
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError,
                asyncio.LimitOverrunError, OSError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    # -- auth (TempAuth) ---------------------------------------------------
    async def _auth_handshake(self, hdrs: dict):
        user = hdrs.get("x-auth-user", "")
        key = hdrs.get("x-auth-key", "")
        uid = user.split(":", 1)[0]
        try:
            rec = await self.users.get(uid)
        except RGWError:
            return 401, {}, b"bad credentials"
        if rec.get("suspended") or not hmac.compare_digest(
                key.encode(), rec["secret_key"].encode()):
            # bytes compare: str compare_digest raises on non-ASCII
            # header values, which must be a 401, not a dead socket
            return 401, {}, b"bad credentials"
        token = _mint_token(uid, rec["secret_key"])
        url = f"http://{self.host}:{self.port}/v1/AUTH_{uid}"
        return 200, {"x-auth-token": token,
                     "x-storage-token": token,
                     "x-storage-url": url}, b""

    async def _validate_token(self, token: str) -> str:
        """Token -> uid, or raise AccessDenied."""
        try:
            rest = token.removeprefix("AUTH_tk")
            uid, exp_s, mac = rest.rsplit(":", 2)
            exp = int(exp_s)
        except ValueError:
            raise RGWError("AccessDenied", "malformed token")
        if exp < time.time():
            raise RGWError("AccessDenied", "token expired")
        try:
            rec = await self.users.get(uid)
        except RGWError:
            raise RGWError("AccessDenied", "unknown account")
        want = hmac.new(rec["secret_key"].encode(),
                        f"{uid}:{exp}".encode(),
                        hashlib.sha256).hexdigest()[:32]
        if not hmac.compare_digest(want, mac):
            raise RGWError("AccessDenied", "bad token")
        if rec.get("suspended"):
            raise RGWError("AccessDenied", f"{uid} suspended")
        return uid

    # -- TempURL (reference rgw_swift_auth.h:176 TempURLEngine) ------------
    async def _temp_url(self, method: str, path: str, query: dict,
                        hdrs: dict, body: bytes):
        """Validate ``?temp_url_sig=&temp_url_expires=`` pre-signed
        access against the account's Temp-URL keys (the
        X-Account-Meta-Temp-URL-Key / -Key-2 metadata), then execute
        the object op as the account.  Signature = HMAC(key,
        "<method>\\n<expires>\\n<path>"), sha1 or sha256 by digest
        length; prefix mode signs "prefix:<path-prefix>" and admits
        any object under it.  HEAD is allowed with a GET or PUT
        signature (Swift tempurl middleware rules)."""
        parts = [p for p in path.split("/") if p]
        if len(parts) < 4 or parts[0] != "v1" \
                or not parts[1].startswith("AUTH_"):
            return 401, {}, b"temp url requires an object path"
        account = parts[1][len("AUTH_"):]
        container = parts[2]
        obj = "/".join(parts[3:])
        try:
            expires = int(query["temp_url_expires"])
        except ValueError:
            return 401, {}, b"bad temp_url_expires"
        if time.time() > expires:
            return 401, {}, b"temp url expired"
        try:
            rec = await self.users.get(account)
        except RGWError:
            return 401, {}, b"bad temp url"
        meta = rec.get("swift_meta") or {}
        keys = [meta[k] for k in ("temp-url-key", "temp-url-key-2")
                if meta.get(k)]
        if not keys or rec.get("suspended"):
            return 401, {}, b"no temp-url keys set"
        sig = str(query["temp_url_sig"]).lower()
        digestmod = {40: hashlib.sha1, 64: hashlib.sha256}.get(
            len(sig))
        if digestmod is None:
            return 401, {}, b"bad signature"
        prefix = query.get("temp_url_prefix")
        if prefix is not None:
            if not obj.startswith(prefix):
                return 401, {}, b"object outside signed prefix"
            signed_path = f"/v1/AUTH_{account}/{container}/{prefix}"
            body_of = lambda m: f"{m}\n{expires}\nprefix:{signed_path}"
        else:
            signed_path = path
            body_of = lambda m: f"{m}\n{expires}\n{signed_path}"
        # HEAD validates with a GET or PUT signature too
        methods = {"HEAD": ("HEAD", "GET", "PUT")}.get(method,
                                                       (method,))
        if method not in ("GET", "HEAD", "PUT"):
            return 401, {}, b"method not allowed for temp urls"
        ok = any(
            hmac.compare_digest(
                hmac.new(key.encode(), body_of(m).encode(),
                         digestmod).hexdigest(), sig)
            for key in keys for m in methods
        )
        if not ok:
            return 401, {}, b"invalid temp url signature"
        gw = self.rgw.as_user(account)
        return await self._object(method, gw, container, obj, hdrs,
                                  body, {})

    # -- routing (RGWHandler_REST_SWIFT) -----------------------------------
    async def _route(self, method: str, raw_path: str, hdrs: dict,
                     body: bytes):
        import urllib.parse

        path, _, rawq = raw_path.partition("?")
        query = {}
        for part in rawq.split("&") if rawq else ():
            k, _, v = part.partition("=")
            query[urllib.parse.unquote(k)] = urllib.parse.unquote(v)
        if path.rstrip("/") == "/auth/v1.0":
            return await self._auth_handshake(hdrs)
        if "temp_url_sig" in query and "temp_url_expires" in query:
            # pre-signed access: no token at all (TempURLEngine,
            # reference rgw_swift_auth.h:176)
            return await self._temp_url(method, path, query, hdrs,
                                        body)
        uid = await self._validate_token(hdrs.get("x-auth-token", ""))
        parts = [p for p in path.split("/") if p]
        # /v1/AUTH_<account>[/container[/object...]]
        if len(parts) < 2 or parts[0] != "v1" \
                or not parts[1].startswith("AUTH_"):
            return 404, {}, b"not found"
        account = parts[1][len("AUTH_"):]
        if account != uid:
            raise RGWError("AccessDenied", "cross-account access")
        gw = self.rgw.as_user(uid)
        if len(parts) == 2:
            if method == "POST" and "bulk-delete" in query:
                return await self._bulk_delete(gw, hdrs, body)
            return await self._account(method, gw, uid, hdrs)
        container = parts[2]
        if len(parts) == 3:
            return await self._container(method, gw, container, query,
                                         hdrs)
        obj = "/".join(parts[3:])
        return await self._object(method, gw, container, obj, hdrs,
                                  body, query)

    async def _dlo_read(self, method: str, gw: RGWLite, entry: dict,
                        dlo: str, rng):
        """Dynamic Large Object GET/HEAD: concatenate every object
        under <container>/<prefix> in name order (Swift DLO
        semantics)."""
        from ceph_tpu.services.rgw import manifest_window

        sc, _, prefix = dlo.lstrip("/").partition("/")
        segs = []
        marker = ""
        while True:
            listing = await gw.list_objects(sc, prefix=prefix,
                                            marker=marker,
                                            max_keys=1000)
            segs += listing["contents"]
            if not listing.get("is_truncated"):
                break
            marker = segs[-1]["key"]
        total = sum(int(c["size"]) for c in segs)
        if method == "HEAD":
            return 200, _dlo_headers(entry, total), b""
        start, end = (0, total - 1) if rng is None else \
            (rng[0], min(rng[1], total - 1))
        if rng is not None and start >= total:
            return 416, {"content-range": f"bytes */{total}"}, b""
        chunks = []
        for i, off, length in manifest_window(
                [int(c["size"]) for c in segs], start, end):
            got = await gw.get_object(
                sc, segs[i]["key"], range_=(off, off + length - 1))
            chunks.append(got["data"])
        body = b"".join(chunks)
        hdrs = _dlo_headers(entry, len(body))
        if rng is not None:
            hdrs["content-range"] = f"bytes {start}-{end}/{total}"
            return 206, hdrs, body
        return 200, hdrs, body

    async def _bulk_delete(self, gw: RGWLite, hdrs: dict,
                           body: bytes):
        """Swift bulk delete (POST ?bulk-delete, the bulk middleware):
        newline-separated "container/object" (or bare "container")
        paths, per-item outcomes summarised in a JSON report — one
        bad item must not abort the rest."""
        import urllib.parse
        paths = [urllib.parse.unquote(ln.strip())
                 for ln in body.decode(errors="replace").splitlines()
                 if ln.strip()]
        if len(paths) > 10000:
            return 413, {}, b"too many items"
        deleted = not_found = 0
        errors: list[list[str]] = []
        for p in paths:
            container, _, obj = p.lstrip("/").partition("/")
            try:
                if obj:
                    await gw.delete_object(container, obj)
                else:
                    await gw.delete_bucket(container)
                deleted += 1
            except RGWError as e:
                if e.code in ("NoSuchKey", "NoSuchBucket"):
                    not_found += 1
                else:
                    errors.append([p, e.code])
        report = {
            "Number Deleted": deleted,
            "Number Not Found": not_found,
            "Response Status": "200 OK" if not errors
            else "400 Bad Request",
            "Errors": errors,
        }
        return (200, {"content-type": "application/json"},
                json.dumps(report).encode())

    async def _account(self, method: str, gw: RGWLite, uid: str,
                       hdrs: dict | None = None):
        hdrs = hdrs or {}
        if method == "POST":
            # Swift account metadata (x-account-meta-* sets,
            # x-remove-account-meta-* deletes), kept on the user
            # record like the reference's user attrs
            rec = await self.users.get(uid)
            stored = dict(rec.get("swift_meta") or {})
            sets, removes = _meta_headers_for(hdrs, "account")
            stored.update(sets)
            for k in removes:
                stored.pop(k, None)
            await self.users.set_swift_meta(uid, stored)
            return 204, {}, b""
        if method not in ("GET", "HEAD"):
            return 405, {}, b""
        out = []
        for b in await gw.list_buckets():
            try:
                meta = await gw._bucket_meta(b)
            except RGWError:
                continue
            if meta.get("owner") != uid:
                continue
            nbytes, nobj = await gw._bucket_usage(b)
            out.append({"name": b, "count": nobj, "bytes": nbytes})
        rh = {"content-type": "application/json",
              "x-account-container-count": str(len(out)),
              "x-account-object-count":
                  str(sum(c["count"] for c in out)),
              "x-account-bytes-used":
                  str(sum(c["bytes"] for c in out))}
        rec = await self.users.get(uid)
        for k, v in sorted((rec.get("swift_meta") or {}).items()):
            rh[f"x-account-meta-{k}"] = v
        return 200, rh, json.dumps(out).encode()

    async def _container(self, method: str, gw: RGWLite, name: str,
                         query: dict | None = None,
                         hdrs: dict | None = None):
        query = query or {}
        hdrs = hdrs or {}
        if method == "PUT":
            cmeta = _container_meta_headers(hdrs)
            try:
                await gw.create_bucket(name)
                status = 201
            except RGWError as e:
                if e.code != "BucketAlreadyExists":
                    raise
                status = 202            # Swift: idempotent accept
                if cmeta[0] or cmeta[1]:
                    # an EXISTING container's metadata is owner-gated
                    # (the create path made us the owner already)
                    await gw._check_bucket(name, "FULL_CONTROL")
            if cmeta[0] or cmeta[1]:
                await self._apply_container_meta(gw, name, cmeta)
            return status, {}, b""
        if method == "POST":
            # Swift container metadata update: x-container-meta-* sets,
            # x-remove-container-meta-* deletes (rgw_rest_swift's
            # REST_Swift container POST)
            await gw._check_bucket(name, "FULL_CONTROL")
            await self._apply_container_meta(
                gw, name, _container_meta_headers(hdrs))
            return 204, {}, b""
        if method == "DELETE":
            await gw.delete_bucket(name)
            return 204, {}, b""
        if method in ("GET", "HEAD"):
            # container headers reflect the WHOLE container (Swift
            # semantics), independent of the listing page below
            bmeta = await gw._check_bucket(name, "READ")
            nbytes, nobj = await gw._bucket_usage(name, bmeta)
            rh = {"content-type": "application/json",
                  "x-container-object-count": str(nobj),
                  "x-container-bytes-used": str(nbytes)}
            for k, v in sorted((bmeta.get("swift_meta")
                                or {}).items()):
                rh[f"x-container-meta-{k}"] = v
            # Swift listing semantics: ?limit= caps the page, ?marker=
            # resumes after a name, ?prefix= filters — clients page
            # through arbitrarily large containers
            try:
                limit = max(0, min(int(query.get("limit", 10000)),
                                   10000))
            except ValueError:
                limit = 10000
            if limit == 0:
                # terminal empty page (never "truncated": a paging
                # client could not advance its marker and would spin)
                return 200, rh, b"[]"
            listing = await gw.list_objects(
                name, prefix=query.get("prefix", ""),
                marker=query.get("marker", ""), max_keys=limit,
                delimiter=query.get("delimiter", ""))
            out = [{
                "name": c["key"], "bytes": c["size"],
                "hash": c["etag"],
                "last_modified": _iso(c["mtime"]),
            } for c in listing["contents"]]
            # Swift renders rolled-up prefixes as subdir entries
            out += [{"subdir": cp}
                    for cp in listing.get("common_prefixes", ())]
            out.sort(key=lambda e: e.get("name", e.get("subdir",
                                                       "")))
            if listing.get("is_truncated"):
                rh["x-container-truncated"] = "true"
            return 200, rh, json.dumps(out).encode()
        return 405, {}, b""

    @staticmethod
    async def _apply_container_meta(gw: RGWLite, name: str,
                                    cmeta: tuple[dict, list]) -> None:
        sets, removes = cmeta
        bmeta = await gw._bucket_meta(name)
        stored = dict(bmeta.get("swift_meta") or {})
        stored.update(sets)
        for k in removes:
            stored.pop(k, None)
        bmeta["swift_meta"] = stored
        await gw._put_bucket_meta(name, bmeta)

    async def _reap_if_expired(self, gw: RGWLite, container: str,
                               obj: str, entry: dict) -> bool:
        """Swift object expiry on the read path: an object past its
        X-Delete-At reads as absent and is deleted inline (the
        object-expirer daemon's reconciliation, collapsed)."""
        if not _expired(entry, time.time()):
            return False
        try:
            await gw.delete_object(container, obj)
        except RGWError:
            pass                  # already raced away
        return True

    async def expirer_pass(self, now: float | None = None) -> dict:
        """One object-expirer sweep over every container (Swift's
        object-expirer daemon role): reap objects whose X-Delete-At
        has passed.  Returns container -> [reaped names]."""
        now = time.time() if now is None else now
        gw = self.rgw
        reaped: dict[str, list[str]] = {}
        for container in await gw.list_buckets():
            # ONE index read per container, not one head per object:
            # the entries already carry the meta the check needs
            try:
                bmeta = await gw._bucket_meta(container)
                index = await gw._index_all(container, bmeta)
            except RGWError:
                continue
            for key, raw in index.items():
                entry = json.loads(raw)
                if entry.get("delete_marker"):
                    continue
                if _expired(entry, now):
                    try:
                        await gw.delete_object(container, key)
                    except RGWError:
                        continue
                    reaped.setdefault(container, []).append(key)
        return reaped

    async def _object(self, method: str, gw: RGWLite, container: str,
                      obj: str, hdrs: dict, body: bytes,
                      query: dict | None = None):
        query = query or {}
        mm = query.get("multipart-manifest", "")
        if method == "PUT" and mm == "put":
            # SLO manifest: JSON [{path, etag?, size_bytes?}, ...]
            try:
                listing = json.loads(body.decode())
                segments = []
                for s in listing:
                    sb, _, sk = str(s["path"]).lstrip("/").partition("/")
                    if not sb or not sk:
                        raise ValueError(s.get("path"))
                    segments.append({
                        "bucket": sb, "key": sk,
                        "etag": s.get("etag", ""),
                        "size_bytes": s.get("size_bytes", 0),
                    })
            except (ValueError, TypeError, KeyError) as e:
                return 400, {}, f"bad manifest: {e!r}".encode()
            slo_meta = {k[len("x-object-meta-"):]: v
                        for k, v in hdrs.items()
                        if k.startswith("x-object-meta-")}
            exp = _parse_expiry(hdrs)
            if exp is not None:
                slo_meta["delete_at"] = exp
            out = await gw.put_slo_manifest(
                container, obj, segments,
                content_type=hdrs.get("content-type",
                                      "application/octet-stream"),
                metadata=slo_meta)
            return 201, {"etag": out["etag"]}, b""
        if method == "GET" and mm == "get":
            entry = await gw.head_object(container, obj)
            descr = _slo_descr(entry)
            if descr is None:
                return 400, {}, b"not an SLO manifest"
            return 200, {"content-type": "application/json"}, \
                json.dumps(descr).encode()
        if method == "DELETE" and mm == "delete":
            # delete the manifest AND its segments (Swift semantics)
            entry = await gw.head_object(container, obj)
            descr = _slo_descr(entry) or []
            await gw.delete_object(container, obj)
            for s in descr:
                sb, _, sk = str(s["name"]).lstrip("/").partition("/")
                try:
                    await gw.delete_object(sb, sk)
                except RGWError:
                    pass            # already gone / foreign container
            return 204, {}, b""
        if method == "PUT":
            # slo_segments is SERVER-owned metadata: a client header
            # forging it would poison manifest introspection/delete
            meta = _client_meta(hdrs)
            exp = _parse_expiry(hdrs)
            if exp is not None:
                meta["delete_at"] = exp
            dlo = hdrs.get("x-object-manifest", "")
            if dlo:
                # DLO: zero-byte manifest whose GET concatenates every
                # object under <container>/<prefix> (Swift dynamic
                # large objects)
                meta["dlo_manifest"] = dlo
            out = await gw.put_object(
                container, obj, body,
                content_type=hdrs.get("content-type",
                                      "application/octet-stream"),
                metadata=meta)
            return 201, {"etag": out["etag"]}, b""
        if method == "POST":
            # Swift POST REPLACES the object metadata set (unlike S3
            # copy-with-metadata); -lite rewrites the index entry.
            # X-Object-Manifest follows Swift semantics: present sets
            # the DLO pointer, absent drops it (clients re-send it to
            # keep a manifest through a metadata update).
            await gw._check_bucket(container, "WRITE")
            entry = await gw.head_object(container, obj)
            if await self._reap_if_expired(gw, container, obj,
                                           entry):
                return 404, {}, b""      # updating a ghost lies
            meta = _client_meta(hdrs)
            exp = _parse_expiry(hdrs)
            if exp is not None:
                meta["delete_at"] = exp
            elif "x-remove-delete-at" not in hdrs:
                # POST replaces the meta set, but expiry survives
                # unless explicitly removed (Swift keeps X-Delete-At
                # through metadata updates)
                old_exp = (entry.get("meta") or {}).get("delete_at")
                if old_exp is not None:
                    meta["delete_at"] = old_exp
            slo = (entry.get("meta") or {}).get("slo_segments")
            if slo is not None:
                meta["slo_segments"] = slo     # server-owned: sticky
            dlo = hdrs.get("x-object-manifest", "")
            if dlo and not entry.get("slo"):
                meta["dlo_manifest"] = dlo
            entry["meta"] = meta
            bmeta = await gw._bucket_meta(container)
            await gw._index_set(container, bmeta, obj,
                                json.dumps(entry).encode())
            return 202, {}, b""
        if method == "DELETE":
            await gw.delete_object(container, obj)
            return 204, {}, b""
        if method in ("GET", "HEAD"):
            rng = None
            rh = hdrs.get("range", "")
            if rh.startswith("bytes=") and "-" in rh[6:]:
                a, _, b = rh[6:].partition("-")
                if a:
                    rng = (int(a), int(b) if b else (1 << 62))
                    if rng[1] < rng[0]:
                        # RFC 9110: a syntactically inverted range is
                        # INVALID — ignore it and serve the full body
                        rng = None
            if method == "HEAD":
                entry = await gw.head_object(container, obj)
                if await self._reap_if_expired(gw, container, obj,
                                               entry):
                    return 404, {}, b""
                dlo = (entry.get("meta") or {}).get("dlo_manifest")
                if dlo and not entry.get("slo"):
                    return await self._dlo_read("HEAD", gw, entry,
                                                dlo, rng)
                return 200, _obj_headers(entry), b""
            got = await gw.get_object(container, obj, range_=rng)
            if await self._reap_if_expired(gw, container, obj, got):
                return 404, {}, b""
            dlo = (got.get("meta") or {}).get("dlo_manifest")
            if dlo and not got.get("slo"):
                # a manifest's stored body is empty: the probe wasted
                # nothing and the hot plain-GET path stays one read
                return await self._dlo_read("GET", gw, got, dlo, rng)
            rh = _obj_headers(got)
            if rng is not None:
                size = int(got.get("size", 0))
                if rng[0] >= size:
                    # unsatisfiable range: 416, never a 206 whose
                    # Content-Range would read end < start
                    return 416, {"content-range": f"bytes */{size}"}, \
                        b""
                # the entity is the RANGE: frame it correctly or a
                # keep-alive peer blocks waiting for the full size
                end = min(rng[1], size - 1)
                rh["content-length"] = str(len(got["data"]))
                rh["content-range"] = f"bytes {rng[0]}-{end}/{size}"
                return 206, rh, got["data"]
            return 200, rh, got["data"]
        return 405, {}, b""


_SERVER_META = ("slo_segments", "dlo_manifest", "delete_at")


def _parse_expiry(hdrs: dict) -> float | None:
    """X-Delete-At (epoch) / X-Delete-After (relative seconds) —
    Swift object expiry.  Past or non-numeric values are 400s."""
    at = hdrs.get("x-delete-at")
    after = hdrs.get("x-delete-after")
    if at is None and after is None:
        return None
    # non-numeric values raise ValueError, which the dispatcher
    # renders as the 400 Swift answers
    when = float(at) if at is not None \
        else time.time() + float(after)
    if not when > time.time():
        # the inverted comparison catches NaN too — storing it would
        # read as instantly-expired (silent data loss on first GET)
        raise ValueError("X-Delete-At is in the past")
    return when


def _expired(entry: dict, now: float) -> bool:
    """ONE expiry predicate for the read-path reap and the sweep."""
    exp = (entry.get("meta") or {}).get("delete_at")
    return exp is not None and float(exp) <= now


def _meta_headers_for(hdrs: dict, scope: str) -> tuple[dict, list]:
    """(sets, removes) from x-<scope>-meta-* /
    x-remove-<scope>-meta-* headers (scope: container / account)."""
    pfx = f"x-{scope}-meta-"
    rm_pfx = f"x-remove-{scope}-meta-"
    sets = {k[len(pfx):]: v for k, v in hdrs.items()
            if k.startswith(pfx) and len(k) > len(pfx)}
    removes = [k[len(rm_pfx):] for k in hdrs
               if k.startswith(rm_pfx)]
    return sets, removes


def _container_meta_headers(hdrs: dict) -> tuple[dict, list]:
    return _meta_headers_for(hdrs, "container")


def _client_meta(hdrs: dict) -> dict:
    """x-object-meta-* minus the server-owned keys (forging them would
    poison manifest introspection/resolution)."""
    return {k[len("x-object-meta-"):]: v
            for k, v in hdrs.items()
            if k.startswith("x-object-meta-")
            and k[len("x-object-meta-"):] not in _SERVER_META}


def _dlo_headers(entry: dict, size: int) -> dict:
    hdrs = _obj_headers(entry)
    hdrs["content-length"] = str(size)
    hdrs["x-object-manifest"] = entry["meta"]["dlo_manifest"]
    return hdrs


def _slo_descr(entry: dict) -> list | None:
    """The trusted manifest description: entry['slo'] is set only by
    put_slo_manifest (user metadata cannot forge the server flag)."""
    if not entry.get("slo"):
        return None
    descr = (entry.get("meta") or {}).get("slo_segments")
    if not isinstance(descr, list) or not all(
            isinstance(s, dict) and "name" in s for s in descr):
        return None
    return descr


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000000",
                         time.gmtime(ts))


def _obj_headers(entry: dict) -> dict:
    hdrs = {
        "content-type": entry.get("content_type",
                                  "application/octet-stream"),
        "etag": entry.get("etag", ""),
        "x-timestamp": str(entry.get("mtime", 0.0)),
        "content-length": str(entry.get("size", 0)),
    }
    for k, v in (entry.get("meta") or {}).items():
        if k not in _SERVER_META:
            hdrs[f"x-object-meta-{k}"] = str(v)
    exp = (entry.get("meta") or {}).get("delete_at")
    if exp is not None:
        hdrs["x-delete-at"] = str(int(float(exp)))
    return hdrs
