"""rbd-mirror-lite: snapshot-based cross-cluster image replication.

The role of reference src/tools/rbd_mirror (ImageReplayer.cc) in its
modern SNAPSHOT-BASED mode (journal mode is the legacy path): the mirror
daemon periodically takes a mirror snapshot on the primary image, ships
the delta since the last mirrored snapshot to the secondary cluster, and
marks the same snapshot there — the secondary is a crash-consistent
point-in-time copy that advances snapshot by snapshot. Resumability
falls out of the snapshot names themselves: the newest mirror snapshot
present on BOTH sides is the sync base, so a restarted daemon (or a
re-pointed one) needs no extra state.

Delta computation reads the image at the new and base snapshots and
ships only changed blocks (the diff-iterate role; the -lite tradeoff is
reading both versions instead of consulting an object map).
"""

from __future__ import annotations

import asyncio

from ceph_tpu.common.log import Dout
from ceph_tpu.services.rbd import RBD, Image, RBDError

log = Dout("rbd")

SNAP_PREFIX = ".mirror."


def _mirror_snaps(img: Image) -> list[int]:
    out = []
    for name in img.snaps:
        if name.startswith(SNAP_PREFIX):
            try:
                out.append(int(name[len(SNAP_PREFIX):]))
            except ValueError:
                continue
    return sorted(out)


class RBDMirror:
    def __init__(self, src: RBD, dst: RBD, poll_interval: float = 0.5):
        self.src = src
        self.dst = dst
        self.poll_interval = poll_interval
        self._task: asyncio.Task | None = None
        self._stopped = False
        self.bytes_shipped = 0

    # -- one image ---------------------------------------------------------
    async def mirror_image(self, name: str) -> int:
        """Advance the secondary to a fresh primary snapshot; returns
        bytes shipped (0 when nothing changed since the base)."""
        src_img = await self.src.open(name)
        # sync base = newest mirror snap present on both sides
        try:
            dst_img = await self.dst.open(name)
        except RBDError:
            await self.dst.create(name, size=src_img.size,
                                  order=src_img.order)
            dst_img = await self.dst.open(name)
        src_marks = set(_mirror_snaps(src_img))
        dst_marks = set(_mirror_snaps(dst_img))
        common = sorted(src_marks & dst_marks)
        base = common[-1] if common else None

        # new mirror point on the primary
        new_mark = (max(src_marks | dst_marks) + 1
                    if (src_marks | dst_marks) else 1)
        new_snap = f"{SNAP_PREFIX}{new_mark}"
        await src_img.snap_create(new_snap)
        new_size = int(src_img.snaps[new_snap]["size"])
        if dst_img.size != new_size:
            await dst_img.resize(new_size)

        base_snap = f"{SNAP_PREFIX}{base}" if base is not None else None
        shipped = 0
        bs = src_img.obj_size
        for off in range(0, new_size, bs):
            want = min(bs, new_size - off)
            cur = await src_img.read_at_snap(new_snap, off, want)
            if base_snap is not None and base_snap in src_img.snaps:
                prev = await src_img.read_at_snap(base_snap, off, want)
                if cur == prev:
                    continue            # unchanged block: skip
            await dst_img.write(off, cur)
            shipped += len(cur)
        # mark the same point on the secondary, then retire older marks
        # (one base is enough; the reference keeps a bounded trail)
        await dst_img.snap_create(new_snap)
        for mark in sorted(src_marks):
            if mark != new_mark:
                try:
                    await src_img.snap_remove(f"{SNAP_PREFIX}{mark}")
                except RBDError:
                    pass
        for mark in sorted(dst_marks):
            if mark != new_mark:
                try:
                    await dst_img.snap_remove(f"{SNAP_PREFIX}{mark}")
                except RBDError:
                    pass
        self.bytes_shipped += shipped
        log.dout(5, "mirrored %s to mark %d (%d bytes)", name, new_mark,
                 shipped)
        return shipped

    async def sync_once(self) -> int:
        total = 0
        for name in await self.src.list():
            try:
                total += await self.mirror_image(name)
            except (RBDError, IOError) as e:
                log.derr("mirror of %s failed: %s", name, e)
        return total

    # -- daemon form -------------------------------------------------------
    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        while not self._stopped:
            try:
                await self.sync_once()
            except Exception as e:           # noqa: BLE001
                log.derr("mirror pass failed: %s", e)
            try:
                await asyncio.sleep(self.poll_interval)
            except asyncio.CancelledError:
                return

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
