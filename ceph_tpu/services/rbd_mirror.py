"""rbd-mirror-lite: cross-cluster image replication, both modes.

The role of reference src/tools/rbd_mirror (ImageReplayer.cc):

SNAPSHOT mode (RBDMirror): the daemon periodically takes a mirror
snapshot on the primary image, ships the delta since the last mirrored
snapshot to the secondary cluster, and marks the same snapshot there —
the secondary is a crash-consistent point-in-time copy that advances
snapshot by snapshot.  Resumability falls out of the snapshot names
themselves: the newest mirror snapshot present on BOTH sides is the
sync base, so a restarted daemon needs no extra state.

JOURNAL mode (JournalReplayer): the daemon registers as a client of
the primary image's journal (services/rbd_journal.py, the
src/journal/Journaler.h:32 role) and TAILS the entry stream, applying
each event to the secondary image and persisting its commit position
in the journal header (ImageReplayer.cc replay path).  Because the
journal — not the image — is the source of truth, the secondary
converges even on entries the crashed primary appended but never
applied, and a restarted replayer resumes exactly at its commit
position.  Consumed objects are trimmed once every registered client
has passed them.

Delta computation in snapshot mode reads the image at the new and base
snapshots and ships only changed blocks (the diff-iterate role; the
-lite tradeoff is reading both versions instead of consulting an
object map).
"""

from __future__ import annotations

import asyncio

from ceph_tpu.common.log import Dout
from ceph_tpu.services.rbd import RBD, Image, RBDError

log = Dout("rbd")

SNAP_PREFIX = ".mirror."


def _mirror_snaps(img: Image) -> list[int]:
    out = []
    for name in img.snaps:
        if name.startswith(SNAP_PREFIX):
            try:
                out.append(int(name[len(SNAP_PREFIX):]))
            except ValueError:
                continue
    return sorted(out)


class RBDMirror:
    def __init__(self, src: RBD, dst: RBD, poll_interval: float = 0.5):
        self.src = src
        self.dst = dst
        self.poll_interval = poll_interval
        self._task: asyncio.Task | None = None
        self._stopped = False
        self.bytes_shipped = 0

    # -- one image ---------------------------------------------------------
    async def mirror_image(self, name: str) -> int:
        """Advance the secondary to a fresh primary snapshot; returns
        bytes shipped (0 when nothing changed since the base)."""
        src_img = await self.src.open(name)
        # sync base = newest mirror snap present on both sides
        try:
            dst_img = await self.dst.open(name)
        except RBDError:
            await self.dst.create(name, size=src_img.size,
                                  order=src_img.order)
            dst_img = await self.dst.open(name)
        src_marks = set(_mirror_snaps(src_img))
        dst_marks = set(_mirror_snaps(dst_img))
        common = sorted(src_marks & dst_marks)
        base = common[-1] if common else None

        # new mirror point on the primary
        new_mark = (max(src_marks | dst_marks) + 1
                    if (src_marks | dst_marks) else 1)
        new_snap = f"{SNAP_PREFIX}{new_mark}"
        await src_img.snap_create(new_snap)
        new_size = int(src_img.snaps[new_snap]["size"])
        if dst_img.size != new_size:
            await dst_img.resize(new_size)

        base_snap = f"{SNAP_PREFIX}{base}" if base is not None else None
        shipped = 0
        bs = src_img.obj_size
        for off in range(0, new_size, bs):
            want = min(bs, new_size - off)
            cur = await src_img.read_at_snap(new_snap, off, want)
            if base_snap is not None and base_snap in src_img.snaps:
                prev = await src_img.read_at_snap(base_snap, off, want)
                if cur == prev:
                    continue            # unchanged block: skip
            await dst_img.write(off, cur)
            shipped += len(cur)
        # mark the same point on the secondary, then retire older marks
        # (one base is enough; the reference keeps a bounded trail)
        await dst_img.snap_create(new_snap)
        for mark in sorted(src_marks):
            if mark != new_mark:
                try:
                    await src_img.snap_remove(f"{SNAP_PREFIX}{mark}")
                except RBDError:
                    pass
        for mark in sorted(dst_marks):
            if mark != new_mark:
                try:
                    await dst_img.snap_remove(f"{SNAP_PREFIX}{mark}")
                except RBDError:
                    pass
        self.bytes_shipped += shipped
        log.dout(5, "mirrored %s to mark %d (%d bytes)", name, new_mark,
                 shipped)
        return shipped

    async def sync_once(self) -> int:
        total = 0
        for name in await self.src.list():
            try:
                total += await self.mirror_image(name)
            except (RBDError, IOError) as e:
                log.derr("mirror of %s failed: %s", name, e)
        return total

    # -- daemon form -------------------------------------------------------
    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        while not self._stopped:
            try:
                await self.sync_once()
            except Exception as e:           # noqa: BLE001
                log.derr("mirror pass failed: %s", e)
            try:
                await asyncio.sleep(self.poll_interval)
            except asyncio.CancelledError:
                return

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass


class JournalReplayer:
    """Journal-mode mirroring (ImageReplayer.cc): tail the primary
    image's journal and apply its entries to the secondary image."""

    def __init__(self, src: RBD, dst: RBD, client_id: str = "mirror",
                 poll_interval: float = 0.2):
        self.src = src
        self.dst = dst
        self.client_id = client_id
        self.poll_interval = poll_interval
        self._task: asyncio.Task | None = None
        self._stopped = False
        self.entries_applied = 0
        self.images_bootstrapped = 0
        self._journals: dict[str, object] = {}   # name -> ImageJournal

    async def _src_image_meta(self, name: str) -> tuple[str, dict]:
        image_id = await self.src.image_id(name)
        return image_id, await self.src.image_header(image_id)

    async def _bootstrap(self, name: str, dst_img) -> None:
        """Full image sync (ImageReplayer bootstrap): the journal was
        trimmed past this client's position, so the entry stream alone
        cannot reconstruct the secondary.  Copy the primary's current
        blocks — SPARSELY: the object map answers which blocks exist,
        so an almost-empty image syncs in a handful of reads, not
        size/obj_size of them (the reference's object-map-aware sync)."""
        src_img = await self.src.open(name)
        if dst_img.size != src_img.size:
            await dst_img.resize(src_img.size)
        bs = src_img.obj_size
        copied = 0
        for objno in range(-(-src_img.size // bs)):
            off = objno * bs
            want = min(bs, src_img.size - off)
            if not await src_img._obj_exists(objno):
                if await dst_img._obj_exists(objno):
                    # divergent secondary block with no primary
                    # counterpart: zero it, or it survives the sync
                    await dst_img.write(off, b"\0" * want)
                continue
            await dst_img.write(off, await src_img.read(off, want))
            copied += want
        self.images_bootstrapped += 1
        log.dout(5, "journal mirror bootstrapped %s (%d of %d bytes)",
                 name, copied, src_img.size)

    async def replay_image(self, name: str) -> int:
        """Apply every journal entry newer than this replayer's commit
        position to the secondary; returns entries applied.  Reads ONLY
        the journal and the primary header — the primary image handle
        may be dead (the crash-consistency property journal mode buys
        over snapshot mode).  A journal trimmed past our position
        triggers a full-image bootstrap first."""
        from ceph_tpu.services.rbd_journal import (
            ImageJournal,
            replay_to_image,
        )

        journal = self._journals.get(name)
        if journal is None:
            image_id, _ = await self._src_image_meta(name)
            journal = ImageJournal(self.src.ioctx, image_id,
                                   client_id=self.client_id)
            await journal.register()
            self._journals[name] = journal
        try:
            dst_img = await self.dst.open(name)
        except RBDError:
            _, header = await self._src_image_meta(name)
            await self.dst.create(name, size=int(header["size"]),
                                  order=int(header["order"]))
            dst_img = await self.dst.open(name)
        pos = await journal.committed()
        horizon = await journal.trim_horizon()
        from_tid = None
        if pos + 1 < horizon:
            await self._bootstrap(name, dst_img)
            # the copy subsumes every trimmed entry; surviving entries
            # re-apply idempotently on top of it
            from_tid = horizon - 1
        applied = await replay_to_image(dst_img, journal,
                                        from_tid=from_tid)
        if from_tid is not None and applied == 0:
            # bootstrap with an empty surviving stream: persist the
            # position or every pass would re-bootstrap
            await journal.commit(from_tid)
        if applied:
            await journal.trim()
        await dst_img.close()
        self.entries_applied += applied
        return applied

    async def sync_once(self) -> int:
        total = 0
        for name in await self.src.list():
            try:
                total += await self.replay_image(name)
            except (RBDError, IOError) as e:
                log.derr("journal replay of %s failed: %s", name, e)
        return total

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        while not self._stopped:
            try:
                await self.sync_once()
            except Exception as e:           # noqa: BLE001
                log.derr("journal replay pass failed: %s", e)
            try:
                await asyncio.sleep(self.poll_interval)
            except asyncio.CancelledError:
                return

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
