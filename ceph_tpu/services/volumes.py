"""Volumes: CephFS subvolume management (mgr volumes module role).

Reference src/pybind/mgr/volumes: subvolumes are operator-managed
directory trees under ``/volumes/<group>/<name>`` with a ``.meta``
sidecar (the reference stores the same under a uuid indirection and a
``.meta`` config file), created/removed/listed through ``ceph fs
subvolume`` verbs.  Subvolume snapshots ride the MDS snap realms
(``.snap`` of the subvolume root).

-lite divergence: no uuid indirection layer and no async purge queue —
removal walks the tree inline (trees are operator-scale here); quota is
recorded in the meta sidecar (advisory, as before the reference wired
subvolume quotas into the MDS).
"""

from __future__ import annotations

import json
import time

from ceph_tpu.client.fs import CephFS, FSError

NO_GROUP = "_nogroup"
META = ".meta"
ENOENT = -2
EEXIST = -17
ENOTEMPTY = -39
EINVAL = -22


class VolumeManager:
    def __init__(self, fs: CephFS):
        self.fs = fs

    # -- paths -------------------------------------------------------------
    @staticmethod
    def _group_path(group: str | None) -> str:
        return f"/volumes/{group or NO_GROUP}"

    @classmethod
    def _subvol_path(cls, name: str, group: str | None) -> str:
        if "/" in name or name.startswith("."):
            raise FSError(EINVAL, f"bad subvolume name {name!r}")
        return f"{cls._group_path(group)}/{name}"

    # -- groups ------------------------------------------------------------
    async def group_create(self, group: str, mode: int = 0o755) -> None:
        if "/" in group or group.startswith((".", "_")):
            raise FSError(EINVAL, f"bad group name {group!r}")
        await self.fs.mkdirs(self._group_path(group), mode)

    async def group_ls(self) -> list[str]:
        try:
            names = await self.fs.readdir("/volumes")
        except FSError as e:
            if e.rc != ENOENT:
                raise
            return []
        return sorted(n for n in names if n != NO_GROUP)

    async def group_rm(self, group: str) -> None:
        path = self._group_path(group)
        if await self.fs.readdir(path):
            raise FSError(ENOTEMPTY,
                          f"group {group!r} still has subvolumes")
        await self.fs.rmdir(path)

    # -- subvolumes ---------------------------------------------------------
    async def create(self, name: str, group: str | None = None,
                     mode: int = 0o755, size: int = 0) -> str:
        """Create the subvolume directory + meta sidecar; returns the
        data path handed to mounters (``fs subvolume getpath``)."""
        path = self._subvol_path(name, group)
        try:
            await self.fs.stat(path)
            raise FSError(EEXIST, f"subvolume {name!r} exists")
        except FSError as e:
            if e.rc != ENOENT:
                raise
        await self.fs.mkdirs(path, mode)
        await self.fs.write_file(f"{path}/{META}", json.dumps({
            "name": name, "group": group or NO_GROUP,
            "created": time.time(), "mode": mode, "size": size,
            "state": "complete",
        }).encode())
        return path

    async def ls(self, group: str | None = None) -> list[str]:
        try:
            names = await self.fs.readdir(self._group_path(group))
        except FSError as e:
            if e.rc != ENOENT:
                raise
            return []
        return sorted(names)

    async def getpath(self, name: str, group: str | None = None) -> str:
        path = self._subvol_path(name, group)
        await self.fs.stat(path)           # ENOENT surfaces here
        return path

    async def info(self, name: str, group: str | None = None) -> dict:
        path = await self.getpath(name, group)
        meta = json.loads(await self.fs.read_file(f"{path}/{META}"))
        entries = await self.fs.readdir(path)
        meta["path"] = path
        meta["entries"] = sum(1 for n in entries if n != META)
        meta["snapshots"] = sorted(await self.snapshot_ls(name, group))
        return meta

    async def rm(self, name: str, group: str | None = None,
                 force: bool = False) -> None:
        """Remove the subvolume tree.  Refuses while snapshots cover
        it (matching the reference's snapshot-retention refusal)
        unless ``force`` also removes the snapshots first."""
        path = await self.getpath(name, group)
        snaps = await self.snapshot_ls(name, group)
        if snaps:
            if not force:
                raise FSError(ENOTEMPTY,
                              f"subvolume {name!r} has snapshots "
                              f"{snaps}; use force")
            for s in snaps:
                await self.fs.rmsnap(path, s)
        await self._rmtree(path)

    async def _rmtree(self, path: str) -> None:
        """Depth-first removal (the reference defers this to an async
        purge-queue thread; inline at -lite scale)."""
        for name, d in sorted((await self.fs.readdir(path)).items()):
            child = f"{path}/{name}"
            if d.get("type") == "dir":
                await self._rmtree(child)
            else:
                await self.fs.unlink(child)
        await self.fs.rmdir(path)

    # -- snapshots (subvolume .snap realms) ---------------------------------
    async def snapshot_create(self, name: str, snap: str,
                              group: str | None = None) -> int:
        path = await self.getpath(name, group)
        return await self.fs.mksnap(path, snap)

    async def snapshot_ls(self, name: str,
                          group: str | None = None) -> list[str]:
        path = await self.getpath(name, group)
        return sorted(await self.fs.listsnaps(path))

    async def snapshot_rm(self, name: str, snap: str,
                          group: str | None = None) -> None:
        path = await self.getpath(name, group)
        await self.fs.rmsnap(path, snap)
