"""Volumes: CephFS subvolume management (mgr volumes module role).

Reference src/pybind/mgr/volumes: subvolumes are operator-managed
directory trees under ``/volumes/<group>/<name>`` with a ``.meta``
sidecar (the reference stores the same under a uuid indirection and a
``.meta`` config file), created/removed/listed through ``ceph fs
subvolume`` verbs.  Subvolume snapshots ride the MDS snap realms
(``.snap`` of the subvolume root).

-lite divergence: no uuid indirection layer and no async purge queue —
removal walks the tree inline (trees are operator-scale here).  A
subvolume's size IS enforced: it becomes a max_bytes directory quota
on the subvolume root (the ceph.quota vxattr wiring), adjustable with
``fs subvolume resize``.
"""

from __future__ import annotations

import json
import time

from ceph_tpu.client.fs import CephFS, FSError

NO_GROUP = "_nogroup"
META = ".meta"
ENOENT = -2
EEXIST = -17
ENOTEMPTY = -39
EINVAL = -22


class VolumeManager:
    def __init__(self, fs: CephFS):
        self.fs = fs

    # -- paths -------------------------------------------------------------
    @staticmethod
    def _group_path(group: str | None) -> str:
        # Same validation as group_create: the FS client collapses ".."
        # lexically, so an unvalidated group like "../.." would aim every
        # subvolume verb (including rm --force) outside /volumes.
        if group is not None and (
            "/" in group or group.startswith((".", "_")) or not group
        ):
            raise FSError(EINVAL, f"bad group name {group!r}")
        return f"/volumes/{group or NO_GROUP}"

    @classmethod
    def _subvol_path(cls, name: str, group: str | None) -> str:
        if not name or "/" in name or name.startswith("."):
            raise FSError(EINVAL, f"bad subvolume name {name!r}")
        return f"{cls._group_path(group)}/{name}"

    # -- groups ------------------------------------------------------------
    async def group_create(self, group: str, mode: int = 0o755) -> None:
        if "/" in group or group.startswith((".", "_")):
            raise FSError(EINVAL, f"bad group name {group!r}")
        await self.fs.mkdirs(self._group_path(group), mode)

    async def group_ls(self) -> list[str]:
        try:
            names = await self.fs.readdir("/volumes")
        except FSError as e:
            if e.rc != ENOENT:
                raise
            return []
        return sorted(n for n in names if n != NO_GROUP)

    async def group_rm(self, group: str) -> None:
        path = self._group_path(group)
        if await self.fs.readdir(path):
            raise FSError(ENOTEMPTY,
                          f"group {group!r} still has subvolumes")
        await self.fs.rmdir(path)

    # -- subvolumes ---------------------------------------------------------
    async def create(self, name: str, group: str | None = None,
                     mode: int = 0o755, size: int = 0) -> str:
        """Create the subvolume directory + meta sidecar; returns the
        data path handed to mounters (``fs subvolume getpath``).
        ``size`` > 0 becomes an ENFORCED max_bytes quota on the
        subvolume root (the reference wires subvolume size to the
        quota vxattr the same way)."""
        path = self._subvol_path(name, group)
        try:
            await self.fs.stat(path)
            raise FSError(EEXIST, f"subvolume {name!r} exists")
        except FSError as e:
            if e.rc != ENOENT:
                raise
        await self.fs.mkdirs(path, mode)
        await self.fs.write_file(f"{path}/{META}", json.dumps({
            "name": name, "group": group or NO_GROUP,
            "created": time.time(), "mode": mode, "size": size,
            "state": "complete",
        }).encode())
        if size > 0:
            await self.fs.setquota(path, max_bytes=size)
        return path

    async def resize(self, name: str, new_size: int,
                     group: str | None = None,
                     no_shrink: bool = False) -> dict:
        """fs subvolume resize: adjust the max_bytes quota (0 =
        infinite).  ``no_shrink`` refuses a target below current
        usage, like the reference's --no_shrink."""
        path = await self.getpath(name, group)
        if new_size < 0:
            raise FSError(EINVAL, "size must be >= 0")
        got = await self.fs.getquota(path)
        if no_shrink and new_size > 0:
            used = (got.get("usage") or {}).get("bytes", 0)
            if new_size < used:
                raise FSError(EINVAL,
                              f"target {new_size} < used {used}")
        # clear -> sidecar write -> apply: the META rewrite lives
        # INSIDE the realm, so writing it under either the old or the
        # new limit could EDQUOT a legal resize.  A failure mid-window
        # re-applies the OLD limit — an error must not leave the
        # subvolume silently unlimited (a process crash in the window
        # still can; the next resize heals it).
        old_limit = int(got["quota"].get("max_bytes", 0))
        await self.fs.setquota(path)
        applied = False
        try:
            meta = json.loads(
                await self.fs.read_file(f"{path}/{META}"))
            meta["size"] = new_size
            await self.fs.write_file(f"{path}/{META}",
                                     json.dumps(meta).encode())
            if new_size > 0:
                await self.fs.setquota(path, max_bytes=new_size)
            applied = True
        finally:
            if not applied and old_limit > 0:
                try:
                    await self.fs.setquota(path,
                                           max_bytes=old_limit)
                except FSError:
                    pass
        return {"path": path, "size": new_size}

    async def ls(self, group: str | None = None) -> list[str]:
        try:
            names = await self.fs.readdir(self._group_path(group))
        except FSError as e:
            if e.rc != ENOENT:
                raise
            return []
        return sorted(names)

    async def getpath(self, name: str, group: str | None = None) -> str:
        path = self._subvol_path(name, group)
        await self.fs.stat(path)           # ENOENT surfaces here
        return path

    async def info(self, name: str, group: str | None = None) -> dict:
        path = await self.getpath(name, group)
        meta = json.loads(await self.fs.read_file(f"{path}/{META}"))
        entries = await self.fs.readdir(path)
        meta["path"] = path
        meta["entries"] = sum(1 for n in entries if n != META)
        meta["snapshots"] = sorted(await self.snapshot_ls(name, group))
        q = await self.fs.getquota(path)
        meta["quota"] = q["quota"]
        meta["bytes_used"] = (q.get("usage") or {}).get("bytes", 0)
        return meta

    async def rm(self, name: str, group: str | None = None,
                 force: bool = False) -> None:
        """Remove the subvolume tree.  Refuses while snapshots cover
        it (matching the reference's snapshot-retention refusal)
        unless ``force`` also removes the snapshots first."""
        path = await self.getpath(name, group)
        snaps = await self.snapshot_ls(name, group)
        if snaps:
            if not force:
                raise FSError(ENOTEMPTY,
                              f"subvolume {name!r} has snapshots "
                              f"{snaps}; use force")
            for s in snaps:
                await self.fs.rmsnap(path, s)
        await self._rmtree(path)     # rmdir drops the quota record

    async def _rmtree(self, path: str) -> None:
        """Depth-first removal (the reference defers this to an async
        purge-queue thread; inline at -lite scale)."""
        for name, d in sorted((await self.fs.readdir(path)).items()):
            child = f"{path}/{name}"
            if d.get("type") == "dir":
                await self._rmtree(child)
            else:
                await self.fs.unlink(child)
        await self.fs.rmdir(path)

    # -- snapshots (subvolume .snap realms) ---------------------------------
    async def snapshot_create(self, name: str, snap: str,
                              group: str | None = None) -> int:
        path = await self.getpath(name, group)
        return await self.fs.mksnap(path, snap)

    async def snapshot_ls(self, name: str,
                          group: str | None = None) -> list[str]:
        path = await self.getpath(name, group)
        return sorted(await self.fs.listsnaps(path))

    async def snapshot_rm(self, name: str, snap: str,
                          group: str | None = None) -> None:
        path = await self.getpath(name, group)
        await self.fs.rmsnap(path, snap)

    async def snapshot_clone(self, name: str, snap: str,
                             target: str,
                             group: str | None = None,
                             target_group: str | None = None) -> str:
        """Clone a subvolume snapshot into a NEW subvolume (the
        volumes module's `subvolume snapshot clone`; synchronous here
        — the reference runs it through an async cloner thread)."""
        if not target:
            raise FSError(EINVAL, "clone needs a target name")
        src = await self.getpath(name, group)
        if snap not in await self.fs.listsnaps(src):
            raise FSError(ENOENT, f"no snapshot {snap!r}")
        src_meta = json.loads(
            await self.fs.read_file(f"{src}/{META}"))
        dst = await self.create(target, target_group,
                                mode=int(src_meta.get("mode",
                                                      0o755)),
                                size=int(src_meta.get("size", 0)))
        # in-progress marker (the reference's clone state tracking):
        # a half-copied target must never read as a good clone
        await self._set_state(dst, "cloning")
        try:
            await self._copy_tree(f"{src}/.snap/{snap}", dst,
                                  root=True)
        except BaseException:
            try:
                await self.rm(target, target_group, force=True)
            except FSError:
                pass               # partial target survives as
                                   # state='cloning', visibly broken
            raise
        await self._set_state(dst, "complete")
        return dst

    async def _set_state(self, path: str, state: str) -> None:
        meta = json.loads(await self.fs.read_file(f"{path}/{META}"))
        meta["state"] = state
        await self.fs.write_file(f"{path}/{META}",
                                 json.dumps(meta).encode())

    async def _copy_tree(self, src: str, dst: str,
                         root: bool = False) -> None:
        for entry, d in sorted((await self.fs.readdir(src)).items()):
            if root and entry == META:
                continue   # ONLY the root sidecar is server-owned;
                           # a nested user file named .meta must copy
            s, t = f"{src}/{entry}", f"{dst}/{entry}"
            if d.get("type") == "dir":
                await self.fs.mkdir(t)
                await self._copy_tree(s, t)
            elif d.get("type") == "symlink":
                await self.fs.symlink(await self.fs.readlink(s), t)
            else:
                await self.fs.write_file(t, await self.fs.read_file(s))
