"""SLO + utilization mgr module: the serving-observability brain.

``SLOMonitor`` drives :class:`ceph_tpu.common.slo.SLOEngine` from the
per-OSD perf dumps the mgr already polls: each report cycle feeds one
cumulative snapshot into the engine's sliding window, evaluates every
conf-declared objective, and

- raises ``SLO_VIOLATION`` cluster health (mgr_stat passes the payload
  straight to the mon's health map) naming the failing objective and
  the worst daemon,
- contributes ``slo`` + ``utilization`` digest sections the dashboard
  panels and ``/api/slo`` serve,
- exports per-objective error-budget burn-rate gauges plus the
  utilization rate gauges to the Prometheus scrape (``prom_metrics``
  hook rendered by ``Mgr.prometheus_text``).

The utilization layer turns the PR 6-8 raw counters into rates over
the same window: achieved device GiB/s vs the HBM roofline
(``ec_launch_bytes`` over encode+decode launch-us), coalescer
occupancy (ops per launch) and window-wait quantiles, resident-cache
hit rate, and the rebuild-GiB/s vs client-p99 interference pair —
the panel arxiv 1906.08602 says decides EC tail latency.
"""

from __future__ import annotations

import time

from ceph_tpu.common.perf import hist_quantile
from ceph_tpu.common.slo import SLOEngine, targets_from_conf
from ceph_tpu.services.mgr_modules import MgrModule


class SLOMonitor(MgrModule):
    name = "slo"

    def __init__(self, mgr):
        super().__init__(mgr)
        self.engine: SLOEngine | None = None
        self.last_eval: list[dict] = []
        self.util: dict = {}
        # forensic auto-capture transition tracking: a capture fires
        # on the RAISE edge of SLO_VIOLATION (engine) and SLOW_OPS
        # (mon health), never while the condition merely persists
        self._prev_active: set[str] = set()
        self._slow_ops_raised = False

    def _ensure_engine(self) -> SLOEngine:
        # built lazily so conf overrides installed after construction
        # (vstart passes them per-entity) are honored; an empty target
        # list still windows the counters for the utilization layer
        if self.engine is None:
            conf = self.mgr.conf
            self.engine = SLOEngine(
                targets_from_conf(conf),
                window=float(conf["slo_window"]),
                raise_evals=int(conf["slo_raise_evals"]),
                clear_evals=int(conf["slo_clear_evals"]),
            )
        return self.engine

    async def serve_once(self) -> None:
        eng = self._ensure_engine()
        snap = await self.mgr.collect()
        per_daemon = {f"osd.{o}": counters
                      for o, counters in snap["osd_perf"].items()}
        eng.observe(time.monotonic(), per_daemon)
        # recovery state from the previous cycle's digest (this cycle's
        # is being built around us) — one report_interval of lag on the
        # rebuild-floor objective, never on the latency objectives
        digest = self.mgr.last_digest or {}
        recovery = int(digest.get("degraded_objects", 0)) > 0
        self.last_eval = eng.evaluate(recovery_active=recovery)
        self.util = self._utilization(eng)
        await self._forensic_triggers(eng, snap)

    async def _forensic_triggers(self, eng: SLOEngine,
                                 snap: dict) -> None:
        """Flight-recorder integration: journal SLO eval transitions
        and fan an automatic forensic capture on raise edges."""
        jr = self.mgr.journal
        active = set(eng.active)
        for obj in sorted(active - self._prev_active):
            rec = eng.active[obj]
            jr.emit("slo.raise", objective=obj,
                    burn_rate=round(float(rec.get("burn_rate", 0.0)),
                                    3),
                    worst_daemon=rec.get("worst_daemon") or "")
        for obj in sorted(self._prev_active - active):
            jr.emit("slo.clear", objective=obj)
        slo_raised = bool(active - self._prev_active)
        self._prev_active = active
        # SLOW_OPS comes from the mon's health map (OSD beacons), so
        # read it off the status snapshot collect() already fetched
        checks = ((snap.get("status") or {}).get("health") or {}) \
            .get("checks", {})
        slow = checks.get("SLOW_OPS")
        slow_raised = slow is not None and not self._slow_ops_raised
        self._slow_ops_raised = slow is not None
        if not (slo_raised or slow_raised):
            return
        if slo_raised:
            payload = eng.health_checks().get("SLO_VIOLATION", {})
            worst_obj = max(eng.active,
                            key=lambda o: eng.active[o]["burn_rate"])
            worst = eng.active[worst_obj].get("worst_daemon") or ""
            await self.mgr.maybe_auto_capture(
                "SLO_VIOLATION", worst_daemon=worst,
                detail={"message": payload.get("message", ""),
                        "detail": payload.get("detail", []),
                        "objective": worst_obj})
        else:
            await self.mgr.maybe_auto_capture(
                "SLOW_OPS",
                detail={"message": (slow or {}).get("message", "")})

    # -- utilization telemetry (rates from the PR 6-8 counters) -----------
    def _win_pair(self, eng: SLOEngine, key: str) -> tuple[float, float]:
        """Window delta of a LONGRUNAVG counter: (sum, count)."""
        return eng.snapshot_window().pair(key)

    def _utilization(self, eng: SLOEngine) -> dict:
        gib = float(1 << 30)
        win = eng.snapshot_window()
        span = win.span
        peak = float(self.mgr.conf["ec_hbm_peak_gibps"] or 1.0)

        launch_bytes, _ = win.scalar("ec_launch_bytes")
        enc_h, _ = win.hist("ec_encode_launch_us")
        dec_h, _ = win.hist("ec_decode_launch_us")
        launch_s = (enc_h.get("sum", 0.0) + dec_h.get("sum", 0.0)) / 1e6
        device_gibps = (launch_bytes / gib / launch_s) if launch_s > 0 \
            else 0.0

        occ_sum, occ_n = win.pair("ec_coalesce_occupancy")
        wait_h, _ = win.hist("ec_coalesce_wait_hist_us")
        hits, _ = win.scalar("ec_resident_hits")
        misses, _ = win.scalar("ec_resident_misses")
        lookups = hits + misses
        rebuild_bytes, _ = win.scalar("ec_repair_rebuild_bytes")
        cli_h, _ = win.hist("op_latency_us")

        def q_ms(h, q):
            v = hist_quantile(h, q)
            return 0.0 if v is None else round(v / 1000.0, 4)

        return {
            "window_s": round(span, 3),
            # device roofline: achieved GiB/s through EC launches vs
            # the conf'd HBM peak — the % of hardware we actually use
            "device_gibps": round(device_gibps, 3),
            "roofline_pct": round(100.0 * device_gibps / peak, 3),
            "launch_bytes": int(launch_bytes),
            "launch_seconds": round(launch_s, 6),
            # coalescer: how full each shared launch ran, and what the
            # micro-window cost waiters
            "coalesce_occupancy": round(occ_sum / occ_n, 3)
            if occ_n > 0 else 0.0,
            "coalesce_launches": int(occ_n),
            "coalesce_wait_p50_us": round(hist_quantile(wait_h, 0.5)
                                          or 0.0, 1),
            "coalesce_wait_p99_us": round(hist_quantile(wait_h, 0.99)
                                          or 0.0, 1),
            # resident cache
            "resident_hit_rate": round(hits / lookups, 4)
            if lookups > 0 else 0.0,
            # interference panel: rebuild throughput against the
            # client tail it competes with, over the SAME window
            "rebuild_gibps": round(rebuild_bytes / gib / span, 4)
            if span > 0 else 0.0,
            "client_p50_ms": q_ms(cli_h, 0.5),
            "client_p99_ms": q_ms(cli_h, 0.99),
            "client_p999_ms": q_ms(cli_h, 0.999),
        }

    # -- mgr surfaces ------------------------------------------------------
    def health_checks(self) -> dict[str, dict]:
        if self.engine is None:
            return {}
        return self.engine.health_checks()

    def digest_contrib(self) -> dict:
        eng = self.engine
        return {
            "slo": {
                "objectives": self.last_eval,
                "violations": sorted(eng.active) if eng else [],
                "window_s": eng.window_span() if eng else 0.0,
            },
            "utilization": self.util,
        }

    def prom_metrics(self) -> dict[str, dict]:
        """Extra gauge families for the Prometheus exposition."""
        out: dict[str, dict] = {}
        per_obj: dict[str, list] = {"burn_rate": [], "ok": [],
                                    "value": []}
        if self.engine is not None:
            from ceph_tpu.services.mgr import prom_label

            for obj, vals in sorted(self.engine.gauges().items()):
                lab = prom_label(objective=obj)
                for k in per_obj:
                    per_obj[k].append((lab, float(vals[k])))
        out["ceph_slo_burn_rate"] = {
            "help": "error-budget burn rate per SLO objective "
                    "(1.0 = spending exactly the allowed budget)",
            "samples": per_obj["burn_rate"]}
        out["ceph_slo_ok"] = {
            "help": "1 while the objective meets target "
                    "(0 = SLO_VIOLATION active)",
            "samples": per_obj["ok"]}
        out["ceph_slo_value"] = {
            "help": "measured value per SLO objective over the window",
            "samples": per_obj["value"]}
        u = self.util
        for key, help_ in (
                ("device_gibps", "achieved EC device throughput GiB/s"),
                ("roofline_pct", "achieved device GiB/s as % of the "
                                 "HBM roofline (ec_hbm_peak_gibps)"),
                ("coalesce_occupancy", "ops per coalesced launch over "
                                       "the window"),
                ("coalesce_wait_p99_us", "coalescer window-wait p99 us"),
                ("resident_hit_rate", "device-resident shard cache hit "
                                      "rate"),
                ("rebuild_gibps", "repair engine rebuild throughput "
                                  "GiB/s"),
                ("client_p99_ms", "cluster client op p99 ms over the "
                                  "window"),
        ):
            out[f"ceph_util_{key}"] = {
                "help": help_,
                "samples": [("", float(u.get(key, 0.0)))]}
        return out
