"""SLO + utilization mgr module: the serving-observability brain.

``SLOMonitor`` drives :class:`ceph_tpu.common.slo.SLOEngine` from the
per-OSD perf dumps the mgr already polls: each report cycle feeds one
cumulative snapshot into the engine's sliding window, evaluates every
conf-declared objective, and

- raises ``SLO_VIOLATION`` cluster health (mgr_stat passes the payload
  straight to the mon's health map) naming the failing objective and
  the worst daemon,
- contributes ``slo`` + ``utilization`` digest sections the dashboard
  panels and ``/api/slo`` serve,
- exports per-objective error-budget burn-rate gauges plus the
  utilization rate gauges to the Prometheus scrape (``prom_metrics``
  hook rendered by ``Mgr.prometheus_text``).

The utilization layer turns the PR 6-8 raw counters into rates over
the same window: achieved device GiB/s vs the HBM roofline
(``ec_launch_bytes`` over encode+decode launch-us), coalescer
occupancy (ops per launch) and window-wait quantiles, resident-cache
hit rate, and the rebuild-GiB/s vs client-p99 interference pair —
the panel arxiv 1906.08602 says decides EC tail latency.
"""

from __future__ import annotations

import time

from ceph_tpu.common.perf import hist_quantile
from ceph_tpu.common.slo import (
    MultiWindowBurn,
    SLOEngine,
    class_burn,
    targets_from_conf,
)
from ceph_tpu.services.mgr_modules import MgrModule


class SLOMonitor(MgrModule):
    name = "slo"

    def __init__(self, mgr):
        super().__init__(mgr)
        self.engine: SLOEngine | None = None
        self.last_eval: list[dict] = []
        self.util: dict = {}
        # per-tenant-class multiwindow burn pairs (5m/1h): built
        # lazily from conf like the engine; class_eval holds the last
        # evaluate() output for the digest/tsdb/health surfaces
        self.class_burns: MultiWindowBurn | None = None
        self._class_labels: tuple[str, ...] = ()
        self.class_eval: dict[str, dict] = {}
        self.class_hists: dict[str, dict] = {}  # cls -> window hist
        # the last per-daemon snapshot collect() produced — the tsdb
        # retention module (which runs after us) harvests counters
        # from it instead of issuing a second collect
        self.last_snap: dict[str, dict] = {}
        # forensic auto-capture transition tracking: a capture fires
        # on the RAISE edge of SLO_VIOLATION (engine or tenant class)
        # and SLOW_OPS (mon health), never while the condition merely
        # persists
        self._prev_active: set[str] = set()
        self._prev_class_active: set[str] = set()
        self._slow_ops_raised = False

    def _ensure_engine(self) -> SLOEngine:
        # built lazily so conf overrides installed after construction
        # (vstart passes them per-entity) are honored; an empty target
        # list still windows the counters for the utilization layer
        if self.engine is None:
            conf = self.mgr.conf
            self.engine = SLOEngine(
                targets_from_conf(conf),
                window=float(conf["slo_window"]),
                raise_evals=int(conf["slo_raise_evals"]),
                clear_evals=int(conf["slo_clear_evals"]),
            )
        return self.engine

    def _ensure_classes(self) -> MultiWindowBurn:
        if self.class_burns is None:
            conf = self.mgr.conf
            self._class_labels = tuple(
                s.strip()
                for s in str(conf["slo_class_labels"] or "").split(",")
                if s.strip())
            self.class_burns = MultiWindowBurn(
                fast_s=float(conf["slo_burn_fast_s"]),
                slow_s=float(conf["slo_burn_slow_s"]),
                raise_evals=int(conf["slo_raise_evals"]),
                clear_evals=int(conf["slo_clear_evals"]),
            )
        return self.class_burns

    async def serve_once(self) -> None:
        eng = self._ensure_engine()
        snap = await self.mgr.collect()
        per_daemon = {f"osd.{o}": counters
                      for o, counters in snap["osd_perf"].items()}
        self.last_snap = per_daemon
        now = time.monotonic()
        eng.observe(now, per_daemon)
        # recovery state from the previous cycle's digest (this cycle's
        # is being built around us) — one report_interval of lag on the
        # rebuild-floor objective, never on the latency objectives
        digest = self.mgr.last_digest or {}
        recovery = int(digest.get("degraded_objects", 0)) > 0
        self.last_eval = eng.evaluate(recovery_active=recovery)
        # per-class attribution: each class's windowed histogram judged
        # against the SAME latency objectives everyone is held to, fed
        # into the 5m/1h multiwindow pair
        cb = self._ensure_classes()
        if self._class_labels:
            win = eng.snapshot_window()
            for cls in self._class_labels:
                merged, _ = win.hist(f"op_class_{cls}_latency_us")
                self.class_hists[cls] = merged
                cb.observe(now, cls, class_burn(merged, eng.targets))
            self.class_eval = cb.evaluate(now)
        self.util = self._utilization(eng)
        await self._forensic_triggers(eng, snap)

    async def _forensic_triggers(self, eng: SLOEngine,
                                 snap: dict) -> None:
        """Flight-recorder integration: journal SLO eval transitions
        and fan an automatic forensic capture on raise edges."""
        jr = self.mgr.journal
        active = set(eng.active)
        for obj in sorted(active - self._prev_active):
            rec = eng.active[obj]
            jr.emit("slo.raise", objective=obj,
                    burn_rate=round(float(rec.get("burn_rate", 0.0)),
                                    3),
                    worst_daemon=rec.get("worst_daemon") or "")
        for obj in sorted(self._prev_active - active):
            jr.emit("slo.clear", objective=obj)
        slo_raised = bool(active - self._prev_active)
        self._prev_active = active
        # tenant-class raise/clear edges mirror the objective edges:
        # journaled for the flight recorder, capture-triggering below
        cb = self.class_burns
        class_active = set(cb.active) if cb is not None else set()
        for cls in sorted(class_active - self._prev_class_active):
            rec = cb.active[cls]
            jr.emit("slo.class_raise", tenant_class=cls,
                    fast_burn=round(float(rec["fast_burn"]), 3),
                    slow_burn=round(float(rec["slow_burn"]), 3))
        for cls in sorted(self._prev_class_active - class_active):
            jr.emit("slo.class_clear", tenant_class=cls)
        class_raised = bool(class_active - self._prev_class_active)
        self._prev_class_active = class_active
        # SLOW_OPS comes from the mon's health map (OSD beacons), so
        # read it off the status snapshot collect() already fetched
        checks = ((snap.get("status") or {}).get("health") or {}) \
            .get("checks", {})
        slow = checks.get("SLOW_OPS")
        slow_raised = slow is not None and not self._slow_ops_raised
        self._slow_ops_raised = slow is not None
        if not (slo_raised or slow_raised or class_raised):
            return
        if slo_raised or class_raised:
            payload = self.health_checks().get("SLO_VIOLATION", {})
            worst = ""
            worst_obj = ""
            if eng.active:
                worst_obj = max(
                    eng.active,
                    key=lambda o: eng.active[o]["burn_rate"])
                worst = eng.active[worst_obj].get("worst_daemon") or ""
            await self.mgr.maybe_auto_capture(
                "SLO_VIOLATION", worst_daemon=worst,
                detail={"message": payload.get("message", ""),
                        "detail": payload.get("detail", []),
                        "objective": worst_obj,
                        "tenant_class":
                            (cb.worst() if cb is not None else None)
                            or ""})
        else:
            await self.mgr.maybe_auto_capture(
                "SLOW_OPS",
                detail={"message": (slow or {}).get("message", "")})

    # -- utilization telemetry (rates from the PR 6-8 counters) -----------
    def _win_pair(self, eng: SLOEngine, key: str) -> tuple[float, float]:
        """Window delta of a LONGRUNAVG counter: (sum, count)."""
        return eng.snapshot_window().pair(key)

    def _utilization(self, eng: SLOEngine) -> dict:
        gib = float(1 << 30)
        win = eng.snapshot_window()
        span = win.span
        peak = float(self.mgr.conf["ec_hbm_peak_gibps"] or 1.0)

        launch_bytes, _ = win.scalar("ec_launch_bytes")
        enc_h, _ = win.hist("ec_encode_launch_us")
        dec_h, _ = win.hist("ec_decode_launch_us")
        launch_s = (enc_h.get("sum", 0.0) + dec_h.get("sum", 0.0)) / 1e6
        device_gibps = (launch_bytes / gib / launch_s) if launch_s > 0 \
            else 0.0

        occ_sum, occ_n = win.pair("ec_coalesce_occupancy")
        wait_h, _ = win.hist("ec_coalesce_wait_hist_us")
        hits, _ = win.scalar("ec_resident_hits")
        misses, _ = win.scalar("ec_resident_misses")
        lookups = hits + misses
        rebuild_bytes, _ = win.scalar("ec_repair_rebuild_bytes")
        cli_h, _ = win.hist("op_latency_us")

        def q_ms(h, q):
            v = hist_quantile(h, q)
            return 0.0 if v is None else round(v / 1000.0, 4)

        return {
            "window_s": round(span, 3),
            # device roofline: achieved GiB/s through EC launches vs
            # the conf'd HBM peak — the % of hardware we actually use
            "device_gibps": round(device_gibps, 3),
            "roofline_pct": round(100.0 * device_gibps / peak, 3),
            "launch_bytes": int(launch_bytes),
            "launch_seconds": round(launch_s, 6),
            # coalescer: how full each shared launch ran, and what the
            # micro-window cost waiters
            "coalesce_occupancy": round(occ_sum / occ_n, 3)
            if occ_n > 0 else 0.0,
            "coalesce_launches": int(occ_n),
            "coalesce_wait_p50_us": round(hist_quantile(wait_h, 0.5)
                                          or 0.0, 1),
            "coalesce_wait_p99_us": round(hist_quantile(wait_h, 0.99)
                                          or 0.0, 1),
            # resident cache
            "resident_hit_rate": round(hits / lookups, 4)
            if lookups > 0 else 0.0,
            # interference panel: rebuild throughput against the
            # client tail it competes with, over the SAME window
            "rebuild_gibps": round(rebuild_bytes / gib / span, 4)
            if span > 0 else 0.0,
            "client_p50_ms": q_ms(cli_h, 0.5),
            "client_p99_ms": q_ms(cli_h, 0.99),
            "client_p999_ms": q_ms(cli_h, 0.999),
        }

    # -- mgr surfaces ------------------------------------------------------
    def health_checks(self) -> dict[str, dict]:
        """``SLO_VIOLATION`` naming the burning tenant class alongside
        the worst daemon.  Three shapes: objective-only (engine
        violations, no class burning), merged (class detail appended to
        the engine's payload), and class-only (a standalone raise when
        a class pair violates while every cluster objective is ok —
        e.g. a small gold tenant drowning inside a healthy average)."""
        base = self.engine.health_checks() if self.engine else {}
        cb = self.class_burns
        if cb is None or not cb.active:
            return base
        worst_cls = cb.worst() or ""
        wrec = cb.active.get(worst_cls, {})
        cls_msg = (f"tenant class {worst_cls} burning "
                   f"{float(wrec.get('fast_burn', 0.0)):.2f}x (5m) / "
                   f"{float(wrec.get('slow_burn', 0.0)):.2f}x (1h)")
        cls_detail = []
        for cls, rec in sorted(cb.active.items()):
            cls_detail.append(
                f"tenant class {cls}: fast burn "
                f"{float(rec.get('fast_burn', 0.0)):.2f}x / slow burn "
                f"{float(rec.get('slow_burn', 0.0)):.2f}x")
        slo = base.get("SLO_VIOLATION")
        if slo is None:
            return {**base, "SLO_VIOLATION": {
                "severity": "HEALTH_WARN",
                "message": cls_msg,
                "detail": cls_detail,
                "count": len(cb.active),
                "tenant_class": worst_cls,
            }}
        slo = dict(slo)
        slo["message"] = f"{slo.get('message', '')}; {cls_msg}"
        slo["detail"] = list(slo.get("detail", ())) + cls_detail
        slo["tenant_class"] = worst_cls
        return {**base, "SLO_VIOLATION": slo}

    def digest_contrib(self) -> dict:
        eng = self.engine
        cb = self.class_burns
        return {
            "slo": {
                "objectives": self.last_eval,
                "violations": sorted(eng.active) if eng else [],
                "window_s": eng.window_span() if eng else 0.0,
                "classes": self.class_eval,
                "class_violations": sorted(cb.active) if cb else [],
            },
            "utilization": self.util,
        }

    def prom_metrics(self) -> dict[str, dict]:
        """Extra gauge families for the Prometheus exposition."""
        out: dict[str, dict] = {}
        per_obj: dict[str, list] = {"burn_rate": [], "ok": [],
                                    "value": []}
        if self.engine is not None:
            from ceph_tpu.services.mgr import prom_label

            for obj, vals in sorted(self.engine.gauges().items()):
                lab = prom_label(objective=obj)
                for k in per_obj:
                    per_obj[k].append((lab, float(vals[k])))
        if self.class_eval:
            from ceph_tpu.services.mgr import prom_label

            fast, slow = [], []
            for cls, rec in sorted(self.class_eval.items()):
                lab = prom_label(tenant_class=cls)
                fast.append((lab, float(rec.get("fast_burn", 0.0))))
                slow.append((lab, float(rec.get("slow_burn", 0.0))))
            out["ceph_slo_class_fast_burn"] = {
                "help": "tenant-class error-budget burn over the fast "
                        "(5m) window", "samples": fast}
            out["ceph_slo_class_slow_burn"] = {
                "help": "tenant-class error-budget burn over the slow "
                        "(1h) window", "samples": slow}
        out["ceph_slo_burn_rate"] = {
            "help": "error-budget burn rate per SLO objective "
                    "(1.0 = spending exactly the allowed budget)",
            "samples": per_obj["burn_rate"]}
        out["ceph_slo_ok"] = {
            "help": "1 while the objective meets target "
                    "(0 = SLO_VIOLATION active)",
            "samples": per_obj["ok"]}
        out["ceph_slo_value"] = {
            "help": "measured value per SLO objective over the window",
            "samples": per_obj["value"]}
        u = self.util
        for key, help_ in (
                ("device_gibps", "achieved EC device throughput GiB/s"),
                ("roofline_pct", "achieved device GiB/s as % of the "
                                 "HBM roofline (ec_hbm_peak_gibps)"),
                ("coalesce_occupancy", "ops per coalesced launch over "
                                       "the window"),
                ("coalesce_wait_p99_us", "coalescer window-wait p99 us"),
                ("resident_hit_rate", "device-resident shard cache hit "
                                      "rate"),
                ("rebuild_gibps", "repair engine rebuild throughput "
                                  "GiB/s"),
                ("client_p99_ms", "cluster client op p99 ms over the "
                                  "window"),
        ):
            out[f"ceph_util_{key}"] = {
                "help": help_,
                "samples": [("", float(u.get(key, 0.0)))]}
        return out
