"""Object-backed image journal: the src/journal Journaler role for RBD.

The reference journals every image mutation before applying it
(librbd/Journal.cc over src/journal/Journaler.h:32): entries land in
journal data objects, registered clients (the image itself, each
rbd-mirror peer) persist their commit positions in the journal header,
and objects every client has consumed are trimmed.  This gives (a)
crash consistency — an image reopened after a crash replays entries
newer than its own commit position — and (b) journal-based mirroring —
a peer tails the SAME entry stream and applies it remotely, converging
mid-write-stream without snapshots.

Layout (-lite, same roles):
- ``journal.<image_id>``          header; omap ``client.<id>`` -> last
  committed tid (8-byte BE), ``trimmed`` -> first live object number.
- ``journal_data.<image_id>.<N>`` entry objects: consecutive tids in
  segments of ``per_obj`` entries (the reference splays the active set
  across ``splay_width`` objects for parallel appends; segmentation
  keeps the same trim granularity with strictly ordered replay, which
  is the property the correctness story rests on).

Entries are length-prefixed codec frames appended atomically (a RADOS
append is one transaction — no torn entries); tids are dense from 0, so
``tid // per_obj`` names the object and replay needs no index.
"""

from __future__ import annotations

import struct

from ceph_tpu.client.rados import IoCtx, ObjectOperation, RadosError
from ceph_tpu.msg.codec import decode, encode

_LEN = struct.Struct("<I")
_TID = struct.Struct(">Q")

PER_OBJ = 128            # entries per journal data object (trim unit)

# event types (librbd journal/Types.h EventEntry)
EV_WRITE = 1
EV_RESIZE = 3
EV_SNAP_CREATE = 4
EV_SNAP_REMOVE = 5
EV_SNAP_ROLLBACK = 6


class ImageJournal:
    """One image's journal handle (Journaler.h:32 role)."""

    def __init__(self, ioctx: IoCtx, image_id: str,
                 client_id: str = "master", per_obj: int = PER_OBJ):
        self.ioctx = ioctx
        self.image_id = image_id
        self.client_id = client_id
        self.per_obj = per_obj
        self.header_oid = f"journal.{image_id}"
        self._next_tid: int | None = None

    def _data_oid(self, objno: int) -> str:
        return f"journal_data.{self.image_id}.{objno}"

    # -- client registry / commit positions ---------------------------
    async def register(self) -> int:
        """Register this client (idempotent); returns its last committed
        tid (-1 when fresh)."""
        kv = await self._header()
        key = f"client.{self.client_id}"
        if key not in kv:
            await self.ioctx.operate(
                self.header_oid,
                ObjectOperation().create()
                .omap_set({key: _TID.pack(0)}),
            )
            return -1
        return _TID.unpack(kv[key])[0] - 1

    async def _header(self) -> dict[str, bytes]:
        try:
            return await self.ioctx.get_omap(self.header_oid)
        except RadosError as e:
            if e.rc == -2:
                return {}
            raise

    async def committed(self, client_id: str | None = None) -> int:
        kv = await self._header()
        raw = kv.get(f"client.{client_id or self.client_id}")
        return (_TID.unpack(raw)[0] - 1) if raw else -1

    async def commit(self, tid: int) -> None:
        """Persist this client's commit position (monotonic)."""
        cur = await self.committed()
        if tid <= cur:
            return
        await self.ioctx.operate(
            self.header_oid,
            ObjectOperation().omap_set(
                {f"client.{self.client_id}": _TID.pack(tid + 1)}
            ),
        )

    # -- append -------------------------------------------------------
    async def _discover_tail(self) -> int:
        """Next tid, counted from the last populated object.  Commit
        positions floor the scan: a trim that crashed after deleting an
        object but before persisting ``trimmed`` must not make a missing
        object look like the tail (tids must never be reused — entries
        below a client's commit position are invisible to it forever)."""
        kv = await self._header()
        floor = max(
            [_TID.unpack(v)[0]
             for k, v in kv.items() if k.startswith("client.")] or [0]
        )
        objno = max(int(kv.get("trimmed", b"0")),
                    floor // self.per_obj)
        last = None
        while True:
            try:
                raw = await self.ioctx.read(self._data_oid(objno))
            except RadosError as e:
                if e.rc == -2:
                    break
                raise
            last = (objno, raw)
            objno += 1
        if last is None:
            return max(int(kv.get("trimmed", b"0")) * self.per_obj,
                       floor)
        objno, raw = last
        return max(objno * self.per_obj + len(_split_frames(raw)), floor)

    async def append(self, event: int, args: dict) -> int:
        """Durably append one event; returns its tid.  The append IS the
        commit point of the mutation (librbd acks writes at
        journal-safe)."""
        if self._next_tid is None:
            self._next_tid = await self._discover_tail()
        tid = self._next_tid
        payload = encode([tid, event, args])
        await self.ioctx.append(
            self._data_oid(tid // self.per_obj),
            _LEN.pack(len(payload)) + payload,
        )
        self._next_tid = tid + 1
        return tid

    # -- replay / tail ------------------------------------------------
    async def trim_horizon(self) -> int:
        """First tid that can still be read (everything below was
        trimmed).  A client whose position is older than this cannot
        catch up from the journal alone (it needs a full image sync —
        the reference ImageReplayer bootstrap)."""
        kv = await self._header()
        return int(kv.get("trimmed", b"0")) * self.per_obj

    async def entries_after(self, tid: int):
        """Yield (tid, event, args) for every entry with tid > ``tid``
        in order (the Journaler replay/tail read path).  A missing
        object BELOW the committed floor is a crash-trimmed gap and is
        skipped; the first missing object at or past the floor is the
        tail."""
        kv = await self._header()
        floor = max(
            [_TID.unpack(v)[0]
             for k, v in kv.items() if k.startswith("client.")] or [0]
        )
        objno = max(int(kv.get("trimmed", b"0")),
                    (tid + 1) // self.per_obj)
        while True:
            try:
                raw = await self.ioctx.read(self._data_oid(objno))
            except RadosError as e:
                if e.rc != -2:
                    raise
                if (objno + 1) * self.per_obj <= floor:
                    objno += 1          # crash-trimmed gap: keep going
                    continue
                return
            for payload in _split_frames(raw):
                etid, event, args = decode(payload)
                if etid > tid:
                    yield int(etid), int(event), args
            objno += 1

    # -- trim ---------------------------------------------------------
    async def trim(self) -> int:
        """Delete whole objects every registered client has committed
        past (minimum commit position, Journaler trim role); returns the
        number of objects removed."""
        kv = await self._header()
        commits = [
            _TID.unpack(v)[0] - 1
            for k, v in kv.items() if k.startswith("client.")
        ]
        if not commits:
            return 0
        safe_obj = (min(commits) + 1) // self.per_obj
        objno = int(kv.get("trimmed", b"0"))
        removed = 0
        while objno < safe_obj:
            try:
                await self.ioctx.remove(self._data_oid(objno))
            except RadosError as e:
                if e.rc != -2:
                    raise
            objno += 1
            removed += 1
        if removed:
            await self.ioctx.operate(
                self.header_oid,
                ObjectOperation().omap_set(
                    {"trimmed": str(objno).encode()}
                ),
            )
        return removed

    async def destroy(self) -> None:
        kv = await self._header()
        objno = int(kv.get("trimmed", b"0"))
        while True:
            try:
                await self.ioctx.remove(self._data_oid(objno))
            except RadosError as e:
                if e.rc == -2:
                    break
                raise
            objno += 1
        try:
            await self.ioctx.remove(self.header_oid)
        except RadosError as e:
            if e.rc != -2:
                raise


def _split_frames(raw: bytes) -> list[bytes]:
    out = []
    pos = 0
    while pos + _LEN.size <= len(raw):
        (n,) = _LEN.unpack_from(raw, pos)
        pos += _LEN.size
        if pos + n > len(raw):
            break
        out.append(raw[pos:pos + n])
        pos += n
    return out


def coalesce_writes(extents: list[tuple[int, bytes]]
                    ) -> list[tuple[int, bytes]]:
    """Merge a run of write extents into their final overlay (later
    writes win) — the replay-side extent coalescing of the reference's
    journal batching: N overlapping small writes hit the image once,
    not N times.  Returns sorted, disjoint (offset, data) extents."""
    merged: list[tuple[int, bytearray]] = []
    for off, data in extents:
        end = off + len(data)
        keep: list[tuple[int, bytearray]] = []
        for moff, mdata in merged:
            mend = moff + len(mdata)
            if mend <= off or moff >= end:
                keep.append((moff, mdata))      # disjoint: untouched
                continue
            # overlap: the new write overlays; keep the old extent's
            # non-overlapped head/tail
            if moff < off:
                keep.append((moff, mdata[:off - moff]))
            if mend > end:
                keep.append((end, mdata[end - moff:]))
        keep.append((off, bytearray(data)))
        merged = keep
    merged.sort(key=lambda e: e[0])
    # join adjacent extents so replay issues the fewest image writes
    out: list[tuple[int, bytes]] = []
    for off, data in merged:
        if out and out[-1][0] + len(out[-1][1]) == off:
            out[-1] = (out[-1][0], out[-1][1] + bytes(data))
        else:
            out.append((off, bytes(data)))
    return out


async def replay_to_image(img, journal: ImageJournal,
                          from_tid: int | None = None) -> int:
    """Apply every journal entry newer than the commit position (or
    ``from_tid``) to the image (librbd Journal replay on open / the
    ImageReplayer apply loop); returns the count applied.  Entries are
    absolute-state ops, safe to re-apply.  Runs of consecutive WRITE
    events coalesce into their final overlay before touching the image
    (non-write events are barriers — a resize or snap between writes
    keeps its ordering).  The commit position only advances after the
    applied data is durable (cache flushed)."""
    pos = await journal.committed() if from_tid is None else from_tid
    applied = 0
    last = pos
    pending: list[tuple[int, bytes]] = []

    async def flush_writes() -> None:
        for off, data in coalesce_writes(pending):
            if off + len(data) > img.size:
                await img.resize(off + len(data), _journal=False)
            await img.write(off, data, _journal=False)
        pending.clear()

    async for tid, event, args in journal.entries_after(pos):
        if event == EV_WRITE:
            pending.append((int(args["off"]), bytes(args["data"])))
        else:
            await flush_writes()
            await apply_event(img, event, args)
        last = tid
        applied += 1
    await flush_writes()
    if applied:
        if getattr(img, "_cache", None) is not None:
            await img._cache.flush()
        await journal.commit(last)
    return applied


async def apply_event(img, event: int, args: dict) -> None:
    if event == EV_WRITE:
        off, data = int(args["off"]), bytes(args["data"])
        if off + len(data) > img.size:
            # the image was at least this big when the write was
            # journaled; grow to accept it — any later shrink/grow is
            # its own journal entry and restores the final geometry,
            # so replay converges for primaries and mirrors alike
            await img.resize(off + len(data), _journal=False)
        await img.write(off, data, _journal=False)
    elif event == EV_RESIZE:
        await img.resize(int(args["size"]), _journal=False)
    elif event == EV_SNAP_CREATE:
        if args["name"] not in img.snaps:
            await img.snap_create(str(args["name"]), _journal=False)
    elif event == EV_SNAP_REMOVE:
        if args["name"] in img.snaps:
            await img.snap_remove(str(args["name"]), _journal=False)
    elif event == EV_SNAP_ROLLBACK:
        if args["name"] in img.snaps:
            await img.snap_rollback(str(args["name"]), _journal=False)
    else:
        raise ValueError(f"unknown journal event {event}")
