"""QoS defense-plane mgr module: the actuator fan-out.

``QoSMonitor`` runs directly after ``SLOMonitor`` each report cycle
(module dispatch is insertion-ordered), reads the evaluation the SLO
engine just made plus the SAME sliding snapshot window the verdict was
computed from (:meth:`SLOEngine.window`), and drives the
:class:`ceph_tpu.common.qos.QoSController` tick:

- an ``mclock`` retune decision fans a ``qos_set`` wire cmd to every
  up OSD, shrinking/restoring the recovery / backfill / scrub class
  reservations+limits (three AIMD positions off one burn signal),
- burning-flag TRANSITIONS fan out as ``slo_burning`` in the same
  ``qos_set`` payloads: each OSD's ScrubEngine parks its in-flight
  sweep (cursor held) while the cluster burns and resumes on clear,
- per-OSD adaptive hedge timeouts push to exactly the OSDs whose
  shard-read tail moved,
- every decision journals a ``qos.retune`` / ``qos.hedge_push`` event
  into the PR-12 flight recorder (same seed => same event sequence)
  and surfaces as ``ceph_qos_*`` Prometheus gauges, the ``qos`` digest
  section (dashboard ``/api/qos``), and forensic bundles via
  ``forensics_contrib`` — a capture shows what the defense plane was
  doing at violation time.

The third actuator family (RGW admission control) is front-door-local
— services/rgw_http.py sheds with ``503 Slow Down`` from its own conf
— so this module only aggregates its shed telemetry, it does not push
to it.
"""

from __future__ import annotations

import asyncio

from ceph_tpu.common.qos import QoSController
from ceph_tpu.services.mgr_modules import MgrModule


class QoSMonitor(MgrModule):
    name = "qos"

    def __init__(self, mgr):
        super().__init__(mgr)
        self.controller: QoSController | None = None
        self.last_tick: dict = {}
        self._pushed_limit: float | None = None
        self._pushed_burning = False

    def _enabled(self) -> bool:
        return bool(self.mgr.conf["qos_enable"])

    def _ensure_controller(self) -> QoSController:
        # lazy like SLOMonitor's engine: vstart installs conf
        # overrides per-entity after construction
        if self.controller is None:
            self.controller = QoSController.from_conf(self.mgr.conf)
        return self.controller

    async def serve_once(self) -> None:
        if not self._enabled():
            return
        slo = self.mgr.modules.get("slo")
        eng = getattr(slo, "engine", None)
        if eng is None or not slo.last_eval:
            return
        ctrl = self._ensure_controller()
        out = ctrl.tick(slo.last_eval, eng.snapshot_window())
        self.last_tick = out
        jr = self.mgr.journal
        payloads: dict[int, dict] = {}      # osd id -> qos_set data
        osdmap = self.mgr.monc.osdmap
        up = {osd: info for osd, info in
              (osdmap.osds.items() if osdmap else ())
              if info.up}

        # the replication class is not an mClock class: its decision is
        # actuated as a token-bucket rate on the sync agents by the
        # multisite mgr module (which reads last_tick), so it is
        # journaled here but never fanned to OSDs
        rp = out.get("replication")
        if rp and rp["changed"]:
            jr.emit("qos.retune", actuator="sync-agent",
                    clazz="replication",
                    limit=round(rp["limit"], 3),
                    reservation=round(rp["reservation"], 3),
                    floor=round(rp["floor"], 3),
                    burn=round(out["burn"], 3),
                    burning=out["burning"])

        for clazz in ("recovery", "backfill", "scrub"):
            dec = out.get(clazz)
            if not dec or not dec["changed"]:
                continue
            jr.emit("qos.retune", actuator="mclock", clazz=clazz,
                    limit=round(dec["limit"], 3),
                    reservation=round(dec["reservation"], 3),
                    floor=round(dec["floor"], 3),
                    burn=round(out["burn"], 3),
                    burning=out["burning"])
            for osd in up:
                payloads.setdefault(
                    osd, {}).setdefault("mclock", {})[clazz] = {
                        "reservation": dec["reservation"],
                        "limit": dec["limit"],
                    }
            if clazz == "recovery":
                self._pushed_limit = dec["limit"]

        for daemon, timeout in sorted(out["hedge"].items()):
            # daemons are keyed "osd.N" by SLOMonitor's snapshot feed
            try:
                osd = int(str(daemon).split(".", 1)[1])
            except (IndexError, ValueError):
                continue
            if osd not in up:
                continue
            payloads.setdefault(osd, {})["hedge_timeout"] = timeout
            jr.emit("qos.hedge_push", daemon=str(daemon),
                    timeout_ms=round(timeout * 1e3, 3))

        # the scrub pause gate: the daemons park in-flight sweeps
        # while the cluster burns SLO, so a burning-flag TRANSITION
        # must reach every up OSD even when no mClock class retuned
        # this tick — and any payload already going out carries the
        # current flag so a restarted OSD re-learns it for free
        burning = bool(out["burning"])
        if burning != self._pushed_burning:
            self._pushed_burning = burning
            jr.emit("qos.scrub_gate",
                    action="pause" if burning else "resume",
                    burn=round(out["burn"], 3))
            for osd in up:
                payloads.setdefault(osd, {})
        for data in payloads.values():
            data["slo_burning"] = burning

        if payloads:
            await asyncio.gather(*(
                self.mgr.osd_request(osd, up[osd].addr, "qos_set",
                                     **data)
                for osd, data in payloads.items()))

    # -- mgr surfaces ------------------------------------------------------
    def _rgw_sheds(self) -> dict:
        """Front-door shed telemetry: rgw_http publishes its counters
        into the shared process namespace via the proc journal — count
        qos.shed events still in the ring (best effort)."""
        from ceph_tpu.common.events import proc_journal

        sheds = [e for e in proc_journal().snapshot()
                 if e.get("type") == "qos.shed"]
        return {"recent_sheds": len(sheds)}

    def digest_contrib(self) -> dict:
        if not self._enabled():
            return {"qos": {"enabled": False}}
        ctrl = self.controller
        out = {"enabled": True}
        if ctrl is not None:
            out.update(ctrl.state())
            out["burning"] = bool(self.last_tick.get("burning", False))
            out["burn"] = round(
                float(self.last_tick.get("burn", 0.0)), 3)
        out.update(self._rgw_sheds())
        return {"qos": out}

    def forensics_contrib(self) -> dict:
        """Controller state folded into every forensic bundle."""
        if self.controller is None:
            return {}
        state = self.controller.state()
        state["enabled"] = self._enabled()
        state["burning"] = bool(self.last_tick.get("burning", False))
        return state

    def prom_metrics(self) -> dict[str, dict]:
        ctrl = self.controller
        if ctrl is None:
            return {}
        from ceph_tpu.services.mgr import prom_label

        st = ctrl.state()
        out = {
            "ceph_qos_recovery_limit": {
                "help": "controller-set recovery-class mClock limit "
                        "ops/s (AIMD position)",
                "samples": [("", float(st["recovery_limit"]))]},
            "ceph_qos_recovery_floor": {
                "help": "recovery pacing floor ops/s (derived from "
                        "slo_rebuild_floor_gibs and the share/ops "
                        "floors)",
                "samples": [("", float(st["recovery_floor"]))]},
            "ceph_qos_backfill_limit": {
                "help": "controller-set backfill-class mClock limit "
                        "ops/s (planned-motion AIMD position)",
                "samples": [("", float(st["backfill_limit"]))]},
            "ceph_qos_backfill_floor": {
                "help": "backfill pacing floor ops/s (share/ops "
                        "floors; planned motion has no rebuild-GiB "
                        "term)",
                "samples": [("", float(st["backfill_floor"]))]},
            "ceph_qos_scrub_limit": {
                "help": "controller-set scrub-class mClock limit "
                        "ops/s (integrity-verification AIMD position)",
                "samples": [("", float(st["scrub_limit"]))]},
            "ceph_qos_scrub_floor": {
                "help": "scrub pacing floor ops/s (share/ops floors; "
                        "verification of fully-redundant data is "
                        "squeezed hardest under client burn)",
                "samples": [("", float(st["scrub_floor"]))]},
            "ceph_qos_replication_limit": {
                "help": "controller-set replication-class pacing rate "
                        "ops/s pushed to multisite sync agents (fourth "
                        "AIMD position)",
                "samples": [("", float(st["replication_limit"]))]},
            "ceph_qos_replication_floor": {
                "help": "replication pacing floor ops/s — the bound on "
                        "how fast RPO may grow while clients burn",
                "samples": [("", float(st["replication_floor"]))]},
            "ceph_qos_retunes": {
                "help": "cumulative mClock retune decisions",
                "samples": [("", float(st["retunes"]))]},
            "ceph_qos_burning": {
                "help": "1 while the controller sees client latency "
                        "burn > 1.0",
                "samples": [("", 1.0 if self.last_tick.get("burning")
                             else 0.0)]},
        }
        hedge = [(prom_label(daemon=d), float(ms))
                 for d, ms in sorted(st["hedge_timeouts_ms"].items())]
        out["ceph_qos_hedge_timeout_ms"] = {
            "help": "adaptive EC hedge-read timeout pushed per OSD",
            "samples": hedge or [("", 0.0)]}
        return out
