"""Mgr: cluster metrics aggregation + prometheus exposition.

The reference's manager pulls MMgrReport perf-counter payloads from every
daemon (src/mgr/DaemonServer.h:51) and the prometheus mgr module renders
them (src/pybind/mgr/prometheus/module.py:1021). Here the mgr polls: it
asks each up OSD for a ``perf_dump`` (the admin-socket ``perf dump``
surface, reference common/admin_socket.h:105) and merges the replies with
monitor status into one snapshot, rendered in the prometheus text
exposition format with the metric names the reference's module exports
(ceph_osd_op, ceph_osd_op_in_bytes, ceph_osd_up, ...).
"""

from __future__ import annotations

import asyncio
import json
import math
import os
import tempfile
import time

from ceph_tpu.common.config import ConfigProxy
from ceph_tpu.common.events import (
    EventJournal,
    merge_timeline,
    proc_journal,
)
from ceph_tpu.common.perf import bucket_le, hist_merge, hist_quantile
from ceph_tpu.common.tracing import assemble_tree
from ceph_tpu.mon.client import MonClient
from ceph_tpu.msg.message import Message
from ceph_tpu.msg.messenger import Connection, Messenger, Policy


def prom_escape(value: str) -> str:
    """Escape a prometheus label VALUE per the text exposition spec:
    backslash first (it is the escape char), then double-quote and
    newline.  Daemon names are tame today, but free-form label values
    (SLO objective specs, pool names) must not be able to break the
    scrape."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def prom_label(**labels) -> str:
    """Render one ``{k="v",...}`` label set with escaped values."""
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{prom_escape(v)}"' for k, v in labels.items())
    return "{" + inner + "}"


class Mgr:
    def __init__(self, monmap: dict[str, str],
                 conf: ConfigProxy | None = None, name: str = "mgr.x",
                 modules: list | None = None):
        from ceph_tpu.services.mgr_modules import (
            Balancer,
            DeviceHealth,
            Insights,
            PGAutoscaler,
            Progress,
            SnapSchedule,
            Telemetry,
        )

        self.conf = conf or ConfigProxy()
        self.name = name
        self.msgr = Messenger(name, self.conf)
        self.msgr.set_policy("mon", Policy.lossy_client())
        self.msgr.set_policy("osd", Policy.lossy_client())
        self.msgr.set_dispatcher(self)
        self.monc = MonClient(name, monmap, self.conf, msgr=self.msgr)
        self._tid = 0
        self._futures: dict[int, asyncio.Future] = {}
        self.admin_socket = None
        if modules is None:
            from ceph_tpu.services.mgr_perf import (
                IOStat,
                OSDPerfQuery,
                RBDSupport,
            )
            from ceph_tpu.services.mgr_multisite import (
                MultisiteMonitor,
            )
            from ceph_tpu.services.mgr_qos import QoSMonitor
            from ceph_tpu.services.mgr_slo import SLOMonitor
            from ceph_tpu.services.mgr_tsdb import TSDBMonitor
            from ceph_tpu.services.orchestrator import Orchestrator

            pq = OSDPerfQuery(self)
            # QoSMonitor runs directly after SLOMonitor (insertion
            # order is dispatch order): each report cycle the defense
            # plane acts on the evaluation the SLO engine just made,
            # MultisiteMonitor follows so the replication-class
            # decision reaches the sync agents the same cycle, and
            # TSDBMonitor runs LAST so the retention layer records
            # what this cycle actually concluded
            modules = [Balancer(self), PGAutoscaler(self),
                       Progress(self), DeviceHealth(self),
                       Telemetry(self), Insights(self),
                       SnapSchedule(self), Orchestrator(self),
                       pq, RBDSupport(self, pq), IOStat(self),
                       SLOMonitor(self), QoSMonitor(self),
                       MultisiteMonitor(self), TSDBMonitor(self)]
        self.modules = {m.name: m for m in modules}
        # delta-encoded collect state: one decoder per OSD stream plus
        # counter-verified payload accounting (the cfg16 A/B and the
        # ts-smoke read these — bytes are measured, never estimated)
        self._delta_decoders: dict[int, object] = {}
        self.collect_stats = {
            "cycles": 0, "payload_bytes": 0, "last_payload_bytes": 0,
            "resyncs": 0, "delta": False,
        }
        self.last_digest: dict | None = None
        # flight recorder: the mgr's own ring (SLO eval transitions,
        # capture bookkeeping) + the bounded in-memory bundle index the
        # dashboard's /api/forensics serves
        self.journal = EventJournal(
            name, size=int(self.conf["event_journal_size"]))
        self._forensics: list[dict] = []
        self._forensics_seq = 0
        self._last_capture_mono = 0.0

    async def ms_dispatch(self, conn: Connection, msg: Message) -> None:
        if msg.type == "perf_dump_reply":
            fut = self._futures.pop(int(msg.data.get("tid", 0)), None)
            if fut is not None and not fut.done():
                fut.set_result(msg.data.get("counters", {}))
            return
        if msg.type == "perf_dump_delta_reply":
            fut = self._futures.pop(int(msg.data.get("tid", 0)), None)
            if fut is not None and not fut.done():
                fut.set_result(dict(msg.data))
            return
        if msg.type == "pg_stats_reply":
            fut = self._futures.pop(int(msg.data.get("tid", 0)), None)
            if fut is not None and not fut.done():
                fut.set_result(msg.data.get("pgs", []))
            return
        if msg.type in ("perf_query_reply", "perf_query_dump_reply"):
            fut = self._futures.pop(int(msg.data.get("tid", 0)), None)
            if fut is not None and not fut.done():
                fut.set_result(dict(msg.data))
            return
        if msg.type == "dump_traces_reply":
            fut = self._futures.pop(int(msg.data.get("tid", 0)), None)
            if fut is not None and not fut.done():
                fut.set_result(msg.data.get("spans", []))
            return
        if msg.type == "forensics_capture_reply":
            fut = self._futures.pop(int(msg.data.get("tid", 0)), None)
            if fut is not None and not fut.done():
                fut.set_result(dict(msg.data))
            return
        if msg.type == "qos_set_reply":
            fut = self._futures.pop(int(msg.data.get("tid", 0)), None)
            if fut is not None and not fut.done():
                fut.set_result(dict(msg.data))
            return
        await self.monc.ms_dispatch(conn, msg)

    def ms_handle_reset(self, conn: Connection) -> None:
        self.monc.ms_handle_reset(conn)

    def ms_handle_connect(self, conn: Connection) -> None:
        pass

    async def start(self, timeout: float = 20.0) -> None:
        await self.monc.start(timeout)
        self.monc.sub_want("osdmap")
        self.monc.renew_subs()
        await self.monc.wait_for_map(1, timeout)
        run_dir = self.conf["admin_socket_dir"]
        if run_dir:
            from ceph_tpu.common.admin_socket import AdminSocket

            sock = AdminSocket(self.name)
            sock.register("status", lambda: {
                "entity": self.name,
                "modules": sorted(self.modules),
                "osdmap_epoch": (self.monc.osdmap.epoch
                                 if self.monc.osdmap else 0),
            }, "mgr state")
            sock.register("config show", self.conf.show,
                          "live configuration")
            from ceph_tpu.common.log import recent_lines
            sock.register("log dump", recent_lines,
                          "recent log ring (crash context)")
            sock.register("events dump", lambda: {
                "stats": self.journal.stats(),
                "events": self.journal.snapshot(),
            }, "flight-recorder event journal (full ring)")
            sock.register("forensics ls", self.forensics_index,
                          "forensic bundles captured this session")
            sock.register("ts query", self.ts_query,
                          "time-series query (name= or prefix=, "
                          "start/end/tier/max_points)")
            await sock.start(run_dir)
            self.admin_socket = sock

    async def shutdown(self) -> None:
        for mod in self.modules.values():
            stop = getattr(mod, "stop", None)
            if stop is not None:
                await stop()
        if self.admin_socket is not None:
            await self.admin_socket.stop()
            self.admin_socket = None
        dash = getattr(self, "dashboard", None)
        if dash is not None:
            await dash.stop()
            self.dashboard = None
        await self.monc.shutdown()
        await self.msgr.shutdown()

    # -- collection --------------------------------------------------------
    async def _poll_osd(self, osd: int, addr: str,
                        timeout: float = 3.0,
                        what: str = "perf_dump"):
        self._tid += 1
        tid = self._tid
        fut = asyncio.get_running_loop().create_future()
        self._futures[tid] = fut
        try:
            await self.msgr.send_to(
                addr, Message(what, {"tid": tid}), f"osd.{osd}"
            )
            return await asyncio.wait_for(fut, timeout)
        except (ConnectionError, asyncio.TimeoutError):
            self._futures.pop(tid, None)
            return None

    async def osd_request(self, osd: int, addr: str, mtype: str,
                          timeout: float = 3.0, **data):
        """One request/reply exchange with an OSD (dynamic perf query
        control + dump); None on timeout/unreachable."""
        self._tid += 1
        tid = self._tid
        fut = asyncio.get_running_loop().create_future()
        self._futures[tid] = fut
        try:
            await self.msgr.send_to(
                addr, Message(mtype, {"tid": tid, **data}),
                f"osd.{osd}"
            )
            return await asyncio.wait_for(fut, timeout)
        except (ConnectionError, asyncio.TimeoutError):
            self._futures.pop(tid, None)
            return None

    async def collect(self) -> dict:
        """One cluster snapshot: mon status + per-osd perf counters.

        With ``mgr_perf_collect_delta`` (the default) each OSD ships
        only counters changed since the epoch we acked — the decoded
        dumps are bit-identical to a full collect, but the wire
        payload is proportional to what MOVED, not to what exists
        (sublinear at the 1000-OSD scale ROADMAP item 1 targets).
        Payload bytes are counter-verified into ``collect_stats``
        either way, so the A/B is measured, never estimated."""
        from ceph_tpu.common.perf_collect import (
            DeltaCollectDecoder,
            payload_bytes,
        )

        status = (await self.monc.command("status"))["data"]
        osdmap = self.monc.osdmap
        osd_perf: dict[int, dict] = {}
        delta = bool(self.conf["mgr_perf_collect_delta"])
        cycle_bytes = 0
        if osdmap is not None and delta:
            decs = self._delta_decoders
            polls = {
                osd: self.osd_request(
                    osd, info.addr, "perf_dump_delta",
                    ack_epoch=decs[osd].epoch
                    if osd in decs else 0)
                for osd, info in osdmap.osds.items() if info.up
            }
            # a decoder created before its first reply would ack a
            # stale 0 forever; create on reply instead
            results = await asyncio.gather(*polls.values())
            for osd, payload in zip(polls, results):
                if payload is None:
                    continue
                payload.pop("tid", None)
                cycle_bytes += payload_bytes(payload)
                dec = decs.get(osd)
                if dec is None:
                    dec = decs[osd] = DeltaCollectDecoder()
                if payload.get("full"):
                    self.collect_stats["resyncs"] += 1
                osd_perf[osd] = dec.decode(payload)
        elif osdmap is not None:
            polls = {
                osd: self._poll_osd(osd, info.addr)
                for osd, info in osdmap.osds.items() if info.up
            }
            results = await asyncio.gather(*polls.values())
            for osd, counters in zip(polls, results):
                if counters is not None:
                    cycle_bytes += payload_bytes(
                        {"counters": counters})
                    osd_perf[osd] = counters
        self.collect_stats["cycles"] += 1
        self.collect_stats["payload_bytes"] += cycle_bytes
        self.collect_stats["last_payload_bytes"] = cycle_bytes
        self.collect_stats["delta"] = delta
        return {
            "status": status,
            "osds": {
                osd: {"up": info.up, "in": info.in_cluster}
                for osd, info in (osdmap.osds.items() if osdmap else ())
            },
            "osd_perf": osd_perf,
        }

    def ts_query(self, name: str = "", start=None, end=None,
                 tier: str = "auto", prefix: str = "",
                 max_points=0) -> dict:
        """Time-series query against the retention module's store —
        the one entry point the dashboard ``/api/ts``, the ``ts
        query`` admin-socket command, and tests share.  With neither
        ``name`` nor ``prefix`` it returns the catalog."""
        ts = self.modules.get("ts")
        if ts is None:
            return {"error": "tsdb module not loaded"}
        return ts.query(
            name=str(name or ""),
            start=None if start is None else float(start),
            end=None if end is None else float(end),
            tier=str(tier or "auto"), prefix=str(prefix or ""),
            max_points=int(max_points or 0))

    async def collect_trace(self, trace_id: str) -> list[dict]:
        """Cluster-wide trace reassembly: fan ``dump_traces`` across
        every up OSD plus the mon's span ring, dedupe by span id, and
        assemble ONE parent-linked tree (the ``trace collect``
        backend and the dashboard's /api/trace payload)."""
        spans: list[dict] = []
        osdmap = self.monc.osdmap
        if osdmap is not None:
            polls = {
                osd: self.osd_request(osd, info.addr, "dump_traces",
                                      trace_id=trace_id)
                for osd, info in osdmap.osds.items() if info.up
            }
            for got in await asyncio.gather(*polls.values()):
                if got:
                    spans.extend(got)
        try:
            mon = await self.monc.command("dump_traces",
                                          trace_id=trace_id)
            spans.extend((mon.get("data") or {}).get("spans", []))
        except (ConnectionError, asyncio.TimeoutError, KeyError):
            pass
        seen: dict[str, dict] = {}
        for s in spans:
            seen.setdefault(str(s.get("span_id")), s)
        return assemble_tree(list(seen.values()))

    # -- forensics (flight-recorder capture) -------------------------------
    def forensics_dir(self) -> str:
        d = str(self.conf["forensics_dir"] or "")
        if not d:
            d = os.path.join(tempfile.gettempdir(),
                             "ceph_tpu_forensics")
        os.makedirs(d, exist_ok=True)
        return d

    def forensics_index(self) -> list[dict]:
        """Bundles captured this mgr session, newest last (the
        dashboard /api/forensics listing and ``forensics ls`` asok)."""
        return list(self._forensics)

    def forensics_bundle(self, bundle_id: str) -> dict | None:
        """Load one bundle back from disk by id (index entries carry
        the path, so this also works across mgr restarts when the
        caller knows the directory)."""
        for entry in self._forensics:
            if entry["id"] == bundle_id:
                try:
                    with open(entry["path"]) as f:
                        return json.load(f)
                except (OSError, ValueError):
                    return None
        return None

    async def forensics_capture(self, reason: str,
                                worst_daemon: str = "",
                                detail: dict | None = None) -> dict:
        """Fan a ``forensics_capture`` over every up daemon, snapshot
        the mon + mgr + process journals, merge one epoch-aligned
        timeline, and persist the JSON bundle.  Returns the index
        entry (id, path, worst_daemon, ...)."""
        window = float(self.conf["forensics_window_s"])
        daemons: dict[str, dict] = {}
        events: list[dict] = []
        osdmap = self.monc.osdmap
        if osdmap is not None:
            polls = {
                osd: self.osd_request(osd, info.addr,
                                      "forensics_capture",
                                      window_s=window)
                for osd, info in osdmap.osds.items() if info.up
            }
            got_all = await asyncio.gather(*polls.values())
            for osd, got in zip(polls, got_all):
                if got:
                    got.pop("tid", None)
                    daemons[f"osd.{osd}"] = got
                    events.extend(got.get("events", ()))
        try:
            mon = await self.monc.command("dump_events",
                                          window_s=window)
            md = mon.get("data") or {}
            if md.get("events") or md.get("stats"):
                daemons["mon"] = {"events": md.get("events", []),
                                  "journal": md.get("stats", {})}
                events.extend(md.get("events", ()))
        except (ConnectionError, asyncio.TimeoutError, KeyError):
            md = {}
        # process-global emitters (failpoints, chaos schedule, mesh
        # launches): prefer the mon's view, fall back to our own —
        # in this tree both see the same module-level ring
        proc_events = md.get("proc_events") \
            or proc_journal().snapshot(window)
        if proc_events:
            daemons["proc"] = {"events": proc_events}
            events.extend(proc_events)
        own = self.journal.snapshot(window)
        if own:
            daemons[self.name] = {"events": own}
            events.extend(own)
        timeline = merge_timeline(events)
        if not worst_daemon:
            worst_daemon = self._worst_from_bundle(daemons)
        # mgr-module state at capture time (the QoS controller's AIMD
        # position, pushed hedge timeouts, shed counts): a forensic
        # bundle must show what the defense plane was DOING when the
        # violation fired, not just what the daemons saw
        module_state: dict[str, dict] = {}
        for mname, mod in self.modules.items():
            hook = getattr(mod, "forensics_contrib", None)
            if hook is None:
                continue
            try:
                contrib = hook()
            except Exception:
                continue
            if contrib:
                module_state[mname] = contrib
        self._forensics_seq += 1
        bundle_id = (f"forensics-{int(time.time())}"
                     f"-{self._forensics_seq:03d}")
        bundle = {
            "id": bundle_id,
            "reason": reason,
            "captured_at": time.time(),
            "window_s": window,
            "worst_daemon": worst_daemon,
            "detail": detail or {},
            "daemons": daemons,
            "modules": module_state,
            "timeline": timeline,
        }
        path = os.path.join(self.forensics_dir(), f"{bundle_id}.json")
        try:
            with open(path, "w") as f:
                json.dump(bundle, f)
        except OSError:
            path = ""
        entry = {
            "id": bundle_id, "path": path, "reason": reason,
            "captured_at": bundle["captured_at"],
            "worst_daemon": worst_daemon,
            "events": len(timeline),
            "daemons": sorted(daemons),
        }
        self._forensics.append(entry)
        del self._forensics[:-64]        # bounded in-memory index
        self._last_capture_mono = time.monotonic()
        self.journal.emit("forensics.capture", reason=reason,
                          bundle=bundle_id,
                          worst_daemon=worst_daemon,
                          events=len(timeline))
        return dict(entry)

    @staticmethod
    def _worst_from_bundle(daemons: dict[str, dict]) -> str:
        """Fallback attribution when the trigger carried no payload:
        the daemon with the most slow ops in its captured ring, else
        the one with the deepest sampled queue."""
        worst, score = "", 0
        for name, d in daemons.items():
            slow = d.get("slow_ops") or {}
            n = int(slow.get("num_ops", 0) or 0)
            if n > score:
                worst, score = name, n
        return worst

    async def maybe_auto_capture(self, reason: str,
                                 worst_daemon: str = "",
                                 detail: dict | None = None
                                 ) -> dict | None:
        """Cooldown-gated capture for automatic triggers: a flapping
        health check must not storm bundles."""
        cd = float(self.conf["forensics_cooldown_s"])
        if (self._last_capture_mono
                and time.monotonic() - self._last_capture_mono < cd):
            return None
        try:
            return await self.forensics_capture(
                reason, worst_daemon=worst_daemon, detail=detail)
        except (ConnectionError, asyncio.TimeoutError):
            return None

    # -- PGMap digest (DaemonServer + PGMap aggregation) -------------------
    async def collect_pg_stats(self) -> dict[int, list[dict]]:
        """Poll every up OSD for per-PG stats (the MPGStats pull)."""
        osdmap = self.monc.osdmap
        if osdmap is None:
            return {}
        polls = {
            osd: self._poll_osd(osd, info.addr, what="pg_stats")
            for osd, info in osdmap.osds.items() if info.up
        }
        results = await asyncio.gather(*polls.values())
        return {osd: pgs for osd, pgs in zip(polls, results)
                if pgs is not None}

    async def build_digest(self) -> dict:
        """Fold per-OSD PG stats into the PGMap digest the monitor's
        MgrStatMonitor persists (reference src/mon/PGMap.cc summaries)."""
        per_osd = await self.collect_pg_stats()
        pgs_by_state: dict[str, int] = {}
        pools: dict[int, dict] = {}
        num_objects = num_bytes = degraded = misplaced = 0
        pool_names = {}
        osd_df: dict[int, dict] = {}
        osdmap = self.monc.osdmap
        if osdmap is not None:
            pool_names = {p.pool_id: p.name
                          for p in osdmap.pools.values()}
        seen: set[str] = set()
        for osd, pgs in sorted(per_osd.items()):
            osd_bytes = 0
            for st in pgs:
                osd_bytes += int(st.get("num_bytes", 0))
                pgid = str(st.get("pgid"))
                if pgid in seen:
                    continue          # one primary report per PG wins
                seen.add(pgid)
                state = str(st.get("state", "unknown"))
                pgs_by_state[state] = pgs_by_state.get(state, 0) + 1
                num_objects += int(st.get("num_objects", 0))
                num_bytes += int(st.get("num_bytes", 0))
                degraded += int(st.get("degraded", 0))
                misplaced += int(st.get("misplaced", 0))
                pid = int(st.get("pool", 0))
                p = pools.setdefault(pid, {
                    "name": pool_names.get(pid, str(pid)),
                    "num_pgs": 0, "num_objects": 0, "num_bytes": 0,
                    "degraded": 0, "misplaced": 0,
                })
                p["num_pgs"] += 1
                p["num_objects"] += int(st.get("num_objects", 0))
                p["num_bytes"] += int(st.get("num_bytes", 0))
                p["degraded"] += int(st.get("degraded", 0))
                p["misplaced"] += int(st.get("misplaced", 0))
            osd_df[osd] = {"bytes_used": osd_bytes}
        return {
            "pgs_by_state": pgs_by_state,
            "num_pgs": len(seen),
            "num_objects": num_objects,
            "num_bytes": num_bytes,
            "degraded_objects": degraded,
            "misplaced_objects": misplaced,
            "pools": pools,
            "osd_df": osd_df,
        }

    async def report(self) -> dict:
        """One aggregation + module + push cycle (MMonMgrReport).
        Two passes: serve + health first, so modules that OBSERVE the
        digest (telemetry) see health_checks populated."""
        digest = await self.build_digest()
        health: dict = {}
        for mod in self.modules.values():
            await mod.serve_once()
            health.update(mod.health_checks())
        if health:
            digest["health_checks"] = health
        for mod in self.modules.values():
            observe = getattr(mod, "observe_digest", None)
            if observe is not None:
                observe(digest)
            digest.update(mod.digest_contrib())
        self.last_digest = digest       # dashboard/metrics snapshot
        await self.monc.command("mgr report", digest=digest)
        return digest

    async def report_loop(self, interval: float = 1.0) -> None:
        """Periodic digest push; run as a task alongside the mgr."""
        while True:
            try:
                await self.report()
            except (ConnectionError, asyncio.TimeoutError, KeyError):
                pass
            await asyncio.sleep(interval)

    # -- prometheus exposition ---------------------------------------------
    def prometheus_extra(self) -> dict[str, dict]:
        """Gauge families contributed by modules (``prom_metrics``
        hook): the SLO burn rates + utilization rates ride the same
        scrape as the daemon counters."""
        extra: dict[str, dict] = {}
        for mod in self.modules.values():
            hook = getattr(mod, "prom_metrics", None)
            if hook is not None:
                extra.update(hook())
        return extra

    @staticmethod
    def prometheus_text(snapshot: dict,
                        extra: dict[str, dict] | None = None) -> str:
        """Render one snapshot in the text exposition format, with the
        metric names the reference prometheus module exports.
        ``extra`` appends module gauge families (name -> {"help",
        "type"?, "samples": [(labels, value)]}).  Label values are
        escaped per the exposition spec and ``# HELP``/``# TYPE``
        lines are emitted once per metric name even when several
        daemons (or an extra family) export the same series."""
        lines: list[str] = []
        described: set[str] = set()

        def metric(name: str, help_: str, samples: list[tuple[str, float]],
                   mtype: str = "gauge") -> None:
            if name not in described:
                described.add(name)
                lines.append(f"# HELP {name} {help_}")
                lines.append(f"# TYPE {name} {mtype}")
            for labels, value in samples:
                lines.append(f"{name}{labels} {value:g}")

        st = snapshot["status"]
        health = {"HEALTH_OK": 0, "HEALTH_WARN": 1, "HEALTH_ERR": 2}.get(
            st["health"]["status"], 2
        )
        metric("ceph_health_status", "cluster health (0=ok 1=warn 2=err)",
               [("", health)])
        om = st["osdmap"]
        metric("ceph_osd_stat", "osd counts by state", [
            ('{state="total"}', om["num_osds"]),
            ('{state="up"}', om["num_up_osds"]),
            ('{state="in"}', om["num_in_osds"]),
        ])
        metric("ceph_pool_count", "pools", [("", om["num_pools"])])
        metric("ceph_mon_quorum_count", "monitors in quorum",
               [("", len(st["mon"]["quorum"]))])
        up_samples = [
            (prom_label(ceph_daemon=f"osd.{osd}"),
             1.0 if info["up"] else 0.0)
            for osd, info in sorted(snapshot["osds"].items())
        ]
        if up_samples:
            metric("ceph_osd_up", "osd up state", up_samples)
        # per-osd counters, split by dump shape: scalars stay one
        # metric per key; (sum, avgcount) pairs export as *_sum /
        # *_count (NOT collapsed to the sum — the count is what turns
        # a total into a rate); log2 histograms export the full
        # prometheus histogram triplet *_bucket{le=...} (cumulative) /
        # *_sum / *_count per daemon, plus cluster-merged p50/p99
        # gauges (hist_merge across daemons, hist_quantile).
        scalars: dict[str, list[tuple[str, float]]] = {}
        pairs: dict[str, list[tuple[str, float, float]]] = {}
        hists: dict[str, list[tuple[str, dict]]] = {}
        merged: dict[str, dict] = {}
        for osd, counters in sorted(snapshot["osd_perf"].items()):
            lab = prom_label(ceph_daemon=f"osd.{osd}")
            for key, value in sorted(counters.items()):
                if isinstance(value, dict) and "buckets" in value:
                    hists.setdefault(key, []).append(
                        (f"osd.{osd}", value))
                    merged[key] = hist_merge(merged.get(key), value)
                elif isinstance(value, dict) and (
                        "sum" in value or "avgcount" in value):
                    pairs.setdefault(key, []).append(
                        (lab, float(value.get("sum", 0.0)),
                         float(value.get("avgcount", 0))))
                elif isinstance(value, dict):
                    # nested structured sections (ec_kernels) are not
                    # counters; they ride the digest, not the scrape
                    continue
                else:
                    scalars.setdefault(key, []).append(
                        (lab, float(value)))
        for key, samples in sorted(scalars.items()):
            metric(f"ceph_osd_{key}", f"osd {key} perf counter", samples,
                   mtype="counter")
        for key, entries in sorted(pairs.items()):
            metric(f"ceph_osd_{key}_sum", f"osd {key} total",
                   [(lab, s) for lab, s, _ in entries], mtype="counter")
            metric(f"ceph_osd_{key}_count", f"osd {key} samples",
                   [(lab, c) for lab, _, c in entries], mtype="counter")
        for key, entries in sorted(hists.items()):
            base = f"ceph_osd_{key}"
            if base not in described:
                described.add(base)
                lines.append(f"# HELP {base} osd {key} log2 histogram")
                lines.append(f"# TYPE {base} histogram")
            for daemon, h in entries:
                dlab = prom_escape(daemon)
                cum = 0
                for i, c in enumerate(h.get("buckets", ())):
                    cum += int(c)
                    le = bucket_le(i)
                    le_s = "+Inf" if math.isinf(le) else f"{le:g}"
                    lines.append(
                        f'{base}_bucket{{ceph_daemon="{dlab}",'
                        f'le="{le_s}"}} {cum:g}')
                lines.append(f'{base}_sum{{ceph_daemon="{dlab}"}} '
                             f'{float(h.get("sum", 0.0)):g}')
                lines.append(f'{base}_count{{ceph_daemon="{dlab}"}} '
                             f'{int(h.get("count", 0)):g}')
            m = merged[key]
            metric(f"{base}_quantile",
                   f"cluster-merged {key} quantiles",
                   [('{q="0.5"}', hist_quantile(m, 0.5) or 0.0),
                    ('{q="0.99"}', hist_quantile(m, 0.99) or 0.0)])
        for name, fam in sorted((extra or {}).items()):
            metric(name, str(fam.get("help", name)),
                   list(fam.get("samples", ())),
                   mtype=str(fam.get("type", "gauge")))
        return "\n".join(lines) + "\n"
