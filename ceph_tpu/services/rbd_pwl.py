"""Persistent write-back log for rbd images (the pwl/RWL role).

Reference src/librbd/cache/ReplicatedWriteLog.cc (+ cache/pwl/*): a
client-local persistent log in front of an image.  Writes persist to
the log and ack immediately (crash-consistent at local-storage
latency); a flusher retires entries to the cluster strictly in log
order; after a client crash, reopening the cache replays unretired
entries, so acked writes are never lost and the cluster image only
ever reflects a prefix of the acked write stream (the pwl ordering
guarantee).

Divergences from the reference, TPU-host-first: the log is a plain
crc-framed append file (no PMEM/DAX; frame format shared with nothing
else — torn tails truncate at the first bad frame like store/walstore),
and the in-memory overlay is a seq-ordered list merged at read time
(at DevCluster scale a linear merge beats the reference's AVL extent
trees).  Journaling (rbd_journal.py) and pwl are alternative write
paths — layering both would double-log, as in the reference.
"""

from __future__ import annotations

import os
import struct
import zlib

_MAGIC = 0x52574C31            # "RWL1"
_HDR = struct.Struct("<IIQQI")  # magic, len, seq, offset, crc
_CRC_HDR = struct.Struct("<IIQQ")   # the crc-covered header prefix


def _frame_crc(ln: int, seq: int, off: int, data: bytes) -> int:
    """CRC covers the header fields AND the payload: a bit-flip in the
    offset must fail validation, not replay good data at the wrong
    image location."""
    return zlib.crc32(data, zlib.crc32(
        _CRC_HDR.pack(_MAGIC, ln, seq, off)))


class PersistentWriteLog:
    """Wraps an open Image with a file-backed write-back log."""

    def __init__(self, image, path: str,
                 capacity: int = 64 << 20):
        self.image = image
        self.path = path
        self.capacity = capacity
        self._f = None
        self._seq = 0
        # pending entries in log order: (seq, offset, bytes)
        self._pending: list[tuple[int, int, bytes]] = []
        self._log_bytes = 0
        import asyncio

        self._flush_lock = asyncio.Lock()

    # -- log file ----------------------------------------------------------
    async def open(self) -> None:
        """Open (or create) the log; replay any unretired entries left
        by a crash into the overlay so acked writes stay visible."""
        replayed = self._read_log() if os.path.exists(self.path) else []
        self._f = open(self.path, "ab")
        for seq, off, data in replayed:
            self._pending.append((seq, off, data))
            self._seq = max(self._seq, seq)
        self._log_bytes = self._f.tell()

    def _read_log(self) -> list[tuple[int, int, bytes]]:
        """Parse frames; stop at the first torn/corrupt frame and
        truncate there (prefix semantics — a torn ack was never
        returned to the caller)."""
        entries = []
        with open(self.path, "rb") as f:
            raw = f.read()
        pos = 0
        good = 0
        while pos + _HDR.size <= len(raw):
            magic, ln, seq, off, crc = _HDR.unpack_from(raw, pos)
            end = pos + _HDR.size + ln
            if magic != _MAGIC or end > len(raw):
                break
            data = raw[pos + _HDR.size:end]
            if _frame_crc(ln, seq, off, data) != crc:
                break
            entries.append((seq, off, data))
            pos = good = end
        if good < len(raw):
            with open(self.path, "r+b") as f:
                f.truncate(good)
        return entries

    def _append_frame(self, seq: int, off: int, data: bytes) -> None:
        frame = _HDR.pack(_MAGIC, len(data), seq, off,
                          _frame_crc(len(data), seq, off, data)) + data
        self._f.write(frame)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._log_bytes += len(frame)

    # -- data path ---------------------------------------------------------
    async def write(self, offset: int, data: bytes) -> None:
        """Persist to the log and ack; the cluster write happens at
        flush/retire time.  Over-capacity applies backpressure by
        flushing synchronously (the reference's dirty high-water)."""
        if self._f is None:
            raise IOError("pwl not open")
        if offset + len(data) > self.image.size:
            raise IOError("write past end of image")
        data = bytes(data)
        self._seq += 1
        self._append_frame(self._seq, offset, data)
        self._pending.append((self._seq, offset, data))
        if self._log_bytes > self.capacity:
            await self.flush()

    async def read(self, offset: int, length: int) -> bytes:
        """Image data with the pending overlay merged in log order
        (newest write wins per byte)."""
        if self._f is None:
            raise IOError("pwl not open")
        base = bytearray(await self.image.read(offset, length))
        length = len(base)
        for _seq, off, data in self._pending:
            lo = max(off, offset)
            hi = min(off + len(data), offset + length)
            if lo < hi:
                base[lo - offset:hi - offset] = \
                    data[lo - off:hi - off]
        return bytes(base)

    async def flush(self) -> None:
        """Retire pending entries to the cluster IN LOG ORDER, then
        roll the log.  Only the snapshot taken at entry is retired and
        dropped — writes acked while the flush awaited stay pending
        and keep their log frames (the rewrite below), so a concurrent
        ack is never lost.  A crash mid-flush re-applies a prefix on
        replay — full-data writes make that idempotent."""
        if self._f is None:
            raise IOError("pwl not open")
        async with self._flush_lock:
            n = len(self._pending)
            for _seq, off, data in self._pending[:n]:
                await self.image.write(off, data)
            await self.image.flush()
            del self._pending[:n]
            # roll the file AFTER the cluster flush completed; frames
            # for still-pending (concurrently acked) writes are
            # rewritten synchronously — no await between truncate and
            # rewrite, so no ack can slip in between
            self._f.truncate(0)
            self._f.seek(0)
            self._log_bytes = 0
            for seq, off, data in self._pending:
                self._append_frame(seq, off, data)
            self._f.flush()
            os.fsync(self._f.fileno())

    @property
    def dirty_bytes(self) -> int:
        return sum(len(d) for _, _, d in self._pending)

    async def invalidate(self) -> None:
        """Drop pending writes WITHOUT retiring them (the
        rbd_cache-invalidate escape hatch for a discarded client)."""
        self._pending.clear()
        if self._f is not None:
            self._f.truncate(0)
            self._f.flush()
            os.fsync(self._f.fileno())
            self._log_bytes = 0

    async def close(self) -> None:
        if self._f is None:
            return
        await self.flush()
        self._f.close()
        self._f = None
