"""S3 bucket-policy documents: parse, validate, evaluate.

Reference src/rgw/rgw_iam_policy.{h,cc}: IAM policy JSON attached to a
bucket, evaluated per request as (principal, action, resource) against
each statement; the verdict lattice is explicit Deny > Allow > default
(fall through to ACLs).  This is the same evaluation order the
reference implements in rgw_op.cc verify_permission (policy first,
deny short-circuits, default falls back to ACL grants).

Scope: the Principal/Action/NotAction/Resource/Effect statement core
with S3-style ``*`` wildcards.  Condition blocks are NOT supported and
are rejected at validation time — silently ignoring a condition would
grant more than the document says, the one failure mode a policy
engine must never have.
"""

from __future__ import annotations

import json
import re

ARN_S3_PREFIX = "arn:aws:s3:::"
ARN_USER_PREFIX = "arn:aws:iam:::user/"

# Exactly the actions the enforcement paths evaluate (rgw.py data-path
# _check_bucket annotations).  Bucket administration (ACL/policy/
# notification/versioning config) is NOT policy-evaluated — it stays
# owner/ACL-gated — so granting those actions would be silently inert;
# validation rejects them instead.
KNOWN_ACTIONS = frozenset({
    "s3:*",
    "s3:GetObject", "s3:GetObjectVersion",
    "s3:PutObject", "s3:DeleteObject", "s3:DeleteObjectVersion",
    "s3:ListBucket", "s3:ListBucketVersions",
    "s3:ListBucketMultipartUploads", "s3:AbortMultipartUpload",
    "s3:PutObjectRetention", "s3:GetObjectRetention",
    "s3:PutObjectLegalHold", "s3:GetObjectLegalHold",
    "s3:BypassGovernanceRetention",
    "s3:PutObjectTagging", "s3:GetObjectTagging", "s3:DeleteObjectTagging",
})


class PolicyError(ValueError):
    """Malformed policy document (maps to S3 MalformedPolicy)."""


def _listify(v) -> list:
    if isinstance(v, list):
        return v
    return [v]


def _principals(stmt: dict) -> list[str]:
    """Normalized principal ids; '*' means everyone incl. anonymous."""
    p = stmt.get("Principal")
    if p == "*":
        return ["*"]
    if isinstance(p, dict) and "AWS" in p:
        out = []
        for ent in _listify(p["AWS"]):
            if not isinstance(ent, str):
                raise PolicyError("Principal entries must be strings")
            if ent.startswith(ARN_USER_PREFIX):
                ent = ent[len(ARN_USER_PREFIX):]
            out.append(ent)
        return out
    raise PolicyError("Principal must be \"*\" or {\"AWS\": [...]}")


def _norm_resource(r: str) -> str:
    if r.startswith(ARN_S3_PREFIX):
        r = r[len(ARN_S3_PREFIX):]
    if not r:
        raise PolicyError("empty Resource")
    return r


def validate(doc: str | dict) -> dict:
    """Parse + validate a policy document; returns the parsed dict.
    Raises PolicyError on anything the evaluator would not honor
    exactly (unknown actions, Condition blocks, bad principals)."""
    if isinstance(doc, (str, bytes)):
        try:
            doc = json.loads(doc)
        except ValueError as e:
            raise PolicyError(f"not JSON: {e}") from None
    if not isinstance(doc, dict):
        raise PolicyError("policy must be a JSON object")
    stmts = doc.get("Statement")
    if not isinstance(stmts, list) or not stmts:
        raise PolicyError("Statement must be a non-empty list")
    for stmt in stmts:
        if not isinstance(stmt, dict):
            raise PolicyError("statements must be objects")
        if stmt.get("Effect") not in ("Allow", "Deny"):
            raise PolicyError("Effect must be Allow or Deny")
        if "Condition" in stmt:
            raise PolicyError("Condition blocks are not supported")
        if "NotPrincipal" in stmt:
            raise PolicyError("NotPrincipal is not supported")
        if "NotResource" in stmt:
            raise PolicyError("NotResource is not supported")
        if ("Action" in stmt) == ("NotAction" in stmt):
            raise PolicyError(
                "exactly one of Action/NotAction is required")
        for a in _listify(stmt.get("Action", stmt.get("NotAction"))):
            if not isinstance(a, str) or not a.startswith("s3:"):
                raise PolicyError(f"bad action {a!r}")
            if "*" not in a and a not in KNOWN_ACTIONS:
                raise PolicyError(f"unknown action {a!r}")
        _principals(stmt)
        resources = _listify(stmt["Resource"]) if "Resource" in stmt \
            else []
        if not resources:
            raise PolicyError("Resource is required")
        for r in resources:
            if not isinstance(r, str):
                raise PolicyError("Resource entries must be strings")
            _norm_resource(r)
    return doc


def _wild_match(pattern: str, value: str) -> bool:
    """AWS policy wildcards: only ``*`` (any run) and ``?`` (any one
    char) are metacharacters — brackets etc. match literally (fnmatch
    character classes would silently change Deny semantics for keys
    containing ``[``)."""
    rx = "".join(
        ".*" if ch == "*" else "." if ch == "?" else re.escape(ch)
        for ch in pattern
    )
    return re.fullmatch(rx, value) is not None


def _match_any(patterns: list[str], value: str) -> bool:
    return any(_wild_match(p, value) for p in patterns)


def _stmt_matches(stmt: dict, principal: str, action: str,
                  resource: str) -> bool:
    prins = _principals(stmt)
    if "*" not in prins and principal not in prins:
        return False
    acts = _listify(stmt["Action"]) if "Action" in stmt else []
    nacts = _listify(stmt["NotAction"]) if "NotAction" in stmt else []
    if acts:
        if not _match_any(acts, action):
            return False
    elif _match_any(nacts, action):
        return False
    res = [_norm_resource(r) for r in _listify(stmt["Resource"])]
    return _match_any(res, resource)


def evaluate(doc: dict, principal: str, action: str,
             resource: str) -> str:
    """'deny' | 'allow' | 'default' (explicit deny wins; no match
    falls back to the caller's ACL path)."""
    verdict = "default"
    for stmt in doc.get("Statement", ()):
        if not _stmt_matches(stmt, principal, action, resource):
            continue
        if stmt["Effect"] == "Deny":
            return "deny"
        verdict = "allow"
    return verdict
