"""RGW multisite: asynchronous zone-to-zone data sync, graded.

The role of reference src/rgw/rgw_data_sync.cc (5,054 LoC of coroutine
machinery) at -lite scale, keeping its defining design: the SOURCE zone
maintains per-bucket data logs (cls_rgw bilog, appended atomically by
the gateway on every mutation), and an independent SYNC AGENT on the
secondary zone tails those logs and replays the mutations — pull-based,
asynchronous, restartable, with the sync position persisted on the
SECONDARY (so a restarted agent resumes where it left off, and the
primary needs no knowledge of its peers). Two phases per bucket, exactly
like the reference:

- FULL SYNC: a new bucket is bootstrapped by snapshotting every shard's
  log position FIRST, then copying every listed object — mutations that
  land mid-copy are past the snapshot, so the incremental phase replays
  them and nothing is trimmed before it has been replayed.
- INCREMENTAL: replay log entries past the stored per-shard marker; a
  put copies the object's CURRENT content (replays converge to the
  newest state), a delete tolerates already-gone keys. Applied entries
  advance the marker; the source shard is trimmed up to the low-water
  mark (radosgw-admin datalog trim role).

Geo-replication extensions over the original agent:

- SHARDED CURSORS: the datalog is sharded by object key
  (``rgw_datalog_shards``); the agent keeps one persisted cursor per
  (bucket, shard) and replays/trims shards independently, with a
  deterministic per-shard exponential backoff on errors.
- LAST-WRITER-WINS: replicated puts stamp the source mtime and zone id
  into object metadata (``rgw-source-mtime`` / ``rgw-source-zone``);
  before overwriting, the agent compares (mtime, zone) pairs and skips
  stale incoming writes.  The pair is a pure function of the original
  client write and totally ordered (zone id breaks mtime ties), so two
  zones that both wrote the same key during a partition converge to the
  same winner no matter the replay order.
- MEASUREMENT: ``rgw-sync`` perf counters (replicated puts/deletes/
  bytes, reconciles, trims, conflicts, paced waits), a :meth:`lag`
  ledger pricing unreplicated entries in entries AND bytes per shard
  (the RPO cursor ledger the zone-loss drill grades against), and
  ``sync.{full,incr,trim}`` flight-recorder events.
- PACING: :meth:`set_rate` installs a token-bucket rate limit on
  replicated ops — the actuation point for the replication QoS class
  (``qos_replication_*``), so a burning client SLO sheds replication
  bandwidth down to a floor instead of letting it trample the tail.

This is the framework's geo/DCN replication analog (SURVEY §2.10
"cross-cluster" row): the data path between zones is ordinary object
IO, asynchronous with respect to client writes on the primary.
"""

from __future__ import annotations

import asyncio

from ceph_tpu.client.rados import RadosError
from ceph_tpu.common.backoff import ExpBackoff
from ceph_tpu.common.events import emit_proc
from ceph_tpu.common.log import Dout
from ceph_tpu.common.perf import CounterType, PerfCounters
from ceph_tpu.common.qos import TokenBucket
from ceph_tpu.services.rgw import RGWError, RGWLite

log = Dout("rgw-sync")

STATUS_OID = "rgw.sync.status"   # secondary-side omap: bucket/shard -> seq

# metadata keys carrying LWW provenance on replicated objects
META_MTIME = "rgw-source-mtime"
META_ZONE = "rgw-source-zone"


def _marker_key(bucket: str, shard: int) -> str:
    # NUL separator: bucket names may legally contain dots/digits, so a
    # dotted suffix would collide with a bucket literally named "b.1"
    return f"{bucket}\x00{shard}"


class RGWSyncAgent:
    def __init__(self, src: RGWLite, dst: RGWLite,
                 poll_interval: float = 0.2, trim: bool = True,
                 src_zone: str = "", dst_zone: str = "",
                 seed: int = 0):
        self.src = src
        self.dst = dst
        self.poll_interval = poll_interval
        self.trim = trim
        self.src_zone = src_zone
        self.dst_zone = dst_zone
        self.shards = max(1, int(getattr(src, "datalog_shards", 1)))
        self._task: asyncio.Task | None = None
        self._stopped = False
        self.synced_ops = 0
        # replication-class pacing (QoS actuation point): 0 = unlimited
        self.rate_ops = 0.0
        self._bucket: TokenBucket | None = None
        # per-(bucket, shard) error backoff: deterministic jitter, and
        # a not-before deadline so one failing shard never stalls the
        # healthy ones
        self._seed = seed
        self._backoff: dict[str, ExpBackoff] = {}
        self._defer_until: dict[str, float] = {}
        self.perf = PerfCounters(
            f"rgw-sync-{dst_zone or 'dst'}" if dst_zone else "rgw-sync")
        for key in ("sync_put_ops", "sync_del_ops",
                    "sync_reconcile_ops", "sync_bytes",
                    "sync_full_passes", "sync_incr_passes",
                    "sync_trims", "sync_retries",
                    "sync_conflict_skips", "sync_paced_waits",
                    "sync_purged", "sync_errors"):
            self.perf.add(key, CounterType.U64)
        for key in ("sync_trim_seq", "sync_lag_entries",
                    "sync_lag_bytes"):
            self.perf.add(key, CounterType.GAUGE)

    # -- pacing (replication QoS class actuation) -------------------------
    def set_rate(self, ops_per_s: float) -> None:
        """Install the replication-class pacing rate the QoS controller
        decided (0 disables pacing).  Burst = 1s of grants so a retune
        takes effect within the next handful of ops."""
        ops_per_s = max(0.0, float(ops_per_s))
        if ops_per_s == self.rate_ops:
            return
        self.rate_ops = ops_per_s
        if ops_per_s <= 0.0:
            self._bucket = None
            return
        now = asyncio.get_event_loop().time()
        self._bucket = TokenBucket(ops_per_s, max(1.0, ops_per_s), now)

    async def _pace(self) -> None:
        b = self._bucket
        if b is None:
            return
        loop = asyncio.get_event_loop()
        while not b.take(loop.time()):
            self.perf.inc("sync_paced_waits")
            await asyncio.sleep(max(b.retry_after(), 0.001))

    # -- sync position (persisted on the secondary) ----------------------
    async def _get_marker(self, bucket: str,
                          shard: int = 0) -> int | None:
        keys = [_marker_key(bucket, shard)]
        if shard == 0:
            keys.append(bucket)     # pre-shard agents stored bare names
        try:
            kv = await self.dst.ioctx.get_omap(STATUS_OID, keys)
        except RadosError as e:
            if e.rc == -2:
                return None
            raise
        for k in keys:
            if k in kv:
                return int(kv[k])
        return None

    async def _set_marker(self, bucket: str, shard: int,
                          seq: int) -> None:
        from ceph_tpu.client.rados import ObjectOperation

        await self.dst.ioctx.operate(STATUS_OID, ObjectOperation()
                                     .create()
                                     .omap_set({
                                         _marker_key(bucket, shard):
                                         str(seq).encode(),
                                     }))

    async def markers(self) -> dict[str, dict[int, int]]:
        """All persisted cursors: bucket -> shard -> seq."""
        try:
            kv = await self.dst.ioctx.get_omap(STATUS_OID)
        except RadosError as e:
            if e.rc == -2:
                return {}
            raise
        out: dict[str, dict[int, int]] = {}
        for k, v in kv.items():
            if "\x00" in k:
                bucket, _, shard = k.rpartition("\x00")
                out.setdefault(bucket, {})[int(shard)] = int(v)
            else:
                out.setdefault(k, {}).setdefault(0, int(v))
        return out

    # -- last-writer-wins ------------------------------------------------
    @staticmethod
    def _lww_pair(got: dict, default_zone: str) -> tuple[float, str]:
        """The (mtime, zone) provenance pair of an object: replicated
        copies carry it in metadata; local client writes fall back to
        the index mtime and the owning zone's id."""
        meta = got.get("meta") or {}
        try:
            mtime = float(meta.get(META_MTIME, ""))
        except (TypeError, ValueError):
            mtime = float(got.get("mtime") or 0.0)
        zone = str(meta.get(META_ZONE) or default_zone)
        return (mtime, zone)

    async def _dst_pair(self, bucket: str,
                        key: str) -> tuple[float, str] | None:
        try:
            got = await self.dst.get_object(bucket, key)
        except (RGWError, RadosError) as e:
            if isinstance(e, RGWError) and e.code in (
                    "NoSuchKey", "NoSuchBucket"):
                return None
            if isinstance(e, RadosError) and e.rc == -2:
                return None
            raise
        return self._lww_pair(got, self.dst_zone)

    # -- object replay ----------------------------------------------------
    async def _replicate_put(self, bucket: str, key: str,
                             force: bool = False) -> None:
        try:
            got = await self.src.get_object(bucket, key)
        except RGWError as e:
            if e.code == "NoSuchKey":
                return          # deleted again since; the del entry follows
            raise
        pair = self._lww_pair(got, self.src_zone)
        if not force:
            local = await self._dst_pair(bucket, key)
            if local is not None and pair < local:
                # the destination already holds a newer write (total
                # order: mtime, then zone id) — applying would
                # un-converge
                self.perf.inc("sync_conflict_skips")
                return
        await self._pace()
        meta = dict(got.get("meta") or {})
        meta.setdefault(META_MTIME, repr(pair[0]))
        meta.setdefault(META_ZONE, pair[1])
        await self.dst.put_object(
            bucket, key, got["data"],
            content_type=got.get("content_type", "binary/octet-stream"),
            metadata=meta,
            tags=got.get("tags") or None,
        )
        self.perf.inc("sync_put_ops")
        self.perf.inc("sync_bytes", len(got.get("data") or b""))

    async def _replicate_del(self, bucket: str, key: str,
                             mtime: float = 0.0) -> None:
        if mtime > 0.0:
            local = await self._dst_pair(bucket, key)
            if local is not None and local > (mtime, self.src_zone):
                # a write newer than the delete landed here; LWW keeps it
                self.perf.inc("sync_conflict_skips")
                return
        await self._pace()
        try:
            await self.dst.delete_object(bucket, key)
            self.perf.inc("sync_del_ops")
        except RGWError as e:
            if e.code != "NoSuchKey":
                raise

    async def _reconcile(self, bucket: str, key: str) -> None:
        """Mirror the key's CURRENT source state.  Version-level ops
        (del-version restores/promotions) change what is current
        without being a plain put/del, so re-read and converge."""
        self.perf.inc("sync_reconcile_ops")
        try:
            got = await self.src.get_object(bucket, key)
        except RGWError as e:
            if e.code != "NoSuchKey":
                raise
            await self._replicate_del(bucket, key)
            return
        await self._pace()
        meta = dict(got.get("meta") or {})
        pair = self._lww_pair(got, self.src_zone)
        meta.setdefault(META_MTIME, repr(pair[0]))
        meta.setdefault(META_ZONE, pair[1])
        await self.dst.put_object(
            bucket, key, got["data"],
            content_type=got.get("content_type", "binary/octet-stream"),
            metadata=meta,
            tags=got.get("tags") or None,
        )

    # -- phases ------------------------------------------------------------
    async def _full_sync(self, bucket: str) -> dict[int, int]:
        """Bootstrap a bucket: EVERY shard's log position first, then
        copy everything (writes racing the copy land past the snapshot,
        so incremental replay covers them and trim — which only runs
        behind the replay cursor — can never discard them unreplayed).

        Full sync treats the source as AUTHORITATIVE: listed keys are
        copied unconditionally (no last-writer-wins skip) and
        destination keys absent from the source listing are PURGED.
        In the active-passive model the only way the destination
        diverges at bootstrap is a previous life of this zone: writes
        it acked before it died that never replicated out — exactly
        the loss the RPO ledger priced — so a revived zone resyncing
        from the promoted master rolls them back to converge
        bit-identically.  A fresh secondary's bucket is empty, so both
        rules are no-ops on normal bootstrap; LWW still governs the
        incremental phase, where both sides are live."""
        positions: dict[int, int] = {}
        for shard in range(self.shards):
            positions[shard] = int(
                (await self.src.log_list(bucket, after=0,
                                         max_entries=1, shard=shard))
                .get("max_seq", 0))
        if bucket not in await self.dst.list_buckets():
            await self.dst.create_bucket(bucket)
        marker = ""
        copied = 0
        src_keys: set[str] = set()
        while True:
            listing = await self.src.list_objects(bucket, marker=marker)
            for entry in listing["contents"]:
                src_keys.add(entry["key"])
                await self._replicate_put(bucket, entry["key"],
                                          force=True)
                self.synced_ops += 1
                copied += 1
            if not listing["is_truncated"]:
                break
            marker = listing["next_marker"]
        purged = 0
        marker = ""
        while True:
            listing = await self.dst.list_objects(bucket, marker=marker)
            for entry in listing["contents"]:
                if entry["key"] in src_keys:
                    continue
                await self._pace()
                try:
                    await self.dst.delete_object(bucket, entry["key"])
                except RGWError as e:
                    if e.code != "NoSuchKey":
                        raise
                self.perf.inc("sync_purged")
                purged += 1
            if not listing["is_truncated"]:
                break
            marker = listing["next_marker"]
        for shard, position in positions.items():
            await self._set_marker(bucket, shard, position)
            if self.trim and position > 0:
                # the copy mirrored every mutation at/below the
                # snapshot, so the entries behind it are replayed by
                # construction — trim them or idle shards hold their
                # bootstrap backlog forever
                await self.src.log_trim(bucket, position, shard=shard)
                self.perf.inc("sync_trims")
                emit_proc("sync.trim", bucket=bucket, shard=shard,
                          source=self.src_zone, upto=position)
        self.perf.inc("sync_full_passes")
        emit_proc("sync.full", bucket=bucket, zone=self.dst_zone,
                  source=self.src_zone, objects=copied, purged=purged,
                  positions={str(s): p for s, p in positions.items()})
        log.dout(5, "full sync of %s done at %r (purged %d)",
                 bucket, positions, purged)
        return positions

    async def _incremental(self, bucket: str, shard: int,
                           after: int) -> int:
        listing = await self.src.log_list(bucket, after=after,
                                          shard=shard)
        last = after
        applied = 0
        for entry in listing["entries"]:
            if entry["op"] == "put":
                await self._replicate_put(bucket, entry["key"])
            elif entry["op"] == "del":
                await self._replicate_del(
                    bucket, entry["key"],
                    mtime=float(entry.get("mtime") or 0.0))
            else:
                # del-version &co: converge on current source state
                await self._reconcile(bucket, entry["key"])
            last = int(entry["seq"])
            self.synced_ops += 1
            applied += 1
        if last != after:
            await self._set_marker(bucket, shard, last)
            self.perf.inc("sync_incr_passes")
            emit_proc("sync.incr", bucket=bucket, shard=shard,
                      zone=self.dst_zone, source=self.src_zone,
                      applied=applied, marker=last)
            if self.trim:
                await self.src.log_trim(bucket, last, shard=shard)
                self.perf.inc("sync_trims")
                self.perf.set("sync_trim_seq", last)
                emit_proc("sync.trim", bucket=bucket, shard=shard,
                          source=self.src_zone, upto=last)
        return last

    async def sync_once(self) -> int:
        """One pass over every source bucket and shard; returns the
        number of ops applied.  A failing (bucket, shard) backs off
        deterministically without stalling the others."""
        before = self.synced_ops
        now = asyncio.get_event_loop().time()
        for bucket in await self.src.list_buckets():
            try:
                marker0 = await self._get_marker(bucket, 0)
            except (RadosError, ConnectionError) as e:
                log.derr("marker read for %s failed: %s", bucket, e)
                self.perf.inc("sync_errors")
                continue
            if marker0 is None:
                try:
                    await self._full_sync(bucket)
                except (RGWError, RadosError, ConnectionError) as e:
                    log.derr("full sync of %s failed: %s", bucket, e)
                    self.perf.inc("sync_errors")
                continue
            for shard in range(self.shards):
                name = _marker_key(bucket, shard)
                if self._defer_until.get(name, 0.0) > now:
                    continue
                try:
                    after = marker0 if shard == 0 else \
                        await self._get_marker(bucket, shard)
                    await self._incremental(bucket, shard,
                                            after or 0)
                except (RGWError, RadosError, ConnectionError) as e:
                    log.derr("sync of %s shard %d failed: %s",
                             bucket, shard, e)
                    self.perf.inc("sync_errors")
                    self.perf.inc("sync_retries")
                    bo = self._backoff.setdefault(name, ExpBackoff(
                        seed=self._seed, name=name))
                    self._defer_until[name] = now + bo.next_delay()
                else:
                    if name in self._backoff:
                        self._backoff[name].reset()
                        self._defer_until.pop(name, None)
        return self.synced_ops - before

    # -- RPO ledger --------------------------------------------------------
    async def lag(self) -> dict:
        """Unreplicated backlog per (bucket, shard): entries AND bytes
        acked on the source but not yet replayed here.  This is the
        cursor ledger — in a zone loss, the bytes below are exactly the
        RPO the drill must measure."""
        out: dict = {"entries": 0, "bytes": 0, "buckets": {}}
        for bucket in await self.src.list_buckets():
            bout: dict = {"entries": 0, "bytes": 0, "shards": {}}
            for shard in range(self.shards):
                after = await self._get_marker(bucket, shard) or 0
                entries = 0
                size = 0
                while True:
                    listing = await self.src.log_list(
                        bucket, after=after, shard=shard)
                    got = listing.get("entries", [])
                    if not got:
                        break
                    for e in got:
                        entries += 1
                        size += int(e.get("size") or 0)
                    after = int(got[-1]["seq"])
                bout["shards"][shard] = {"entries": entries,
                                         "bytes": size}
                bout["entries"] += entries
                bout["bytes"] += size
            out["buckets"][bucket] = bout
            out["entries"] += bout["entries"]
            out["bytes"] += bout["bytes"]
        self.perf.set("sync_lag_entries", out["entries"])
        self.perf.set("sync_lag_bytes", out["bytes"])
        return out

    def status(self) -> dict:
        """Telemetry snapshot (radosgw-admin sync status role)."""
        return {
            "source_zone": self.src_zone,
            "dest_zone": self.dst_zone,
            "shards": self.shards,
            "running": self._task is not None and not self._stopped,
            "synced_ops": self.synced_ops,
            "rate_ops": self.rate_ops,
            "counters": self.perf.dump(),
        }

    # -- daemon form -------------------------------------------------------
    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        while not self._stopped:
            try:
                await self.sync_once()
            except Exception as e:           # noqa: BLE001
                log.derr("sync pass failed: %s", e)
            try:
                await asyncio.sleep(self.poll_interval)
            except asyncio.CancelledError:
                return

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
