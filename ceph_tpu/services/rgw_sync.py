"""RGW multisite-lite: asynchronous zone-to-zone data sync.

The role of reference src/rgw/rgw_data_sync.cc (5,054 LoC of coroutine
machinery) at -lite scale, keeping its defining design: the SOURCE zone
maintains per-bucket data logs (cls_rgw bilog, appended atomically by
the gateway on every mutation), and an independent SYNC AGENT on the
secondary zone tails those logs and replays the mutations — pull-based,
asynchronous, restartable, with the sync position persisted on the
SECONDARY (so a restarted agent resumes where it left off, and the
primary needs no knowledge of its peers). Two phases per bucket, exactly
like the reference:

- FULL SYNC: a new bucket is bootstrapped by snapshotting the source
  log position FIRST, then copying every listed object — mutations that
  land mid-copy are re-applied by the incremental phase (idempotent
  puts converge).
- INCREMENTAL: replay log entries past the stored marker; a put copies
  the object's CURRENT content (replays converge to the newest state),
  a delete tolerates already-gone keys. Applied entries advance the
  marker; the source log is trimmed up to the low-water mark
  (radosgw-admin datalog trim role).

This is the framework's geo/DCN replication analog (SURVEY §2.10
"cross-cluster" row): the data path between zones is ordinary object
IO, asynchronous with respect to client writes on the primary.
"""

from __future__ import annotations

import asyncio
import json

from ceph_tpu.client.rados import RadosError
from ceph_tpu.common.log import Dout
from ceph_tpu.services.rgw import RGWError, RGWLite

log = Dout("rgw-sync")

STATUS_OID = "rgw.sync.status"       # secondary-side omap: bucket -> seq


class RGWSyncAgent:
    def __init__(self, src: RGWLite, dst: RGWLite,
                 poll_interval: float = 0.2, trim: bool = True):
        self.src = src
        self.dst = dst
        self.poll_interval = poll_interval
        self.trim = trim
        self._task: asyncio.Task | None = None
        self._stopped = False
        self.synced_ops = 0

    # -- sync position (persisted on the secondary) ----------------------
    async def _get_marker(self, bucket: str) -> int | None:
        try:
            kv = await self.dst.ioctx.get_omap(STATUS_OID, [bucket])
        except RadosError as e:
            if e.rc == -2:
                return None
            raise
        if bucket not in kv:
            return None
        return int(kv[bucket])

    async def _set_marker(self, bucket: str, seq: int) -> None:
        from ceph_tpu.client.rados import ObjectOperation

        await self.dst.ioctx.operate(STATUS_OID, ObjectOperation()
                                     .create()
                                     .omap_set({
                                         bucket: str(seq).encode(),
                                     }))

    # -- object replay ----------------------------------------------------
    async def _replicate_put(self, bucket: str, key: str) -> None:
        try:
            got = await self.src.get_object(bucket, key)
        except RGWError as e:
            if e.code == "NoSuchKey":
                return          # deleted again since; the del entry follows
            raise
        await self.dst.put_object(
            bucket, key, got["data"],
            content_type=got.get("content_type", "binary/octet-stream"),
            metadata=got.get("meta", {}),
            tags=got.get("tags") or None,
        )

    async def _replicate_del(self, bucket: str, key: str) -> None:
        try:
            await self.dst.delete_object(bucket, key)
        except RGWError as e:
            if e.code != "NoSuchKey":
                raise

    async def _reconcile(self, bucket: str, key: str) -> None:
        """Mirror the key's CURRENT source state.  Version-level ops
        (del-version restores/promotions) change what is current
        without being a plain put/del, so re-read and converge."""
        try:
            got = await self.src.get_object(bucket, key)
        except RGWError as e:
            if e.code != "NoSuchKey":
                raise
            await self._replicate_del(bucket, key)
            return
        await self.dst.put_object(
            bucket, key, got["data"],
            content_type=got.get("content_type", "binary/octet-stream"),
            metadata=got.get("meta", {}),
            tags=got.get("tags") or None,
        )

    # -- phases ------------------------------------------------------------
    async def _full_sync(self, bucket: str) -> int:
        """Bootstrap a bucket: log position first, then copy everything
        (writes racing the copy are covered by incremental replay)."""
        position = int((await self.src.log_list(bucket, after=0,
                                                max_entries=1))
                       .get("max_seq", 0))
        if bucket not in await self.dst.list_buckets():
            await self.dst.create_bucket(bucket)
        marker = ""
        while True:
            listing = await self.src.list_objects(bucket, marker=marker)
            for entry in listing["contents"]:
                await self._replicate_put(bucket, entry["key"])
                self.synced_ops += 1
            if not listing["is_truncated"]:
                break
            marker = listing["next_marker"]
        await self._set_marker(bucket, position)
        log.dout(5, "full sync of %s done at seq %d", bucket, position)
        return position

    async def _incremental(self, bucket: str, after: int) -> int:
        listing = await self.src.log_list(bucket, after=after)
        last = after
        for entry in listing["entries"]:
            if entry["op"] == "put":
                await self._replicate_put(bucket, entry["key"])
            elif entry["op"] == "del":
                await self._replicate_del(bucket, entry["key"])
            else:
                # del-version &co: converge on current source state
                await self._reconcile(bucket, entry["key"])
            last = int(entry["seq"])
            self.synced_ops += 1
        if last != after:
            await self._set_marker(bucket, last)
            if self.trim:
                await self.src.log_trim(bucket, last)
        return last

    async def sync_once(self) -> int:
        """One pass over every source bucket; returns ops applied."""
        before = self.synced_ops
        for bucket in await self.src.list_buckets():
            try:
                marker = await self._get_marker(bucket)
                if marker is None:
                    await self._full_sync(bucket)
                else:
                    await self._incremental(bucket, marker)
            except (RGWError, RadosError, ConnectionError) as e:
                log.derr("sync of bucket %s failed: %s", bucket, e)
        return self.synced_ops - before

    # -- daemon form -------------------------------------------------------
    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        while not self._stopped:
            try:
                await self.sync_once()
            except Exception as e:           # noqa: BLE001
                log.derr("sync pass failed: %s", e)
            try:
                await asyncio.sleep(self.poll_interval)
            except asyncio.CancelledError:
                return

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
