"""S3 REST frontend for RGW-lite: the asio/beast frontend role.

The reference serves S3 over HTTP through an embedded server
(src/rgw/rgw_asio_frontend.cc) that parses requests into RGWOps
(rgw_rest_s3.cc) and authenticates AWS Signature V4 headers
(rgw_auth_s3.cc).  This frontend does the same on asyncio streams:

- HTTP/1.1 keep-alive parsing (request line, headers, Content-Length
  bodies) without any web framework — the runtime stays stdlib.
- AWS SigV4 verification against the RGWUsers key table: canonical
  request -> string-to-sign -> derived signing key, exactly the
  published algorithm, so any stock S3 SDK signs compatibly.  No
  Authorization header means the ``anonymous`` identity.
- Routing: service (/), bucket (/b), object (/b/k) levels with the S3
  subresources (?versioning ?versions ?uploads ?lifecycle ?acl
  ?delete ?partNumber&uploadId), Range/ETag/x-amz-meta-* headers and
  XML bodies in the S3 namespace.

Every operation funnels into :class:`RGWLite` ``as_user(uid)`` so ACL,
quota, versioning and datalog behavior is identical to the library
path the rest of the framework (multisite sync, radosgw-admin) uses.
"""

from __future__ import annotations

import asyncio
import calendar
import hashlib
import hmac
import math
import time
import urllib.parse
import xml.etree.ElementTree as ET
from email.utils import formatdate

from ceph_tpu.common.events import emit_proc
from ceph_tpu.common.log import Dout
from ceph_tpu.common.qos import TokenBucket
from ceph_tpu.services.rgw import (
    ANONYMOUS,
    RGWError,
    RGWLite,
    RGWUsers,
    sse_check,
    sse_crypt,
)

log = Dout("rgw-http")

XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"
_MAX_BODY = 256 * 1024 * 1024       # buffered (non-streaming) bodies only
_STREAM_MIN = 1 << 20               # PUT bodies this big stream
_STREAM_CHUNK = 1 << 20
_EMPTY_SHA = hashlib.sha256(b"").hexdigest()

# RGWError code -> HTTP status (rgw_common.cc rgw_http_s3_errors)
_STATUS = {
    "AccessDenied": 403,
    "SignatureDoesNotMatch": 403,
    "InvalidAccessKeyId": 403,
    "NoSuchBucket": 404,
    "NoSuchKey": 404,
    "NoSuchUser": 404,
    "UserAlreadyExists": 409,
    "NoSuchVersion": 404,
    "NoSuchUpload": 404,
    "NoSuchLifecycleConfiguration": 404,
    "NoSuchBucketPolicy": 404,
    "NoSuchCORSConfiguration": 404,
    "NoSuchWebsiteConfiguration": 404,
    "ObjectLockConfigurationNotFoundError": 404,
    "InvalidBucketState": 409,
    "NoSuchObjectLockConfiguration": 404,
    "MalformedPolicy": 400,
    "BucketNotEmpty": 409,
    "BucketAlreadyExists": 409,
    "PreconditionFailed": 412,
    "QuotaExceeded": 403,
    "MethodNotAllowed": 405,
    "InvalidRange": 416,
    "MalformedXML": 400,
    "InvalidStorageClass": 400,
    "NotImplemented": 501,
}


class _HTTPError(Exception):
    def __init__(self, status: int, code: str, msg: str = ""):
        self.status = status
        self.code = code
        self.msg = msg


class _Request:
    def __init__(self, method: str, raw_path: str,
                 headers: dict[str, str], body: bytes):
        self.method = method
        self.headers = headers
        self.body = body
        # streaming PUT bodies: the socket reader + declared length;
        # consumed tracks how much the handler actually drained
        self.stream = None
        self.content_length = len(body)
        self.stream_consumed = 0
        path, _, query = raw_path.partition("?")
        self.raw_path = path
        self.path = urllib.parse.unquote(path)
        self.query: dict[str, str] = {}
        self.raw_query = query
        for part in query.split("&") if query else ():
            k, _, v = part.partition("=")
            self.query[urllib.parse.unquote(k)] = urllib.parse.unquote(v)

    def header(self, name: str, default: str = "") -> str:
        return self.headers.get(name.lower(), default)


# -- SigV4 (rgw_auth_s3.cc) -----------------------------------------------
def _sig_key(secret: str, date: str, region: str, service: str) -> bytes:
    k = hmac.new(b"AWS4" + secret.encode(), date.encode(),
                 hashlib.sha256).digest()
    for part in (region, service, "aws4_request"):
        k = hmac.new(k, part.encode(), hashlib.sha256).digest()
    return k


def _canonical_query(raw_query: str) -> str:
    pairs = []
    for part in raw_query.split("&") if raw_query else ():
        k, eq, v = part.partition("=")
        pairs.append((urllib.parse.unquote(k), urllib.parse.unquote(v)))
    enc = urllib.parse.quote
    return "&".join(
        f"{enc(k, safe='-_.~')}={enc(v, safe='-_.~')}"
        for k, v in sorted(pairs)
    )


def sigv4_string_to_sign(req: _Request, signed_headers: list[str],
                         scope: str, amz_date: str,
                         payload_hash: str | None = None,
                         raw_query: str | None = None) -> str:
    """The ONE SigV4 canonicalization (header auth, presigned
    verification, and URL generation all feed through here so the
    folding/quoting rules can never drift apart).  ``payload_hash``:
    presigned mode forces UNSIGNED-PAYLOAD; ``raw_query``: presigned
    verification signs the query minus X-Amz-Signature."""
    if payload_hash is None:
        payload_hash = req.header("x-amz-content-sha256")
        if payload_hash in ("", "UNSIGNED-PAYLOAD"):
            payload_hash = (payload_hash or
                            hashlib.sha256(req.body).hexdigest())
    canon_headers = "".join(
        f"{h}:{' '.join(req.header(h).split())}\n" for h in signed_headers
    )
    canon_uri = urllib.parse.quote(req.path, safe="/-_.~")
    canonical = "\n".join([
        req.method, canon_uri,
        _canonical_query(req.raw_query if raw_query is None
                         else raw_query),
        canon_headers, ";".join(signed_headers), payload_hash,
    ])
    return "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest(),
    ])


def _parse_scope_date(amz_date: str, cred_day: str) -> float:
    """x-amz-date -> epoch seconds, enforcing the credential-scope
    day match (shared by header auth and presigned verification)."""
    import calendar

    try:
        ts = calendar.timegm(time.strptime(amz_date,
                                           "%Y%m%dT%H%M%SZ"))
    except ValueError:
        raise _HTTPError(403, "AccessDenied", "bad x-amz-date")
    if amz_date[:8] != cred_day:
        raise _HTTPError(403, "SignatureDoesNotMatch",
                         "credential scope date mismatch")
    return ts


def presign_url(method: str, host: str, port: int, bucket: str,
                key: str, access_key: str, secret_key: str,
                expires: int = 3600, region: str = "us-east-1",
                session_token: str | None = None,
                amz_date: str | None = None) -> str:
    """Generate a presigned URL (the SDK generate_presigned_url /
    reference query-string auth role): anyone holding the URL can
    perform ``method`` on bucket/key until it expires.  The signature
    covers method, path, the X-Amz-* query parameters, and the host
    header; the payload is UNSIGNED-PAYLOAD, as presigned requests
    always are."""
    amz_date = amz_date or time.strftime("%Y%m%dT%H%M%SZ",
                                         time.gmtime())
    day = amz_date[:8]
    scope = f"{day}/{region}/s3/aws4_request"
    path = "/" + "/".join(
        urllib.parse.quote(seg, safe="-_.~")
        for seg in f"{bucket}/{key}".split("/"))
    host_hdr = f"{host}:{port}"
    params = [
        ("X-Amz-Algorithm", "AWS4-HMAC-SHA256"),
        ("X-Amz-Credential", f"{access_key}/{scope}"),
        ("X-Amz-Date", amz_date),
        ("X-Amz-Expires", str(int(expires))),
        ("X-Amz-SignedHeaders", "host"),
    ]
    if session_token is not None:
        params.append(("X-Amz-Security-Token", session_token))
    enc = urllib.parse.quote
    query = "&".join(f"{enc(k, safe='-_.~')}={enc(v, safe='-_.~')}"
                     for k, v in sorted(params))
    req = _Request(method, f"{path}?{query}",
                   {"host": host_hdr}, b"")
    sts = sigv4_string_to_sign(req, ["host"], scope, amz_date,
                               payload_hash="UNSIGNED-PAYLOAD")
    sig = hmac.new(_sig_key(secret_key, day, region, "s3"),
                   sts.encode(), hashlib.sha256).hexdigest()
    return (f"http://{host_hdr}{path}?{query}"
            f"&X-Amz-Signature={sig}")


def sigv4_sign(req: _Request, access_key: str, secret_key: str,
               region: str = "us-east-1") -> str:
    """Produce the Authorization header a stock SDK would (the client
    half; the frontend verifies with the same canonicalization)."""
    amz_date = req.header("x-amz-date")
    day = amz_date[:8]
    scope = f"{day}/{region}/s3/aws4_request"
    signed = sorted(h for h in req.headers
                    if h == "host" or h.startswith("x-amz-"))
    sts = sigv4_string_to_sign(req, signed, scope, amz_date)
    sig = hmac.new(_sig_key(secret_key, day, region, "s3"),
                   sts.encode(), hashlib.sha256).hexdigest()
    return (f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}")


class S3Frontend:
    """One listening S3 endpoint over an RGWLite handle."""

    def __init__(self, rgw: RGWLite, users: RGWUsers | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 region: str = "us-east-1",
                 system_users: frozenset[str] = frozenset()):
        self.rgw = rgw
        self.users = users if users is not None else rgw.users
        self.host = host
        self.port = port
        self.region = region
        self.system_users = system_users
        self._server: asyncio.AbstractServer | None = None
        self._reqid = 0
        # bucket -> (fetched_at, cors rules): decoration must not
        # double bucket-meta reads on every Origin-bearing request
        self._cors_cache: dict[str, tuple[float, list]] = {}
        # QoS admission control (the front-door actuator of the
        # defense plane): requests in flight behind the gate + one
        # token bucket per session (access key); conf is read live so
        # the knobs retune without a frontend restart
        self._inflight = 0
        self._buckets: dict[str, TokenBucket] = {}

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._client, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log.dout(1, "s3 frontend on %s:%d", self.host, self.port)
        return self.host, self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        # push workers outliving the rados client would loop against a
        # shut-down connection (warnings + racing teardown writes)
        await self.rgw.stop_push()

    # -- connection loop ---------------------------------------------------
    async def _client(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except _HTTPError as e:
                    status, headers, body = self._error(
                        e.status, e.code, e.msg)
                    stub = _Request("GET", "/", {}, b"")
                    await self._respond(writer, stub, status, headers,
                                        body, keep=False)
                    break
                if req is None:
                    break
                keep = req.header("connection", "keep-alive") != "close"
                if req.stream is not None:
                    # default pessimistic: only a fully drained body
                    # leaves the socket reusable
                    keep_after_stream = keep
                    keep = False
                shed = self._admission(req)
                if shed is not None:
                    # overload sheds at the front door, before any
                    # RADOS work: 503 Slow Down + Retry-After.  A
                    # streamed body was never drained, so the socket
                    # cannot be reused
                    status, headers, body = shed
                    await self._respond(writer, req, status, headers,
                                        body, keep)
                    if not keep:
                        break
                    continue
                self._inflight += 1
                try:
                    with self._class_ctx(req):
                        status, headers, body = await self._route(req)
                except _HTTPError as e:
                    status, headers, body = self._error(e.status, e.code,
                                                        e.msg)
                except RGWError as e:
                    status, headers, body = self._error(
                        _STATUS.get(e.code, 400), e.code, str(e)
                    )
                except (ValueError, ET.ParseError) as e:
                    # malformed numbers/XML/params from the client:
                    # a 400, never a dropped connection
                    status, headers, body = self._error(
                        400, "InvalidArgument", str(e))
                except Exception as e:     # noqa: BLE001 — serve 500
                    log.dout(1, "request failed: %r", e)
                    status, headers, body = self._error(
                        500, "InternalError", type(e).__name__)
                finally:
                    self._inflight -= 1
                if req.stream is not None and \
                        req.stream_consumed >= req.content_length:
                    keep = keep_after_stream
                if req.header("origin"):
                    headers = {**headers,
                               **await self._cors_headers(req)}
                await self._respond(writer, req, status, headers, body,
                                    keep)
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionError, OSError):
                pass

    async def _read_request(self,
                            reader: asyncio.StreamReader
                            ) -> _Request | None:
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except (asyncio.IncompleteReadError, asyncio.LimitOverrunError):
            return None
        lines = head.decode("latin-1").split("\r\n")
        try:
            method, raw_path, _version = lines[0].split(" ", 2)
        except ValueError:
            raise _HTTPError(400, "InvalidRequest", "bad request line")
        headers: dict[str, str] = {}
        for line in lines[1:]:
            if not line:
                continue
            k, _, v = line.partition(":")
            headers[k.strip().lower()] = v.strip()
        try:
            length = int(headers.get("content-length", "0") or "0")
        except ValueError:
            raise _HTTPError(400, "InvalidArgument", "bad content-length")
        if length < 0:
            raise _HTTPError(400, "InvalidArgument", "bad content-length")
        req = _Request(method.upper(), raw_path, headers, b"")
        req.content_length = length
        if self._should_stream(req, length):
            # body stays on the socket; the object handler drains it
            # chunk by chunk into RGWLite (no whole-body buffering)
            req.stream = reader
            return req
        if length > _MAX_BODY:
            # bound only BUFFERED bodies (non-streamable requests);
            # large uploads ride the streaming path or multipart
            raise _HTTPError(400, "EntityTooLarge", str(length))
        req.body = await reader.readexactly(length) if length else b""
        return req

    @staticmethod
    def _should_stream(req: _Request, length: int) -> bool:
        """Plain object PUTs with a declared payload hash stream; the
        hash header is required so SigV4 verifies from headers alone
        and the body sha256 is enforced incrementally."""
        if req.method != "PUT" or length < _STREAM_MIN:
            return False
        if not req.header("x-amz-content-sha256"):
            return False
        parts = req.path.lstrip("/").split("/", 1)
        if len(parts) < 2 or not parts[1]:
            return False                # not an object-level request
        blocked = {"partNumber", "uploadId", "acl", "versioning",
                   "lifecycle", "tagging", "notification", "delete",
                   "retention", "legal-hold", "object-lock"}
        if blocked & set(req.query):
            return False
        if req.header("x-amz-copy-source"):
            return False
        return True

    async def _respond(self, writer: asyncio.StreamWriter, req: _Request,
                       status: int, headers: dict, body,
                       keep: bool) -> None:
        self._reqid += 1
        reason = {200: "OK", 204: "No Content", 206: "Partial Content",
                  403: "Forbidden", 404: "Not Found",
                  503: "Slow Down"}.get(status, "S3")
        out = [f"HTTP/1.1 {status} {reason}"]
        streaming = not isinstance(body, (bytes, bytearray))
        base = {
            "x-amz-request-id": f"{self._reqid:016x}",
            "date": formatdate(usegmt=True),
            "connection": "keep-alive" if keep else "close",
        }
        if not streaming:
            base["content-length"] = str(len(body))
        base.update(headers)    # streaming callers set content-length
        for k, v in base.items():
            out.append(f"{k}: {v}")
        head = "\r\n".join(out).encode("latin-1") + b"\r\n\r\n"
        writer.write(head)
        if req.method != "HEAD":
            if streaming:
                # async-generator body: chunks flow straight from RADOS
                # to the socket, never materializing the whole object
                try:
                    async for chunk in body:
                        writer.write(chunk)
                        await writer.drain()
                except (ConnectionError, asyncio.IncompleteReadError):
                    raise
                except Exception as e:     # noqa: BLE001
                    # backend failure mid-stream: the status line is
                    # gone, so the only honest signal is a truncated
                    # body + closed connection (what beast does too)
                    log.derr("streaming GET aborted: %r", e)
                    await body.aclose()
                    raise ConnectionError("stream aborted") from e
            else:
                writer.write(bytes(body))
        await writer.drain()

    @staticmethod
    def _error(status: int, code: str, msg: str = ""):
        root = ET.Element("Error")
        ET.SubElement(root, "Code").text = code
        ET.SubElement(root, "Message").text = msg
        body = ET.tostring(root, xml_declaration=True,
                           encoding="unicode").encode()
        return status, {"content-type": "application/xml"}, body

    # -- QoS admission control (front-door defense plane) -----------------
    def _qos_conf(self):
        """(max_inflight, session_rate, burst, retry_after) read live
        from conf — 0/0 disables both gates (the default)."""
        try:
            conf = self.rgw.ioctx.rados.conf
            return (int(conf["rgw_max_inflight"]),
                    float(conf["rgw_session_ops_per_s"]),
                    float(conf["rgw_session_burst"]),
                    float(conf["rgw_retry_after_s"]))
        except (AttributeError, KeyError, TypeError, ValueError):
            return 0, 0.0, 8.0, 1.0

    @staticmethod
    def _session_key(req: _Request) -> str:
        """Throttle identity: the access key from the SigV4 header or
        presigned query (cheap string parse, no verification — a shed
        request never reaches auth)."""
        auth = req.header("authorization")
        marker = "Credential="
        i = auth.find(marker)
        if i >= 0:
            cred = auth[i + len(marker):]
            return cred.split("/", 1)[0].split(",", 1)[0]
        cred = req.query.get("X-Amz-Credential", "")
        if cred:
            return cred.split("/", 1)[0]
        return "anonymous"

    def _tenant_class(self, req: _Request) -> str:
        """Tenant class for this request: ``slo_class_map`` access-key
        assignment, defaulting to the LAST ``slo_class_labels`` label
        (bronze) for unmapped keys.  The class rides the rados qclass
        contextvar into per-class OSD latency histograms — the S3-side
        half of the attribution plane (in-process rados clients stamp
        themselves via loadgen's tenant_class)."""
        import re as _re
        try:
            conf = self.rgw.ioctx.rados.conf
            labels = [lbl.strip() for lbl in
                      str(conf["slo_class_labels"] or "").split(",")
                      if lbl.strip()]
            spec = str(conf["slo_class_map"] or "")
        except (AttributeError, KeyError, TypeError):
            return ""
        if not labels:
            return ""
        mapping = {}
        for part in _re.split(r"[,\s]+", spec.strip()):
            if part and "=" in part:
                k, _, v = part.partition("=")
                mapping[k.strip()] = v.strip()
        cls = mapping.get(self._session_key(req), labels[-1])
        return cls if cls in labels else labels[-1]

    def _class_ctx(self, req: _Request):
        cls = self._tenant_class(req)
        if not cls:
            import contextlib
            return contextlib.nullcontext()
        from ceph_tpu.client.rados import op_class
        return op_class(cls)

    def _admission(self, req: _Request):
        """Queue-depth gate + per-session token bucket.  Returns a
        ready 503 Slow Down response tuple when the request sheds,
        None when admitted."""
        max_inflight, rate, burst, retry = self._qos_conf()
        if max_inflight <= 0 and rate <= 0:
            return None
        if max_inflight > 0 and self._inflight >= max_inflight:
            return self._shed(req, "inflight", retry)
        if rate > 0:
            now = asyncio.get_running_loop().time()
            key = self._session_key(req)
            bucket = self._buckets.get(key)
            if bucket is None or bucket.rate != rate:
                bucket = self._buckets[key] = TokenBucket(
                    rate, burst, now)
            if not bucket.take(now):
                return self._shed(req, "session",
                                  max(retry, bucket.retry_after()),
                                  session=key)
        self.rgw.qos_stats["admitted"] += 1
        return None

    def _shed(self, req: _Request, reason: str, retry_after: float,
              session: str = ""):
        self.rgw.qos_stats[f"shed_{reason}"] = \
            self.rgw.qos_stats.get(f"shed_{reason}", 0) + 1
        emit_proc("qos.shed", reason=reason, method=req.method,
                  path=req.path, session=session,
                  inflight=self._inflight)
        log.dout(5, "shed %s %s (%s): 503 Slow Down",
                 req.method, req.path, reason)
        status, headers, body = self._error(
            503, "SlowDown", "please reduce your request rate")
        headers = {**headers,
                   "retry-after": str(max(1, int(round(retry_after))))}
        return status, headers, body

    # -- auth (rgw_auth_s3.cc) --------------------------------------------
    async def _identify(self, req: _Request) -> str:
        auth = req.header("authorization")
        if not auth:
            if req.query.get("X-Amz-Algorithm") \
                    == "AWS4-HMAC-SHA256":
                return await self._identify_presigned(req)
            return ANONYMOUS
        if not auth.startswith("AWS4-HMAC-SHA256 "):
            raise _HTTPError(400, "InvalidArgument", "unsupported auth")
        fields = {}
        for part in auth[len("AWS4-HMAC-SHA256 "):].split(","):
            k, _, v = part.strip().partition("=")
            fields[k] = v
        try:
            cred = fields["Credential"].split("/")
            access_key, day, region = cred[0], cred[1], cred[2]
            signed = fields["SignedHeaders"].split(";")
            their_sig = fields["Signature"]
        except (KeyError, IndexError):
            raise _HTTPError(400, "InvalidArgument", "malformed auth")
        if self.users is None:
            raise _HTTPError(403, "InvalidAccessKeyId", access_key)
        amz_date = req.header("x-amz-date")
        self._check_request_time(amz_date, day)
        uid, secret, session_token = await self._lookup_key(access_key)
        if session_token is not None and not hmac.compare_digest(
                session_token, req.header("x-amz-security-token")):
            # STS credentials are only valid with their session token
            # (reference rgw_sts.cc session validation)
            raise _HTTPError(403, "InvalidToken", access_key)
        scope = f"{day}/{region}/s3/aws4_request"
        sts = sigv4_string_to_sign(req, signed, scope, amz_date)
        want = hmac.new(_sig_key(secret, day, region, "s3"),
                        sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, their_sig):
            raise _HTTPError(403, "SignatureDoesNotMatch", access_key)
        declared = req.header("x-amz-content-sha256")
        if req.stream is None and declared and \
                declared != "UNSIGNED-PAYLOAD" and \
                declared != hashlib.sha256(req.body).hexdigest():
            # a valid signature over a LIED-ABOUT payload hash must
            # not authorize the actual body (replay/tamper guard)
            raise _HTTPError(400, "XAmzContentSHA256Mismatch",
                             "payload hash mismatch")
        return uid

    async def _identify_presigned(self, req: _Request) -> str:
        """Query-string (presigned URL) SigV4 auth — reference
        rgw_auth_s3.cc query-string mode: the signature rides the
        query parameters, the payload is UNSIGNED, and validity is
        bounded by X-Amz-Date + X-Amz-Expires instead of the clock
        skew alone."""
        q = req.query
        try:
            cred = q["X-Amz-Credential"].split("/")
            access_key, day, region = cred[0], cred[1], cred[2]
            amz_date = q["X-Amz-Date"]
            expires = int(q["X-Amz-Expires"])
            signed = q["X-Amz-SignedHeaders"].split(";")
            their_sig = q["X-Amz-Signature"]
        except (KeyError, IndexError, ValueError):
            raise _HTTPError(400, "InvalidArgument",
                             "malformed presigned query")
        if not 1 <= expires <= 604800:
            raise _HTTPError(400, "InvalidArgument",
                             "X-Amz-Expires out of range")
        ts = _parse_scope_date(amz_date, day)
        now = time.time()
        if now > ts + expires:
            raise _HTTPError(403, "AccessDenied",
                             "Request has expired")
        if ts > now + self._SKEW_S:
            raise _HTTPError(403, "RequestTimeTooSkewed", amz_date)
        if self.users is None:
            raise _HTTPError(403, "InvalidAccessKeyId", access_key)
        uid, secret, session_token = await self._lookup_key(access_key)
        if session_token is not None and not hmac.compare_digest(
                session_token, q.get("X-Amz-Security-Token", "")):
            raise _HTTPError(403, "InvalidToken", access_key)
        scope = f"{day}/{region}/s3/aws4_request"
        # the canonical query is everything EXCEPT the signature
        sts = sigv4_string_to_sign(
            req, signed, scope, amz_date,
            payload_hash="UNSIGNED-PAYLOAD",
            raw_query="&".join(
                part for part in req.raw_query.split("&")
                if not part.startswith("X-Amz-Signature=")))
        want = hmac.new(_sig_key(secret, day, region, "s3"),
                        sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, their_sig):
            raise _HTTPError(403, "SignatureDoesNotMatch", access_key)
        return uid

    # Reference rgw_auth_s3.cc rejects requests whose signed timestamp
    # drifts more than RGW_AUTH_GRACE (15 min) from the server clock —
    # without this a captured signed request replays forever.
    _SKEW_S = 15 * 60

    def _check_request_time(self, amz_date: str, cred_day: str) -> None:
        ts = _parse_scope_date(amz_date, cred_day)
        if abs(time.time() - ts) > self._SKEW_S:
            raise _HTTPError(403, "RequestTimeTooSkewed", amz_date)

    async def _lookup_key(self, access_key: str
                          ) -> tuple[str, str, str | None]:
        """(uid, signing secret, required session token or None):
        permanent keys resolve through the user db, STS temp keys
        through the time-bounded credential table."""
        from ceph_tpu.services.rgw import KEYS_OID
        from ceph_tpu.client.rados import RadosError

        try:
            kv = await self.users.ioctx.get_omap(KEYS_OID, [access_key])
        except RadosError as e:
            if e.rc == -2:
                kv = {}
            else:
                raise
        if access_key not in kv:
            sts_rec = await self.users.sts_get(access_key)
            if sts_rec is None:
                raise _HTTPError(403, "InvalidAccessKeyId", access_key)
            rec = await self.users.get(sts_rec["uid"])
            if rec.get("suspended"):
                raise _HTTPError(403, "AccessDenied",
                                 f"{sts_rec['uid']} suspended")
            return (sts_rec["uid"], sts_rec["secret_key"],
                    sts_rec["session_token"])
        uid = kv[access_key].decode()
        rec = await self.users.get(uid)
        if rec.get("suspended"):
            raise _HTTPError(403, "AccessDenied", f"{uid} suspended")
        return uid, rec["secret_key"], None

    # -- CORS (rgw_cors.cc: preflight + response decoration) --------------
    async def _bucket_cors_rules(self, bucket: str) -> list[dict]:
        """The bucket's CORS rules via the system context — CORS
        evaluation is configuration, not an authorized data access
        (preflights are unsigned by design).  A 1s TTL cache keeps
        the decoration hook from doubling bucket-meta reads on every
        Origin-bearing request."""
        from ceph_tpu.client.rados import RadosError

        if not bucket:
            return []
        hit = self._cors_cache.get(bucket)
        now = time.monotonic()
        if hit is not None and now - hit[0] < 1.0:
            return hit[1]
        try:
            meta = await self.rgw._bucket_meta(bucket)
            rules = meta.get("cors") or []
        except (RGWError, RadosError):
            rules = []
        self._cors_cache[bucket] = (now, rules)
        if len(self._cors_cache) > 4096:
            self._cors_cache.clear()
        return rules

    async def _cors_rule(self, req: _Request,
                         method: str) -> tuple[dict | None, dict]:
        """(matched rule, base response headers) for the request's
        bucket + Origin — the one lookup both the preflight and the
        response decoration share."""
        origin = req.header("origin")
        if not origin:
            return None, {}      # no Origin, no CORS evaluation
        bucket = req.path.lstrip("/").split("/", 1)[0]
        rules = await self._bucket_cors_rules(bucket)
        rule = RGWLite.cors_match(rules, origin, method)
        if rule is None:
            return None, {}
        base = {"vary": "Origin"}
        # the credentials grant keys off WHICH pattern matched: only
        # a NON-wildcard pattern may echo the origin with
        # allow-credentials (wildcard + credentials is the exact
        # combination the browser * ban exists to prevent)
        if any(p != "*" and RGWLite._cors_pattern_ok(p, origin)
               for p in rule.get("allowed_origins", ())):
            base["access-control-allow-origin"] = origin
            base["access-control-allow-credentials"] = "true"
        else:
            base["access-control-allow-origin"] = "*"
        return rule, base

    async def _cors_headers(self, req: _Request) -> dict[str, str]:
        if req.method == "OPTIONS":
            return {}     # the preflight handler already decorated
        rule, out = await self._cors_rule(req, req.method)
        if rule is None:
            return {}
        if rule.get("expose_headers"):
            out["access-control-expose-headers"] = \
                ",".join(rule["expose_headers"])
        return out

    async def _preflight(self, req: _Request):
        """OPTIONS preflight (RGWOp_CORS): match Origin + requested
        method against the bucket's rules; never authenticated."""
        origin = req.header("origin")
        want = req.header("access-control-request-method")
        if not origin or not want:
            raise _HTTPError(400, "InvalidArgument",
                             "preflight needs Origin + "
                             "Access-Control-Request-Method")
        rule, headers = await self._cors_rule(req, want)
        if rule is None:
            raise _HTTPError(403, "AccessDenied", "CORSResponse: no "
                             "matching rule")
        headers["access-control-allow-methods"] = \
            ",".join(rule["allowed_methods"])
        want_headers = req.header("access-control-request-headers")
        if want_headers:
            grant = RGWLite.cors_header_grant(
                rule, [h.strip() for h in want_headers.split(",")
                       if h.strip()])
            if grant is None:
                # a disallowed requested header fails the WHOLE
                # preflight (S3 semantics) — a silent subset grant
                # would still be rejected by the browser, opaquely
                raise _HTTPError(403, "AccessDenied",
                                 "CORSResponse: header not allowed")
            headers["access-control-allow-headers"] = ",".join(grant)
        if rule.get("max_age_seconds") is not None:
            headers["access-control-max-age"] = \
                str(rule["max_age_seconds"])
        return 200, headers, b""

    # -- routing (rgw_rest_s3.cc RGWHandler_REST_S3) ----------------------
    async def _route(self, req: _Request):
        if req.method == "OPTIONS":
            return await self._preflight(req)
        uid = await self._identify(req)
        gw = self.rgw.as_user(None if uid in self.system_users
                              else uid)
        parts = req.path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        if uid == ANONYMOUS and bucket and not req.query \
                and req.method in ("GET", "HEAD"):
            web = await self._maybe_website(req, gw, bucket, key)
            if web is not None:
                return web
        if bucket == "admin":
            return await self._admin(req, uid, key)
        if not bucket:
            return await self._service(req, gw)
        if not key:
            return await self._bucket(req, gw, bucket)
        return await self._object(req, gw, bucket, key)

    # -- admin ops API (reference RGWRESTMgr_Admin: /admin/user,
    # /admin/bucket, /admin/usage, /admin/metadata/*) ------------------
    async def _admin(self, req: _Request, uid: str, sub: str):
        """The radosgw admin ops REST surface: JSON in/out, reachable
        only by SYSTEM users (the reference gates on the user's
        system flag)."""
        import json as _json

        if uid not in self.system_users:
            raise _HTTPError(403, "AccessDenied",
                             "admin API requires a system user")

        def jout(status: int, data) -> tuple[int, dict, bytes]:
            body = _json.dumps(data, default=str).encode()
            return status, {"content-type": "application/json"}, body

        q = req.query
        gw = self.rgw.as_user(None)
        if sub == "user":
            tuid = q.get("uid", "")
            if req.method == "GET":
                if not tuid:
                    return jout(200, await self.users.list())
                return jout(200, await self.users.get(tuid))
            if req.method == "PUT":
                rec = await self.users.create(
                    tuid, q.get("display-name", ""),
                    max_size=int(q.get("max-size", 0) or 0),
                    max_objects=int(q.get("max-objects", 0) or 0))
                return jout(201, rec)
            if req.method == "POST":
                if "suspended" in q:
                    await self.users.set_suspended(
                        tuid, q["suspended"] in ("1", "true", "True"))
                if "max-size" in q or "max-objects" in q:
                    await self.users.set_quota(
                        tuid,
                        max_size=int(q.get("max-size", 0) or 0),
                        max_objects=int(q.get("max-objects", 0) or 0))
                return jout(200, await self.users.get(tuid))
            if req.method == "DELETE":
                await self.users.remove(tuid)
                return jout(200, {"removed": tuid})
        elif sub == "bucket":
            tb = q.get("bucket", "")
            if req.method == "GET":
                if not tb:
                    return jout(200, await gw.list_buckets())
                meta = await gw._bucket_meta(tb)
                nbytes, nobj = await gw._bucket_usage(tb)
                return jout(200, {
                    "bucket": tb, "owner": meta.get("owner", ""),
                    "num_objects": nobj, "size_bytes": nbytes,
                    "index_shards": int(meta.get("index_shards", 1)),
                    "versioning": meta.get("versioning", ""),
                })
            if req.method == "DELETE":
                await gw.delete_bucket(tb)
                return jout(200, {"removed": tb})
        elif sub == "usage":
            if req.method == "GET":
                out = {}
                for b in await gw.list_buckets():
                    try:
                        meta = await gw._bucket_meta(b)
                        nbytes, nobj = await gw._bucket_usage(b)
                    except RGWError:
                        continue
                    u = out.setdefault(meta.get("owner", ""), {
                        "buckets": 0, "objects": 0, "bytes": 0})
                    u["buckets"] += 1
                    u["objects"] += nobj
                    u["bytes"] += nbytes
                return jout(200, out)
        elif sub.startswith("metadata"):
            # rgw_rest_metadata.h: enumerate metadata entries by type
            mtype = sub.partition("/")[2] or q.get("type", "")
            if req.method == "GET":
                if mtype == "user":
                    return jout(200, await self.users.list())
                if mtype == "bucket":
                    return jout(200, await gw.list_buckets())
                return jout(200, ["user", "bucket"])
        raise _HTTPError(405, "MethodNotAllowed",
                         f"{req.method} /admin/{sub}")

    async def _maybe_website(self, req: _Request, gw: RGWLite,
                             bucket: str, key: str):
        """Static-website semantics for anonymous browsers on a
        website-configured bucket (rgw_website.cc): directory paths
        resolve to the index document, missing keys to the error
        document (served WITH a 404).  None = not a website bucket,
        fall through to plain S3 handling."""
        try:
            cfg = (await gw._bucket_meta(bucket)).get("website")
        except RGWError:
            return None
        if not cfg:
            return None
        want = key
        if not want or want.endswith("/"):
            want = want + cfg["index"]
        try:
            got = await gw.get_object(bucket, want)
            return 200, _obj_headers(got), (
                b"" if req.method == "HEAD" else got["data"])
        except RGWError as e:
            if e.code not in ("NoSuchKey", "AccessDenied"):
                raise
        err_key = cfg.get("error")
        if err_key:
            try:
                got = await gw.get_object(bucket, err_key)
                return 404, _obj_headers(got), (
                    b"" if req.method == "HEAD" else got["data"])
            except RGWError:
                pass
        raise _HTTPError(404, "NoSuchKey", want)

    async def _service(self, req: _Request, gw: RGWLite):
        if req.method != "GET":
            raise _HTTPError(405, "MethodNotAllowed", req.method)
        root = ET.Element("ListAllMyBucketsResult", xmlns=XMLNS)
        owner = ET.SubElement(root, "Owner")
        ET.SubElement(owner, "ID").text = gw.user or "admin"
        buckets = ET.SubElement(root, "Buckets")
        for name in await gw.list_buckets():
            try:
                meta = await gw._check_bucket(name, "READ")
            except RGWError:
                continue                 # not ours / not readable
            b = ET.SubElement(buckets, "Bucket")
            ET.SubElement(b, "Name").text = name
            ET.SubElement(b, "CreationDate").text = _iso(
                meta.get("created", 0.0))
        return self._xml(root)

    async def _bucket(self, req: _Request, gw: RGWLite, bucket: str):
        q = req.query
        if req.method == "PUT":
            if "versioning" in q:
                cfg = ET.fromstring(req.body.decode() or
                                    "<VersioningConfiguration/>")
                status = cfg.findtext(_ns("Status"), default="",
                                      namespaces=None) or \
                    cfg.findtext("Status", default="")
                await gw.put_bucket_versioning(bucket,
                                               status == "Enabled")
                return 200, {}, b""
            if "lifecycle" in q:
                rules = _parse_lifecycle(req.body)
                await gw.put_lifecycle(bucket, rules)
                return 200, {}, b""
            if "policy" in q:
                # PutBucketPolicy: the body is the JSON document
                # itself; bytes go straight to validate (a non-UTF-8
                # body is MalformedPolicy, not a decode crash)
                await gw.put_bucket_policy(bucket, req.body)
                return 204, {}, b""
            if "acl" in q:
                canned = req.header("x-amz-acl", "private")
                await gw.put_bucket_acl(bucket, canned)
                return 200, {}, b""
            if "cors" in q:
                await gw.put_bucket_cors(bucket,
                                         _parse_cors(req.body))
                self._cors_cache.pop(bucket, None)
                return 200, {}, b""
            if "notification" in q:
                # S3 PutBucketNotificationConfiguration REPLACES the
                # whole document (an empty one disables notifications)
                cfg = ET.fromstring(req.body.decode() or
                                    "<NotificationConfiguration/>")
                configs = []
                for tc in (list(cfg.findall(_ns("TopicConfiguration")))
                           or list(cfg.findall("TopicConfiguration"))):
                    topic = (tc.findtext(_ns("Topic"))
                             or tc.findtext("Topic") or "")
                    topic = topic.rsplit(":", 1)[-1]     # arn -> name
                    events = [e.text for e in
                              (tc.findall(_ns("Event"))
                               or tc.findall("Event")) if e.text]
                    if topic:
                        configs.append({"topic": topic,
                                        "events": events})
                await gw.set_bucket_notifications(bucket, configs)
                return 200, {}, b""
            if "object-lock" in q:
                mode, days, years = _parse_lock_config(req.body)
                await gw.put_object_lock_config(bucket, mode,
                                                days=days,
                                                years=years)
                return 200, {}, b""
            if "website" in q:
                doc = ET.fromstring(req.body.decode())
                idx = (doc.findtext(f"{_ns('IndexDocument')}"
                                    f"/{_ns('Suffix')}")
                       or doc.findtext("IndexDocument/Suffix") or "")
                err = (doc.findtext(f"{_ns('ErrorDocument')}"
                                    f"/{_ns('Key')}")
                       or doc.findtext("ErrorDocument/Key") or "")
                await gw.put_bucket_website(bucket, idx, err)
                return 200, {}, b""
            await gw.create_bucket(bucket, object_lock=req.header(
                "x-amz-bucket-object-lock-enabled",
                "").lower() == "true")
            return 200, {"location": f"/{bucket}"}, b""
        if req.method == "DELETE":
            if "cors" in q:
                await gw.delete_bucket_cors(bucket)
                self._cors_cache.pop(bucket, None)
                return 204, {}, b""
            if "lifecycle" in q:
                await gw.delete_lifecycle(bucket)
                return 204, {}, b""
            if "policy" in q:
                await gw.delete_bucket_policy(bucket)
                return 204, {}, b""
            if "website" in q:
                await gw.delete_bucket_website(bucket)
                return 204, {}, b""
            await gw.delete_bucket(bucket)
            return 204, {}, b""
        if req.method == "HEAD":
            # S3 HeadBucket requires s3:ListBucket
            await gw._check_bucket(bucket, "READ",
                                   action="s3:ListBucket")
            return 200, {}, b""
        if req.method == "POST" and "delete" in q:
            return await self._bulk_delete(req, gw, bucket)
        if req.method != "GET":
            raise _HTTPError(405, "MethodNotAllowed", req.method)
        if "cors" in q:
            rules = await gw.get_bucket_cors(bucket)
            root = ET.Element("CORSConfiguration", xmlns=XMLNS)
            for rule in rules:
                r = ET.SubElement(root, "CORSRule")
                for o in rule.get("allowed_origins", ()):
                    ET.SubElement(r, "AllowedOrigin").text = o
                for m in rule.get("allowed_methods", ()):
                    ET.SubElement(r, "AllowedMethod").text = m
                for h in rule.get("allowed_headers", ()):
                    ET.SubElement(r, "AllowedHeader").text = h
                for h in rule.get("expose_headers", ()):
                    ET.SubElement(r, "ExposeHeader").text = h
                if rule.get("max_age_seconds") is not None:
                    ET.SubElement(r, "MaxAgeSeconds").text = \
                        str(rule["max_age_seconds"])
            return self._xml(root)
        if "versioning" in q:
            state = await gw.get_bucket_versioning(bucket)
            root = ET.Element("VersioningConfiguration", xmlns=XMLNS)
            if state:
                ET.SubElement(root, "Status").text = \
                    "Enabled" if state == "enabled" else "Suspended"
            return self._xml(root)
        if "versions" in q:
            return await self._list_versions(req, gw, bucket)
        if "uploads" in q:
            root = ET.Element("ListMultipartUploadsResult", xmlns=XMLNS)
            ET.SubElement(root, "Bucket").text = bucket
            for up in await gw.list_multipart_uploads(bucket):
                u = ET.SubElement(root, "Upload")
                ET.SubElement(u, "Key").text = up["key"]
                ET.SubElement(u, "UploadId").text = up["upload_id"]
            return self._xml(root)
        if "website" in q:
            cfg = await gw.get_bucket_website(bucket)
            root = ET.Element("WebsiteConfiguration", xmlns=XMLNS)
            idx = ET.SubElement(root, "IndexDocument")
            ET.SubElement(idx, "Suffix").text = cfg["index"]
            if cfg.get("error"):
                err = ET.SubElement(root, "ErrorDocument")
                ET.SubElement(err, "Key").text = cfg["error"]
            return self._xml(root)
        if "object-lock" in q:
            cfg = await gw.get_object_lock_config(bucket)
            root = ET.Element("ObjectLockConfiguration", xmlns=XMLNS)
            ET.SubElement(root, "ObjectLockEnabled").text = "Enabled"
            if cfg.get("mode"):
                rule = ET.SubElement(root, "Rule")
                dr = ET.SubElement(rule, "DefaultRetention")
                ET.SubElement(dr, "Mode").text = cfg["mode"]
                if cfg.get("days"):
                    ET.SubElement(dr, "Days").text = str(cfg["days"])
                if cfg.get("years"):
                    ET.SubElement(dr, "Years").text = \
                        str(cfg["years"])
            return self._xml(root)
        if "lifecycle" in q:
            rules = await gw.get_lifecycle(bucket)
            if not rules:
                raise _HTTPError(404, "NoSuchLifecycleConfiguration",
                                 bucket)
            root = ET.Element("LifecycleConfiguration", xmlns=XMLNS)
            for rule in rules:
                r = ET.SubElement(root, "Rule")
                ET.SubElement(r, "ID").text = rule.get("id", "")
                ET.SubElement(r, "Prefix").text = rule.get("prefix", "")
                ET.SubElement(r, "Status").text = \
                    rule.get("status", "Enabled")
                for kind, outer, inner in (
                        ("expiration", "Expiration", "Days"),
                        ("noncurrent",
                         "NoncurrentVersionExpiration",
                         "NoncurrentDays"),
                        ("abort_mpu",
                         "AbortIncompleteMultipartUpload",
                         "DaysAfterInitiation")):
                    if f"{kind}_days" in rule:
                        days = int(rule[f"{kind}_days"])
                    elif f"{kind}_seconds" in rule:
                        # S3 XML has no seconds granularity: round a
                        # store-API seconds rule UP to whole days so
                        # the emitted document stays valid and
                        # re-PUTtable (never sharper than the rule)
                        days = max(1, math.ceil(
                            float(rule[f"{kind}_seconds"]) / 86400))
                    else:
                        continue
                    e = ET.SubElement(r, outer)
                    ET.SubElement(e, inner).text = str(days)
                for kind, outer, inner in (
                        ("transition", "Transition", "Days"),
                        ("noncurrent_transition",
                         "NoncurrentVersionTransition",
                         "NoncurrentDays")):
                    cls = rule.get(f"{kind}_class")
                    if not cls:
                        continue
                    if f"{kind}_days" in rule:
                        days = int(rule[f"{kind}_days"])
                    elif f"{kind}_seconds" in rule:
                        days = max(1, math.ceil(
                            float(rule[f"{kind}_seconds"]) / 86400))
                    else:
                        continue
                    e = ET.SubElement(r, outer)
                    ET.SubElement(e, inner).text = str(days)
                    ET.SubElement(e, "StorageClass").text = cls
                if rule.get("tags"):
                    flt = ET.SubElement(r, "Filter")
                    holder = (ET.SubElement(flt, "And")
                              if len(rule["tags"]) > 1 else flt)
                    for k, v in sorted(rule["tags"].items()):
                        t = ET.SubElement(holder, "Tag")
                        ET.SubElement(t, "Key").text = k
                        ET.SubElement(t, "Value").text = v
            return self._xml(root)
        if "notification" in q:
            cfgs = await gw.get_bucket_notification(bucket)
            root = ET.Element("NotificationConfiguration", xmlns=XMLNS)
            for c in cfgs:
                tc = ET.SubElement(root, "TopicConfiguration")
                ET.SubElement(tc, "Topic").text = \
                    f"arn:aws:sns:::{c['topic']}"
                for e in c.get("events", ()):
                    ET.SubElement(tc, "Event").text = e
            return self._xml(root)
        if "policy" in q:
            import json as _json

            policy = await gw.get_bucket_policy(bucket)
            return 200, {"content-type": "application/json"}, \
                _json.dumps(policy).encode()
        if "acl" in q:
            acl = await gw.get_bucket_acl(bucket)
            root = ET.Element("AccessControlPolicy", xmlns=XMLNS)
            ET.SubElement(ET.SubElement(root, "Owner"), "ID").text = \
                acl.get("owner", "")
            ET.SubElement(root, "CannedACL").text = \
                acl.get("canned", "private")
            return self._xml(root)
        return await self._list_objects(req, gw, bucket)

    async def _list_objects(self, req: _Request, gw: RGWLite,
                            bucket: str):
        q = req.query
        v2 = q.get("list-type") == "2"
        marker = q.get("continuation-token" if v2 else "marker", "") or \
            q.get("start-after", "")
        max_keys = int(q.get("max-keys", "1000"))
        listing = await gw.list_objects(
            bucket, prefix=q.get("prefix", ""), marker=marker,
            max_keys=max_keys, delimiter=q.get("delimiter", ""),
        )
        root = ET.Element("ListBucketResult", xmlns=XMLNS)
        ET.SubElement(root, "Name").text = bucket
        ET.SubElement(root, "Prefix").text = q.get("prefix", "")
        if q.get("delimiter"):
            ET.SubElement(root, "Delimiter").text = q["delimiter"]
        for cp in listing.get("common_prefixes", ()):
            e = ET.SubElement(root, "CommonPrefixes")
            ET.SubElement(e, "Prefix").text = cp
        ET.SubElement(root, "IsTruncated").text = \
            "true" if listing["is_truncated"] else "false"
        ET.SubElement(root, "KeyCount" if v2 else "MaxKeys").text = \
            str(len(listing["contents"])
                + len(listing.get("common_prefixes", ()))
                if v2 else max_keys)
        if listing["is_truncated"]:
            tag = "NextContinuationToken" if v2 else "NextMarker"
            ET.SubElement(root, tag).text = listing["next_marker"]
        for c in listing["contents"]:
            e = ET.SubElement(root, "Contents")
            ET.SubElement(e, "Key").text = c["key"]
            ET.SubElement(e, "Size").text = str(c["size"])
            ET.SubElement(e, "ETag").text = f'"{c["etag"]}"'
            ET.SubElement(e, "LastModified").text = _iso(c["mtime"])
            ET.SubElement(e, "StorageClass").text = \
                c.get("storage_class", "STANDARD")
        return self._xml(root)

    async def _list_versions(self, req: _Request, gw: RGWLite,
                             bucket: str):
        versions = await gw.list_object_versions(
            bucket, prefix=req.query.get("prefix", ""))
        root = ET.Element("ListVersionsResult", xmlns=XMLNS)
        ET.SubElement(root, "Name").text = bucket
        for v in versions:
            tag = "DeleteMarker" if v["delete_marker"] else "Version"
            e = ET.SubElement(root, tag)
            ET.SubElement(e, "Key").text = v["key"]
            ET.SubElement(e, "VersionId").text = v["version_id"]
            ET.SubElement(e, "IsLatest").text = \
                "true" if v["is_latest"] else "false"
            ET.SubElement(e, "LastModified").text = _iso(v["mtime"])
            if not v["delete_marker"]:
                ET.SubElement(e, "Size").text = str(v["size"])
                ET.SubElement(e, "ETag").text = f'"{v["etag"]}"'
                ET.SubElement(e, "StorageClass").text = \
                    v.get("storage_class", "STANDARD")
        return self._xml(root)

    async def _bulk_delete(self, req: _Request, gw: RGWLite,
                           bucket: str):
        doc = ET.fromstring(req.body.decode())
        root = ET.Element("DeleteResult", xmlns=XMLNS)
        for obj in doc.iter():
            if not obj.tag.endswith("Object"):
                continue
            key = obj.findtext(_ns("Key")) or obj.findtext("Key") or ""
            try:
                await gw.delete_object(bucket, key)
                d = ET.SubElement(root, "Deleted")
                ET.SubElement(d, "Key").text = key
            except RGWError as e:
                er = ET.SubElement(root, "Error")
                ET.SubElement(er, "Key").text = key
                ET.SubElement(er, "Code").text = e.code
        return self._xml(root)

    async def _object(self, req: _Request, gw: RGWLite, bucket: str,
                      key: str):
        q = req.query
        if req.method == "POST":
            if "uploads" in q:
                kms_alg, kms_key = _sse_kms_headers(req)
                upload_id = await gw.initiate_multipart(
                    bucket, key,
                    content_type=req.header("content-type",
                                            "binary/octet-stream"),
                    metadata=_meta_headers(req),
                    lock=_lock_headers(req),
                    sse=kms_alg, kms_key_id=kms_key,
                    storage_class=req.header("x-amz-storage-class"),
                )
                root = ET.Element("InitiateMultipartUploadResult",
                                  xmlns=XMLNS)
                ET.SubElement(root, "Bucket").text = bucket
                ET.SubElement(root, "Key").text = key
                ET.SubElement(root, "UploadId").text = upload_id
                return self._xml(root)
            if "uploadId" in q:
                parts = _parse_complete(req.body)
                done = await gw.complete_multipart(bucket, key,
                                                   q["uploadId"], parts)
                root = ET.Element("CompleteMultipartUploadResult",
                                  xmlns=XMLNS)
                ET.SubElement(root, "Key").text = key
                ET.SubElement(root, "ETag").text = f'"{done["etag"]}"'
                hdrs = {}
                if done.get("version_id"):
                    hdrs["x-amz-version-id"] = done["version_id"]
                status, xh, body = self._xml(root)
                xh.update(hdrs)
                return status, xh, body
            raise _HTTPError(400, "InvalidArgument", "bad POST")
        if req.method == "PUT":
            if "tagging" in q:
                await gw.put_object_tagging(
                    bucket, key, _parse_tagging(req.body),
                    version_id=q.get("versionId"))
                return 200, {}, b""
            if "retention" in q:
                mode, until = _parse_retention(req.body)
                await gw.put_object_retention(
                    bucket, key, mode, until,
                    version_id=q.get("versionId"),
                    bypass_governance=req.header(
                        "x-amz-bypass-governance-retention",
                        "").lower() == "true")
                return 200, {}, b""
            if "legal-hold" in q:
                status = _parse_legal_hold(req.body)
                await gw.put_object_legal_hold(
                    bucket, key, status,
                    version_id=q.get("versionId"))
                return 200, {}, b""
            if "partNumber" in q and "uploadId" in q:
                src = req.header("x-amz-copy-source")
                if src:
                    # UploadPartCopy: source object (+ optional
                    # x-amz-copy-source-range, inclusive bounds)
                    sb, _, sk = src.lstrip("/").partition("/")
                    rng = None
                    rh = req.header("x-amz-copy-source-range")
                    if rh:
                        if not rh.startswith("bytes="):
                            # a malformed range must not silently
                            # become a whole-object copy
                            raise _HTTPError(400, "InvalidArgument",
                                             f"bad range {rh!r}")
                        a, _, b = rh[6:].partition("-")
                        try:
                            rng = (int(a), int(b))
                        except ValueError:
                            raise _HTTPError(400, "InvalidRange", rh)
                    part = await gw.upload_part_copy(
                        bucket, key, q["uploadId"],
                        int(q["partNumber"]), sb,
                        urllib.parse.unquote(sk), src_range=rng,
                        sse_key=_sse_key_headers(req),
                        src_sse_key=_copy_source_sse_key(req))
                    root = ET.Element("CopyPartResult", xmlns=XMLNS)
                    ET.SubElement(root, "ETag").text = \
                        f'"{part["etag"]}"'
                    return self._xml(root)
                part = await gw.upload_part(
                    bucket, key, q["uploadId"], int(q["partNumber"]),
                    req.body, sse_key=_sse_key_headers(req),
                )
                return 200, {"etag": f'"{part["etag"]}"'}, b""
            src = req.header("x-amz-copy-source")
            if src:
                sb, _, sk = src.lstrip("/").partition("/")
                kms_alg, kms_key = _sse_kms_headers(req)
                out = await gw.copy_object(
                    sb, urllib.parse.unquote(sk), bucket, key,
                    src_sse_key=_copy_source_sse_key(req),
                    sse_key=_sse_key_headers(req),
                    sse=kms_alg, kms_key_id=kms_key,
                    storage_class=req.header("x-amz-storage-class"))
                root = ET.Element("CopyObjectResult", xmlns=XMLNS)
                ET.SubElement(root, "ETag").text = f'"{out["etag"]}"'
                return self._xml(root)
            sse_key = _sse_key_headers(req)
            kms_alg, kms_key = _sse_kms_headers(req)
            if kms_alg is not None and sse_key is not None:
                raise _HTTPError(400, "InvalidArgument",
                                 "SSE-C and x-amz-server-side-"
                                 "encryption are mutually exclusive")
            htags = _header_tags(req)
            if htags:
                # validate AND authorize before any body lands: S3
                # requires s3:PutObjectTagging to set tags on PUT,
                # and a tag error must not surface post-creation
                RGWLite.validate_tags(htags)
                meta_b = await gw._check_bucket(
                    bucket, "WRITE", action="s3:PutObjectTagging",
                    key=key)
            if req.stream is not None:
                out = await self._streaming_put(req, gw, bucket, key,
                                                sse_key, kms_alg,
                                                kms_key)
                if htags:
                    # attach to OUR upload only (etag-guarded: a
                    # racing overwrite must not inherit them); a
                    # racing delete means there is nothing to tag —
                    # the PUT itself still succeeded
                    try:
                        await gw._tag_update(bucket, meta_b, key,
                                             htags,
                                             expect_etag=out["etag"])
                    except RGWError as e:
                        if e.code != "NoSuchKey":
                            raise
            else:
                out = await gw.put_object(
                    bucket, key, req.body,
                    content_type=req.header("content-type",
                                            "binary/octet-stream"),
                    metadata=_meta_headers(req),
                    if_none_match=req.header("if-none-match") == "*",
                    sse_key=sse_key,
                    lock=_lock_headers(req),
                    tags=htags,
                    sse=kms_alg, kms_key_id=kms_key,
                    storage_class=req.header("x-amz-storage-class"),
                )
            hdrs = {"etag": f'"{out["etag"]}"'}
            if out.get("version_id"):
                hdrs["x-amz-version-id"] = out["version_id"]
            if sse_key is not None:
                hdrs["x-amz-server-side-encryption-customer-algorithm"] \
                    = "AES256"
            if kms_alg is not None:
                hdrs["x-amz-server-side-encryption"] = kms_alg
                if kms_alg == "aws:kms":
                    hdrs["x-amz-server-side-encryption-aws-kms-key-id"] \
                        = kms_key or RGWLite.DEFAULT_KMS_KEY
            return 200, hdrs, b""
        if req.method == "DELETE":
            if "tagging" in q:
                await gw.delete_object_tagging(
                    bucket, key, version_id=q.get("versionId"))
                return 204, {}, b""
            if "uploadId" in q:
                await gw.abort_multipart(bucket, key, q["uploadId"])
                return 204, {}, b""
            if "versionId" in q:
                await gw.delete_object_version(
                    bucket, key, q["versionId"],
                    bypass_governance=req.header(
                        "x-amz-bypass-governance-retention",
                        "").lower() == "true")
                return 204, {}, b""
            await gw.delete_object(bucket, key)
            return 204, {}, b""
        if req.method in ("GET", "HEAD"):
            if "retention" in q and req.method == "GET":
                ret = await gw.get_object_retention(
                    bucket, key, version_id=q.get("versionId"))
                root = ET.Element("Retention", xmlns=XMLNS)
                ET.SubElement(root, "Mode").text = ret["mode"]
                ET.SubElement(root, "RetainUntilDate").text = \
                    time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                  time.gmtime(ret["until"]))
                return self._xml(root)
            if "legal-hold" in q and req.method == "GET":
                st = await gw.get_object_legal_hold(
                    bucket, key, version_id=q.get("versionId"))
                root = ET.Element("LegalHold", xmlns=XMLNS)
                ET.SubElement(root, "Status").text = st
                return self._xml(root)
            if "tagging" in q and req.method == "GET":
                tags = await gw.get_object_tagging(
                    bucket, key, version_id=q.get("versionId"))
                root = ET.Element("Tagging", xmlns=XMLNS)
                ts = ET.SubElement(root, "TagSet")
                for k, v in sorted(tags.items()):
                    t = ET.SubElement(ts, "Tag")
                    ET.SubElement(t, "Key").text = k
                    ET.SubElement(t, "Value").text = v
                return self._xml(root)
            if "versionId" in q:
                sse_key = _sse_key_headers(req)
                if req.method == "HEAD":
                    entry = await gw.head_object_version(
                        bucket, key, q["versionId"])
                    sse_check(entry, sse_key)
                    hdrs = _obj_headers({**entry, "data": b""})
                    hdrs["x-amz-version-id"] = q["versionId"]
                    return 200, hdrs, b""
                got = await gw.get_object_version(
                    bucket, key, q["versionId"], sse_key=sse_key)
                hdrs = _obj_headers(got)
                hdrs["x-amz-version-id"] = q["versionId"]
                return 200, hdrs, got["data"]
            sse_key = _sse_key_headers(req)
            if req.method == "HEAD":
                entry = await gw.head_object(bucket, key)
                sse_check(entry, sse_key)
                return 200, _obj_headers({**entry, "data": b""}), b""
            entry = await gw.head_object(bucket, key)
            rng = _parse_range(req.header("range"))
            if rng is not None and rng[0] == "suffix":
                size = int(entry["size"])
                rng = (max(0, size - int(rng[1])), size - 1)
            if int(entry["size"]) >= _STREAM_MIN:
                # large bodies stream straight from RADOS to the socket
                entry, gen = await gw.stream_object(
                    bucket, key, range_=rng, sse_key=sse_key,
                    chunk=_STREAM_CHUNK, entry=entry)
                hdrs = _obj_headers({**entry, "data": b""})
                if entry.get("version_id"):
                    hdrs["x-amz-version-id"] = entry["version_id"]
                size = int(entry["size"])
                if rng is not None:
                    end = min(rng[1], size - 1)
                    length = max(0, end - rng[0] + 1)
                    hdrs["content-range"] = \
                        f"bytes {rng[0]}-{end}/{size}"
                    hdrs["content-length"] = str(length)
                    return 206, hdrs, gen
                hdrs["content-length"] = str(size)
                return 200, hdrs, gen
            got = await gw.get_object(bucket, key, range_=rng,
                                      sse_key=sse_key)
            hdrs = _obj_headers(got)
            if got.get("version_id"):
                hdrs["x-amz-version-id"] = got["version_id"]
            if rng is not None:
                end = min(rng[1], got["size"] - 1)
                hdrs["content-range"] = \
                    f"bytes {rng[0]}-{end}/{got['size']}"
                hdrs["content-length"] = str(len(got["data"]))
                return 206, hdrs, got["data"]
            return 200, hdrs, got["data"]
        raise _HTTPError(405, "MethodNotAllowed", req.method)

    async def _streaming_put(self, req: _Request, gw: RGWLite,
                             bucket: str, key: str,
                             sse_key: bytes | None,
                             kms_alg: str | None = None,
                             kms_key: str | None = None) -> dict:
        """Drain the socket body straight into an RGWLite streaming
        session, hashing as it goes; the declared x-amz-content-sha256
        is enforced at the end (a signed-over hash that lied about the
        body must not publish the object — same guard as the buffered
        path, applied post-stream like S3 does)."""
        sp = await gw.begin_put(
            bucket, key, req.content_length,
            content_type=req.header("content-type",
                                    "binary/octet-stream"),
            metadata=_meta_headers(req),
            if_none_match=req.header("if-none-match") == "*",
            lock=_lock_headers(req),
            storage_class=req.header("x-amz-storage-class"),
        )
        if sse_key is not None:
            sp.set_sse_key(sse_key)
        elif kms_alg is not None:
            dk, rec = await gw._kms_begin(kms_alg, kms_key)
            sp.set_sse_kms(dk, rec)
        declared = req.header("x-amz-content-sha256")
        sha = (hashlib.sha256()
               if declared and declared != "UNSIGNED-PAYLOAD" else None)
        try:
            remaining = req.content_length
            while remaining:
                chunk = await req.stream.readexactly(
                    min(_STREAM_CHUNK, remaining))
                req.stream_consumed += len(chunk)
                remaining -= len(chunk)
                if sha is not None:
                    sha.update(chunk)
                await sp.write(chunk)
        except (Exception, asyncio.CancelledError):
            await sp.abort()
            raise
        if sha is not None and sha.hexdigest() != declared:
            await sp.abort()
            raise _HTTPError(400, "XAmzContentSHA256Mismatch",
                             "payload hash mismatch")
        return await sp.complete()

    @staticmethod
    def _xml(root: ET.Element):
        body = ET.tostring(root, xml_declaration=True,
                           encoding="unicode").encode()
        return 200, {"content-type": "application/xml"}, body


# -- helpers ---------------------------------------------------------------
def _ns(tag: str) -> str:
    return f"{{{XMLNS}}}{tag}"


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts))


def _meta_headers(req: _Request) -> dict[str, str]:
    return {k[len("x-amz-meta-"):]: v for k, v in req.headers.items()
            if k.startswith("x-amz-meta-")}


_SSE_PREFIX = "x-amz-server-side-encryption-customer-"


def _sse_kms_headers(req: _Request) -> tuple[str | None, str | None]:
    """Server-managed encryption headers (rgw_crypt.cc SSE-KMS /
    SSE-S3): x-amz-server-side-encryption ∈ {aws:kms, AES256} plus the
    optional x-amz-server-side-encryption-aws-kms-key-id."""
    alg = req.header("x-amz-server-side-encryption")
    if not alg:
        return None, None
    if alg not in ("aws:kms", "AES256"):
        raise _HTTPError(400, "InvalidArgument",
                         f"unsupported server-side encryption {alg!r}")
    key_id = req.header(
        "x-amz-server-side-encryption-aws-kms-key-id") or None
    if key_id and alg != "aws:kms":
        raise _HTTPError(400, "InvalidArgument",
                         "a KMS key id requires aws:kms")
    return alg, key_id


def _copy_source_sse_key(req: _Request) -> bytes | None:
    """The copy-source SSE-C key triple (x-amz-copy-source-server-
    side-encryption-customer-*): identical validation to the
    destination's, by construction."""
    return _sse_key_headers(
        req, "x-amz-copy-source-server-side-encryption-customer-")


def _sse_key_headers(req: _Request,
                     prefix: str | None = None) -> bytes | None:
    """Parse an S3 SSE-C header triple (rgw_crypt.cc
    rgw_s3_prepare_encrypt): algorithm must be AES256, the key is
    base64, and the md5 header (when sent) must match the key.
    ``prefix``: the copy-source variant's header namespace."""
    import base64

    pfx = prefix or _SSE_PREFIX
    alg = req.header(pfx + "algorithm")
    if not alg:
        return None
    if alg != "AES256":
        raise _HTTPError(400, "InvalidArgument",
                         f"unsupported SSE-C algorithm {alg!r}")
    try:
        key = base64.b64decode(req.header(pfx + "key"),
                               validate=True)
    except Exception:
        raise _HTTPError(400, "InvalidArgument", "bad SSE-C key")
    if len(key) != 32:
        raise _HTTPError(400, "InvalidArgument",
                         "SSE-C key must be 256 bits")
    md5h = req.header(pfx + "key-md5")
    if md5h and base64.b64encode(
            hashlib.md5(key).digest()).decode() != md5h:
        raise _HTTPError(400, "InvalidArgument", "SSE-C key md5 mismatch")
    return key


def _obj_headers(got: dict) -> dict[str, str]:
    hdrs = {
        "content-type": got.get("content_type", "binary/octet-stream"),
        "etag": f'"{got.get("etag", "")}"',
        "last-modified": formatdate(got.get("mtime", 0.0), usegmt=True),
        "content-length": str(len(got.get("data", b""))
                              or got.get("size", 0)),
    }
    for k, v in (got.get("meta") or {}).items():
        hdrs[f"x-amz-meta-{k}"] = str(v)
    if got.get("storage_class"):
        # only non-STANDARD classes are stored; S3 likewise omits the
        # header for STANDARD objects
        hdrs["x-amz-storage-class"] = got["storage_class"]
    ret = got.get("retention")
    if ret:
        hdrs["x-amz-object-lock-mode"] = ret["mode"]
        hdrs["x-amz-object-lock-retain-until-date"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime(float(ret["until"])))
    if got.get("legal_hold"):
        hdrs["x-amz-object-lock-legal-hold"] = "ON"
    sse = got.get("sse")
    if sse and sse.get("wrapped") is not None:
        # KMS-managed (SSE-KMS / SSE-S3): server-side headers, never
        # the customer-key ones
        hdrs["x-amz-server-side-encryption"] = sse.get("alg", "aws:kms")
        if sse.get("alg") == "aws:kms":
            hdrs["x-amz-server-side-encryption-aws-kms-key-id"] = \
                sse.get("key_id", "")
    elif sse:
        import base64

        hdrs[_SSE_PREFIX + "algorithm"] = sse.get("alg", "AES256")
        # the wire form of the header is base64(md5), matching what the
        # client sent; the index stores the hex digest
        try:
            hdrs[_SSE_PREFIX + "key-md5"] = base64.b64encode(
                bytes.fromhex(sse.get("key_md5", ""))).decode()
        except ValueError:
            pass
    return hdrs


def _parse_range(value: str) -> tuple[int, int] | tuple[str, int] | None:
    """'bytes=a-b' -> (a, b); 'bytes=a-' -> (a, huge); 'bytes=-n' ->
    ("suffix", n).  Anything malformed (multi-range, garbage) returns
    None: RFC 7233 allows ignoring Range and serving the full body."""
    if not value.startswith("bytes="):
        return None
    start_s, _, end_s = value[len("bytes="):].partition("-")
    try:
        if not start_s:
            n = int(end_s)
            return ("suffix", n) if n > 0 else None
        return int(start_s), int(end_s) if end_s else (1 << 62)
    except ValueError:
        return None


def _parse_complete(body: bytes) -> list[tuple[int, str]]:
    doc = ET.fromstring(body.decode())
    parts: list[tuple[int, str]] = []
    for el in doc.iter():
        if not el.tag.endswith("Part"):
            continue
        num = el.findtext(_ns("PartNumber")) or \
            el.findtext("PartNumber") or "0"
        etag = (el.findtext(_ns("ETag")) or el.findtext("ETag")
                or "").strip('"')
        parts.append((int(num), etag))
    return parts


def _parse_cors(body: bytes) -> list[dict]:
    """CORSConfiguration XML -> rule dicts (namespaced or not)."""
    cfg = ET.fromstring(body.decode() or "<CORSConfiguration/>")

    def texts(rule, tag):
        return [e.text for e in (rule.findall(_ns(tag))
                                 or rule.findall(tag)) if e.text]

    rules = []
    for r in (list(cfg.findall(_ns("CORSRule")))
              or list(cfg.findall("CORSRule"))):
        rule = {
            "allowed_origins": texts(r, "AllowedOrigin"),
            "allowed_methods": texts(r, "AllowedMethod"),
        }
        for tag, field in (("AllowedHeader", "allowed_headers"),
                           ("ExposeHeader", "expose_headers")):
            vals = texts(r, tag)
            if vals:
                rule[field] = vals
        age = (r.findtext(_ns("MaxAgeSeconds"))
               or r.findtext("MaxAgeSeconds"))
        if age:
            rule["max_age_seconds"] = int(age)
        rules.append(rule)
    return rules


def _lock_headers(req: _Request) -> dict | None:
    """x-amz-object-lock-{mode,retain-until-date,legal-hold} on PUT
    object: the new version's explicit lock state."""
    mode = req.header("x-amz-object-lock-mode")
    raw = req.header("x-amz-object-lock-retain-until-date")
    hold = req.header("x-amz-object-lock-legal-hold", "").upper()
    if not mode and not raw and not hold:
        return None
    lock: dict = {}
    if mode or raw:
        if not (mode and raw):
            raise _HTTPError(400, "InvalidArgument",
                             "mode and retain-until-date go "
                             "together")
        try:
            until = calendar.timegm(time.strptime(
                raw.split(".")[0].rstrip("Z"), "%Y-%m-%dT%H:%M:%S"))
        except ValueError:
            raise _HTTPError(400, "InvalidArgument",
                             f"bad retain-until-date {raw!r}")
        lock["mode"] = mode
        lock["until"] = float(until)
    if hold == "ON":
        lock["legal_hold"] = True
    return lock


def _parse_retention(body: bytes) -> tuple[str, float]:
    doc = ET.fromstring(body.decode())
    mode = doc.findtext(_ns("Mode")) or doc.findtext("Mode") or ""
    raw = doc.findtext(_ns("RetainUntilDate")) or \
        doc.findtext("RetainUntilDate") or ""
    try:
        until = calendar.timegm(time.strptime(
            raw.split(".")[0].rstrip("Z"), "%Y-%m-%dT%H:%M:%S"))
    except ValueError:
        raise _HTTPError(400, "MalformedXML",
                         f"bad RetainUntilDate {raw!r}")
    return mode, float(until)


def _parse_legal_hold(body: bytes) -> bool:
    doc = ET.fromstring(body.decode())
    st = (doc.findtext(_ns("Status")) or doc.findtext("Status")
          or "").upper()
    if st not in ("ON", "OFF"):
        raise _HTTPError(400, "MalformedXML", f"bad status {st!r}")
    return st == "ON"


def _parse_lock_config(body: bytes) -> tuple[str | None, int, int]:
    doc = ET.fromstring(body.decode())
    dr = doc.find(f"{_ns('Rule')}/{_ns('DefaultRetention')}")
    if dr is None:
        dr = doc.find("Rule/DefaultRetention")
    if dr is None:
        return None, 0, 0
    mode = dr.findtext(_ns("Mode")) or dr.findtext("Mode") or ""
    days = int(dr.findtext(_ns("Days")) or dr.findtext("Days") or 0)
    years = int(dr.findtext(_ns("Years"))
                or dr.findtext("Years") or 0)
    return mode, days, years


def _parse_tagging(body: bytes) -> dict[str, str]:
    """Tagging XML -> {key: value}."""
    cfg = ET.fromstring(body.decode() or "<Tagging/>")
    ts = (cfg.find(_ns("TagSet")) if cfg.find(_ns("TagSet"))
          is not None else cfg.find("TagSet"))
    tags: dict[str, str] = {}
    for t in (list(ts.findall(_ns("Tag"))) or list(ts.findall("Tag"))
              ) if ts is not None else ():
        k = t.findtext(_ns("Key")) or t.findtext("Key") or ""
        v = t.findtext(_ns("Value")) or t.findtext("Value") or ""
        if k:
            tags[k] = v
    return tags


def _header_tags(req: _Request) -> dict[str, str]:
    """The x-amz-tagging header: URL-encoded key=value pairs."""
    raw = req.header("x-amz-tagging")
    if not raw:
        return {}
    return {urllib.parse.unquote_plus(k): urllib.parse.unquote_plus(v)
            for k, _, v in (p.partition("=")
                            for p in raw.split("&")) if k}


def _parse_lifecycle(body: bytes) -> list[dict]:
    doc = ET.fromstring(body.decode())
    rules = []
    for el in doc.iter():
        if not el.tag.endswith("Rule"):
            continue
        rule = {
            "id": el.findtext(_ns("ID")) or el.findtext("ID") or "",
            "prefix": (el.findtext(_ns("Prefix"))
                       or el.findtext("Prefix")
                       or el.findtext(f"{_ns('Filter')}/{_ns('Prefix')}")
                       or el.findtext("Filter/Prefix")
                       or el.findtext(f"{_ns('Filter')}/{_ns('And')}"
                                      f"/{_ns('Prefix')}")
                       or el.findtext("Filter/And/Prefix") or ""),
            "status": (el.findtext(_ns("Status"))
                       or el.findtext("Status") or "Enabled"),
        }
        # each action element maps to its own rule field; an absent
        # element must stay absent (a defaulted 0-day expiration
        # would expire the whole prefix immediately)
        for xml_path, field in (
                (("Expiration", "Days"), "expiration_days"),
                (("NoncurrentVersionExpiration", "NoncurrentDays"),
                 "noncurrent_days"),
                (("AbortIncompleteMultipartUpload",
                  "DaysAfterInitiation"), "abort_mpu_days"),
                (("Transition", "Days"), "transition_days"),
                (("NoncurrentVersionTransition", "NoncurrentDays"),
                 "noncurrent_transition_days")):
            outer, inner = xml_path
            v = el.findtext(f"{_ns(outer)}/{_ns(inner)}") or \
                el.findtext(f"{outer}/{inner}")
            if v is not None:
                try:
                    rule[field] = int(v)
                except ValueError:
                    # a non-numeric <Days> is the CLIENT's document
                    # error: 400 MalformedXML, never an unhandled
                    # ValueError turning into a 500
                    raise _HTTPError(
                        400, "MalformedXML",
                        f"{outer}/{inner}: {v!r} is not an integer"
                    ) from None
        # unsupported action variants must be REJECTED, not dropped:
        # silently ignoring <Date> would disable the expiry or
        # transition the client asked for on a date we never check
        for outer in ("Expiration", "Transition",
                      "NoncurrentVersionExpiration",
                      "NoncurrentVersionTransition"):
            if el.find(f"{_ns(outer)}/{_ns('Date')}") is not None or \
                    el.find(f"{outer}/Date") is not None:
                raise _HTTPError(
                    501, "NotImplemented",
                    f"{outer}/Date is not supported; use Days")
        if el.find(f"{_ns('Expiration')}/"
                   f"{_ns('ExpiredObjectDeleteMarker')}") is not None \
                or el.find("Expiration/ExpiredObjectDeleteMarker") \
                is not None:
            raise _HTTPError(501, "NotImplemented",
                             "ExpiredObjectDeleteMarker is not "
                             "supported")
        for outer, field in (
                ("Transition", "transition_class"),
                ("NoncurrentVersionTransition",
                 "noncurrent_transition_class")):
            v = el.findtext(f"{_ns(outer)}/{_ns('StorageClass')}") or \
                el.findtext(f"{outer}/StorageClass")
            if v:
                rule[field] = v
        # <Filter><Tag> / <Filter><And><Tag>...: dropping a tag
        # filter silently would expire objects it was protecting
        tags = {}
        for tag_el in el.iter():
            if tag_el.tag.endswith("Tag"):
                k = (tag_el.findtext(_ns("Key"))
                     or tag_el.findtext("Key") or "")
                v = (tag_el.findtext(_ns("Value"))
                     or tag_el.findtext("Value") or "")
                if k:
                    tags[k] = v
        if tags:
            rule["tags"] = tags
        rules.append(rule)
    return rules
