"""Multisite mgr module: geo-replication telemetry + QoS actuation.

``MultisiteMonitor`` runs after ``QoSMonitor`` each report cycle
(module dispatch is insertion-ordered) and closes the replication leg
of the defense loop:

- it reads the replication-class decision the QoS controller just made
  (``QoSMonitor.last_tick["replication"]``) and pushes the pacing rate
  to every attached sync agent via :meth:`RGWSyncAgent.set_rate` — the
  replication class is not an mClock class, so the fan-out is
  in-process to the agents the local zone runs, not a wire cmd to
  OSDs; each push journals ``qos.replication_push``,
- it polls each agent's :meth:`lag` ledger (entries AND bytes
  acked-but-unreplicated per bucket/shard — the live RPO estimate) and
  perf counters, folding both into the ``multisite`` digest section,
  ``ceph_rgw_sync_*`` Prometheus gauges, and forensic bundles.

A zone that runs no orchestrator (single-site deployments) simply has
nothing attached and the module is a no-op.
"""

from __future__ import annotations

from ceph_tpu.services.mgr_modules import MgrModule


class MultisiteMonitor(MgrModule):
    name = "multisite"

    def __init__(self, mgr):
        super().__init__(mgr)
        self.orchestrators: list = []
        self._pushed_rate: float | None = None
        self.last_lag: dict = {}

    def attach(self, orchestrator) -> None:
        """Register a SyncOrchestrator whose agents this module
        measures and paces (vstart wires the zone's own)."""
        if orchestrator not in self.orchestrators:
            self.orchestrators.append(orchestrator)

    def _agents(self) -> dict[str, object]:
        out = {}
        for orch in self.orchestrators:
            for (src, dst), agent in getattr(orch, "agents",
                                             {}).items():
                out[f"{src}->{dst}"] = agent
        return out

    async def serve_once(self) -> None:
        agents = self._agents()
        if not agents:
            return
        # 1. actuate the replication QoS class: the limit the
        # controller last decided becomes every agent's pacing rate
        qos = self.mgr.modules.get("qos")
        dec = (qos.last_tick.get("replication")
               if qos is not None and qos.last_tick else None)
        if dec is not None:
            rate = float(dec["limit"])
            if rate != self._pushed_rate:
                for agent in agents.values():
                    if hasattr(agent, "set_rate"):
                        agent.set_rate(rate)
                self._pushed_rate = rate
                self.mgr.journal.emit(
                    "qos.replication_push", rate=round(rate, 3),
                    agents=len(agents))
        # 2. refresh the lag ledger (the live RPO estimate)
        lag: dict[str, dict] = {}
        for pair, agent in sorted(agents.items()):
            if not hasattr(agent, "lag"):
                continue
            try:
                lag[pair] = await agent.lag()
            except Exception:            # noqa: BLE001 — source down
                lag[pair] = {"entries": -1, "bytes": -1,
                             "unreachable": True}
        self.last_lag = lag

    # -- mgr surfaces ------------------------------------------------------
    def digest_contrib(self) -> dict:
        agents = self._agents()
        if not agents:
            return {}
        out = {
            "agents": {pair: agent.status()
                       for pair, agent in sorted(agents.items())
                       if hasattr(agent, "status")},
            "lag": {pair: {"entries": led.get("entries", 0),
                           "bytes": led.get("bytes", 0)}
                    for pair, led in sorted(self.last_lag.items())},
            "pushed_rate": self._pushed_rate,
        }
        return {"multisite": out}

    def forensics_contrib(self) -> dict:
        d = self.digest_contrib()
        return d.get("multisite", {})

    def prom_metrics(self) -> dict[str, dict]:
        agents = self._agents()
        if not agents:
            return {}
        from ceph_tpu.services.mgr import prom_label

        def samples(counter_key):
            out = []
            for pair, agent in sorted(agents.items()):
                perf = getattr(agent, "perf", None)
                if perf is None:
                    continue
                out.append((prom_label(pair=pair),
                            float(perf.value(counter_key))))
            return out or [("", 0.0)]

        out = {
            "ceph_rgw_sync_put_ops": {
                "help": "objects replicated by put replay",
                "samples": samples("sync_put_ops")},
            "ceph_rgw_sync_del_ops": {
                "help": "deletes replicated by replay",
                "samples": samples("sync_del_ops")},
            "ceph_rgw_sync_bytes": {
                "help": "payload bytes replicated",
                "samples": samples("sync_bytes")},
            "ceph_rgw_sync_reconciles": {
                "help": "version-level ops converged by re-reading "
                        "current source state",
                "samples": samples("sync_reconcile_ops")},
            "ceph_rgw_sync_retries": {
                "help": "per-shard error retries (deterministic "
                        "backoff engaged)",
                "samples": samples("sync_retries")},
            "ceph_rgw_sync_conflict_skips": {
                "help": "incoming writes skipped by last-writer-wins "
                        "(destination held a newer write)",
                "samples": samples("sync_conflict_skips")},
            "ceph_rgw_sync_purged": {
                "help": "destination-only keys removed by full-sync "
                        "resync (a revived zone's unreplicated writes)",
                "samples": samples("sync_purged")},
            "ceph_rgw_sync_paced_waits": {
                "help": "replication ops delayed by the QoS pacing "
                        "token bucket",
                "samples": samples("sync_paced_waits")},
            "ceph_rgw_sync_trim_seq": {
                "help": "latest source-shard sequence trimmed after "
                        "replay",
                "samples": samples("sync_trim_seq")},
            "ceph_rgw_sync_lag_entries": {
                "help": "datalog entries acked on the source but not "
                        "yet replayed (RPO ledger, entries)",
                "samples": samples("sync_lag_entries")},
            "ceph_rgw_sync_lag_bytes": {
                "help": "bytes acked on the source but not yet "
                        "replayed (RPO ledger, bytes)",
                "samples": samples("sync_lag_bytes")},
        }
        return out
