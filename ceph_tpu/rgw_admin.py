"""radosgw-admin: the object-gateway admin CLI.

The role of reference src/rgw/rgw_admin.cc reduced to the surfaces our
RGW-lite implements: user management + quotas, bucket listing/stats,
ACLs, lifecycle processing, zone placement targets (per-storage-class
data pools).

Usage:
    python -m ceph_tpu.rgw_admin --conf cluster.json --pool rgw \
        user create --uid alice
    python -m ceph_tpu.rgw_admin ... bucket stats --bucket site
    python -m ceph_tpu.rgw_admin ... lc process
    python -m ceph_tpu.rgw_admin ... zone placement add \
        --storage-class COLD --data-pool rgw.cold \
        --ec-profile rgw_cold --create-pool
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ceph_tpu.services.rgw import RGWError, RGWLite, RGWUsers


async def _run(args) -> int:
    from ceph_tpu.cli import _load_conf
    from ceph_tpu.client.rados import Rados

    try:
        monmap, conf = _load_conf(args.conf)
    except (OSError, ValueError, KeyError) as e:
        print(f"radosgw-admin: bad conf {args.conf!r}: {e}",
              file=sys.stderr)
        return 1
    rados = Rados(monmap, conf, name="client.rgw-admin")
    try:
        await rados.connect(timeout=args.timeout)
        ioctx = await rados.open_ioctx(args.pool)
        users = RGWUsers(ioctx)
        gw = RGWLite(ioctx, users=users,   # admin/system context
                     datalog_shards=int(
                         rados.conf["rgw_datalog_shards"]))
        out = await _dispatch(args, gw, users)
        if out is not None:
            print(json.dumps(out, indent=2, default=str))
        return 0
    except (IOError, KeyError) as e:
        print(f"radosgw-admin: {e}", file=sys.stderr)
        return 1
    finally:
        await rados.shutdown()


async def _dispatch(args, gw: RGWLite, users: RGWUsers):
    if args.cmd == "user":
        if args.sub == "create":
            return await users.create(
                args.uid, args.display_name,
                max_size=args.max_size, max_objects=args.max_objects,
            )
        if args.sub == "ls":
            return await users.list()
        if args.sub == "info":
            return await users.get(args.uid)
        if args.sub == "rm":
            await users.remove(args.uid)
            return None
        if args.sub in ("suspend", "enable"):
            await users.set_suspended(args.uid,
                                      args.sub == "suspend")
            return None
    if args.cmd == "quota":
        await users.set_quota(args.uid, max_size=args.max_size,
                              max_objects=args.max_objects)
        return None
    if args.cmd == "bucket":
        if args.sub == "ls":
            return await gw.list_buckets()
        if args.sub == "stats":
            meta = await gw._bucket_meta(args.bucket)
            size, count = await gw._bucket_usage(args.bucket, meta)
            return {
                "bucket": args.bucket,
                "owner": meta.get("owner", ""),
                "size_bytes": size,
                "num_objects": count,
                "num_shards": int(meta.get("index_shards", 1)),
                "quota": meta.get("quota", {}),
            }
        if args.sub == "reshard":
            if args.abort:
                await gw.reshard_abort(args.bucket)
                return {"bucket": args.bucket, "aborted": True}
            return await gw.reshard_bucket(args.bucket,
                                           args.num_shards)
        if args.sub == "quota":
            await gw.set_bucket_quota(args.bucket,
                                      max_size=args.max_size,
                                      max_objects=args.max_objects)
            return None
        if args.sub == "acl":
            await gw.put_bucket_acl(args.bucket, args.canned)
            return None
    if args.cmd == "lc":
        if args.sub == "process":
            return await gw.lc_process()
        if args.sub == "get":
            return await gw.get_lifecycle(args.bucket)
    if args.cmd == "gc":
        if args.sub == "list":
            return await gw.gc_list()
        if args.sub == "process":
            return {"reaped": await gw.gc_process()}
    if args.cmd == "zone" and args.sub == "placement":
        # placement targets live in the zone's own pool — no realm
        # topology required (rgw_zone.h RGWZonePlacementInfo verbs)
        from ceph_tpu.services.rgw_zone import ZonePlacement

        zp = ZonePlacement(gw.ioctx)
        if args.psub in ("add", "modify"):
            fn = zp.add if args.psub == "add" else zp.modify
            return await fn(
                args.placement_id,
                storage_class=args.storage_class,
                data_pool=args.data_pool,
                compression=args.compression,
                ec_profile=args.ec_profile,
                ec_k=args.ec_k, ec_m=args.ec_m,
                create_pool=args.create_pool, pg_num=args.pg_num)
        if args.psub == "rm":
            await zp.rm(args.placement_id,
                        args.storage_class or None)
            return {"removed": args.placement_id}
        if args.psub == "ls":
            return await zp.ls()
    if args.cmd == "sync" and args.sub == "status":
        # this zone's view of replication: per-shard source datalog
        # positions (what a peer must reach) + the persisted sync
        # markers of agents pulling INTO this zone (where they are)
        from ceph_tpu.client.rados import RadosError
        from ceph_tpu.services.rgw_sync import STATUS_OID

        try:
            kv = await gw.ioctx.get_omap(STATUS_OID)
        except RadosError as e:
            if e.rc != -2:
                raise
            kv = {}
        markers: dict[str, dict[int, int]] = {}
        for k, v in kv.items():
            if "\x00" in k:
                b, _, s = k.rpartition("\x00")
                markers.setdefault(b, {})[int(s)] = int(v)
            else:
                markers.setdefault(k, {}).setdefault(0, int(v))
        positions: dict[str, dict[str, int]] = {}
        for b in await gw.list_buckets():
            positions[b] = {
                str(s): int((await gw.log_list(
                    b, after=0, max_entries=1, shard=s))
                    .get("max_seq", 0))
                for s in range(gw.datalog_shards)}
        return {
            "datalog_shards": gw.datalog_shards,
            "source_positions": positions,
            "sync_markers": {
                b: {str(s): q for s, q in sorted(m.items())}
                for b, m in sorted(markers.items())},
        }
    if args.cmd in ("realm", "zonegroup", "zone", "period"):
        from ceph_tpu.services.rgw_zone import RealmStore

        store = RealmStore(gw.ioctx)
        if args.cmd == "realm":
            if args.sub == "create":
                return await store.realm_create(args.rgw_realm)
            if args.sub == "list":
                return await store.realm_list()
            if args.sub == "get":
                return await store.realm_get(args.rgw_realm)
        if args.cmd == "zonegroup":
            if args.sub == "create":
                return await store.zonegroup_create(
                    args.rgw_realm, args.rgw_zonegroup,
                    master=args.master)
            if args.sub == "list":
                return await store.zonegroup_list(args.rgw_realm)
        if args.cmd == "zone":
            if args.sub == "create":
                return await store.zone_create(
                    args.rgw_realm, args.rgw_zonegroup,
                    args.rgw_zone, endpoint=args.endpoint,
                    master=args.master)
            if args.sub == "modify":
                return await store.zone_modify(
                    args.rgw_realm, args.rgw_zonegroup,
                    args.rgw_zone,
                    endpoint=args.endpoint or None,
                    master=args.master or None)
            if args.sub == "rm":
                await store.zone_rm(args.rgw_realm,
                                    args.rgw_zonegroup, args.rgw_zone)
                return {"removed": args.rgw_zone}
        if args.cmd == "period":
            if args.sub == "update":
                return await store.period_update(args.rgw_realm,
                                                 commit=args.commit)
            if args.sub == "get":
                return await store.period_get(
                    args.rgw_realm, args.period_id or None)
            if args.sub == "list":
                return await store.period_list(args.rgw_realm)
    raise RGWError("InvalidArgument", f"{args.cmd} {args.sub}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="radosgw-admin",
                                description=__doc__)
    p.add_argument("--conf", default="cluster.json")
    p.add_argument("--pool", default="rgw")
    p.add_argument("--timeout", type=float, default=15.0)
    sub = p.add_subparsers(dest="cmd", required=True)

    user = sub.add_parser("user")
    user_sub = user.add_subparsers(dest="sub", required=True)
    uc = user_sub.add_parser("create")
    uc.add_argument("--uid", required=True)
    uc.add_argument("--display-name", default="")
    uc.add_argument("--max-size", type=int, default=0)
    uc.add_argument("--max-objects", type=int, default=0)
    for sname in ("suspend", "enable"):
        sp_ = user_sub.add_parser(sname)
        sp_.add_argument("--uid", required=True)
    user_sub.add_parser("ls")
    for name in ("info", "rm"):
        x = user_sub.add_parser(name)
        x.add_argument("--uid", required=True)

    quota = sub.add_parser("quota")
    quota.add_argument("sub", choices=["set"])
    quota.add_argument("--uid", required=True)
    quota.add_argument("--max-size", type=int, default=0)
    quota.add_argument("--max-objects", type=int, default=0)

    bucket = sub.add_parser("bucket")
    bucket_sub = bucket.add_subparsers(dest="sub", required=True)
    bucket_sub.add_parser("ls")
    rs = bucket_sub.add_parser("reshard")
    rs.add_argument("--bucket", required=True)
    rs.add_argument("--num-shards", type=int, default=2)
    rs.add_argument("--abort", action="store_true")
    for name in ("stats", "quota", "acl"):
        x = bucket_sub.add_parser(name)
        x.add_argument("--bucket", required=True)
        if name == "quota":
            x.add_argument("--max-size", type=int, default=0)
            x.add_argument("--max-objects", type=int, default=0)
        if name == "acl":
            x.add_argument("--canned", default="private")

    lc = sub.add_parser("lc")
    lc_sub = lc.add_subparsers(dest="sub", required=True)
    lc_sub.add_parser("process")
    lg = lc_sub.add_parser("get")
    lg.add_argument("--bucket", required=True)

    gc = sub.add_parser("gc")
    gc_sub = gc.add_subparsers(dest="sub", required=True)
    gc_sub.add_parser("list")
    gc_sub.add_parser("process")

    # multisite config model (rgw_zone.h realm/zonegroup/zone/period)
    realm = sub.add_parser("realm")
    realm_sub = realm.add_subparsers(dest="sub", required=True)
    for name in ("create", "get"):
        x = realm_sub.add_parser(name)
        x.add_argument("--rgw-realm", required=True)
    realm_sub.add_parser("list")

    zg = sub.add_parser("zonegroup")
    zg_sub = zg.add_subparsers(dest="sub", required=True)
    zgc = zg_sub.add_parser("create")
    zgc.add_argument("--rgw-realm", required=True)
    zgc.add_argument("--rgw-zonegroup", required=True)
    zgc.add_argument("--master", action="store_true")
    zgl = zg_sub.add_parser("list")
    zgl.add_argument("--rgw-realm", required=True)

    zone = sub.add_parser("zone")
    zone_sub = zone.add_subparsers(dest="sub", required=True)
    for name in ("create", "modify", "rm"):
        x = zone_sub.add_parser(name)
        x.add_argument("--rgw-realm", required=True)
        x.add_argument("--rgw-zonegroup", required=True)
        x.add_argument("--rgw-zone", required=True)
        if name != "rm":
            x.add_argument("--endpoint", default="")
            x.add_argument("--master", action="store_true")
    # zone placement targets: per-storage-class data pools
    placement = zone_sub.add_parser("placement")
    pl_sub = placement.add_subparsers(dest="psub", required=True)
    for name in ("add", "modify"):
        x = pl_sub.add_parser(name)
        x.add_argument("--placement-id", default="default-placement")
        x.add_argument("--storage-class", default="STANDARD")
        x.add_argument("--data-pool", default="")
        x.add_argument("--compression", default="")
        x.add_argument("--ec-profile", default="")
        x.add_argument("--ec-k", type=int, default=2)
        x.add_argument("--ec-m", type=int, default=1)
        x.add_argument("--create-pool", action="store_true")
        x.add_argument("--pg-num", type=int, default=8)
    plrm = pl_sub.add_parser("rm")
    plrm.add_argument("--placement-id", default="default-placement")
    plrm.add_argument("--storage-class", default="")
    pl_sub.add_parser("ls")

    sync = sub.add_parser("sync")
    sync_sub = sync.add_subparsers(dest="sub", required=True)
    sync_sub.add_parser("status")

    period = sub.add_parser("period")
    period_sub = period.add_subparsers(dest="sub", required=True)
    pu = period_sub.add_parser("update")
    pu.add_argument("--rgw-realm", required=True)
    pu.add_argument("--commit", action="store_true")
    pg = period_sub.add_parser("get")
    pg.add_argument("--rgw-realm", required=True)
    pg.add_argument("--period-id", default="")
    pl = period_sub.add_parser("list")
    pl.add_argument("--rgw-realm", required=True)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return asyncio.run(_run(args))


if __name__ == "__main__":
    sys.exit(main())
