"""rbd: the block-image admin CLI.

The role of reference src/tools/rbd (rbd create/ls/info/snap/clone/...):
a thin command surface over services.rbd against a cluster conf file
(DevCluster.write_conf), plus import/export to local files.

Usage:
    python -m ceph_tpu.rbd_tool --conf cluster.json --pool rbd \
        create img1 --size 8388608
    python -m ceph_tpu.rbd_tool ... snap create img1@s1
    python -m ceph_tpu.rbd_tool ... clone img1@s1 img2
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from ceph_tpu.services.rbd import RBD, RBDError


def _image_spec(spec: str) -> tuple[str, str | None]:
    name, _, snap = spec.partition("@")
    return name, (snap or None)


async def _run(args) -> int:
    from ceph_tpu.cli import _load_conf
    from ceph_tpu.client.rados import Rados

    try:
        monmap, conf = _load_conf(args.conf)
    except (OSError, ValueError, KeyError) as e:
        print(f"rbd: bad conf {args.conf!r}: {e}",
              file=sys.stderr)
        return 1
    rados = Rados(monmap, conf, name="client.rbd-tool")
    try:
        await rados.connect(timeout=args.timeout)
        ioctx = await rados.open_ioctx(args.pool)
        if getattr(args, "namespace", ""):
            ioctx.set_namespace(args.namespace)
        rbd = RBD(ioctx)
        out = await _dispatch(args, rbd)
        if out is not None:
            print(json.dumps(out, indent=2, default=str))
        return 0
    except (IOError, KeyError) as e:
        print(f"rbd: {e}", file=sys.stderr)
        return 1
    finally:
        await rados.shutdown()


async def _dispatch(args, rbd: RBD):
    cmd = args.cmd
    if cmd == "group":
        from ceph_tpu.services.rbd_group import RBDGroups

        groups = RBDGroups(rbd)
        g = args.group_args
        gc = args.group_cmd
        if gc == "create":
            return {"id": await groups.create(g[0])}
        if gc == "ls":
            return await groups.list()
        if gc == "rm":
            await groups.remove(g[0])
            return None
        if gc == "rename":
            await groups.rename(g[0], g[1])
            return None
        if gc == "image-add":
            await groups.image_add(g[0], g[1])
            return None
        if gc == "image-rm":
            await groups.image_remove(g[0], g[1])
            return None
        if gc == "image-ls":
            return await groups.image_list(g[0])
        if gc == "snap-create":
            return {"id": await groups.snap_create(g[0], g[1])}
        if gc == "snap-ls":
            return await groups.snap_list(g[0])
        if gc == "snap-rm":
            await groups.snap_remove(g[0], g[1])
            return None
        if gc == "snap-rollback":
            await groups.snap_rollback(g[0], g[1])
            return None
    if cmd == "namespace":
        if args.ns_cmd == "create":
            await rbd.namespace_create(args.ns_name)
            return None
        if args.ns_cmd == "ls":
            return await rbd.namespace_list()
        if args.ns_cmd == "rm":
            await rbd.namespace_remove(args.ns_name)
            return None
    if cmd == "create":
        await rbd.create(args.image, args.size, order=args.order,
                         object_map=not args.no_object_map)
        return None
    if cmd == "ls":
        return await rbd.list()
    if cmd == "info":
        img = await rbd.open(args.image)
        info = img.stat()
        info["snaps"] = img.snap_list()
        if img.parent is not None:
            info["parent"] = img.parent
        return info
    if cmd == "rm":
        await rbd.remove(args.image)
        return None
    if cmd == "resize":
        img = await rbd.open(args.image)
        await img.resize(args.size)
        return None
    if cmd == "children":
        name, snap = _image_spec(args.snap_spec)
        if snap is None:
            raise RBDError("children wants image@snap")
        return await rbd.children(name, snap)
    if cmd == "clone":
        name, snap = _image_spec(args.snap_spec)
        if snap is None:
            raise RBDError("clone wants parent image@snap")
        child = args.child
        dest = None
        if "/" in child:            # cross-pool: pool/child syntax
            dpool, child = child.split("/", 1)
            dest = RBD(await rbd.ioctx.rados.open_ioctx(dpool))
        await rbd.clone(name, snap, child, dest=dest)
        return None
    if cmd == "flatten":
        img = await rbd.open(args.image)
        await img.flatten()
        return None
    if cmd == "object-map":
        img = await rbd.open(args.image)
        await img.object_map_rebuild()
        return None
    if cmd == "export":
        img = await rbd.open(args.image)
        data = await img.read(0, img.size)
        with open(args.path, "wb") as f:
            f.write(data)
        return {"exported": len(data)}
    if cmd == "import":
        with open(args.path, "rb") as f:
            data = f.read()
        await rbd.create(args.image, len(data), order=args.order)
        img = await rbd.open(args.image)
        await img.write(0, data)
        return {"imported": len(data)}
    if cmd == "snap":
        name, snap = _image_spec(args.snap_spec)
        img = await rbd.open(name)
        if args.snap_cmd == "ls":
            return img.snap_list()
        if snap is None:
            raise RBDError(f"snap {args.snap_cmd} wants image@snap")
        if args.snap_cmd == "create":
            await img.snap_create(snap)
        elif args.snap_cmd == "rm":
            await img.snap_remove(snap)
        elif args.snap_cmd == "protect":
            await img.snap_protect(snap)
        elif args.snap_cmd == "unprotect":
            await img.snap_unprotect(snap)
        elif args.snap_cmd == "rollback":
            await img.snap_rollback(snap)
        return None
    if cmd in ("deep-cp", "migrate"):
        dst = args.dst
        dest = None
        if "/" in dst:              # cross-pool: pool/name syntax
            dpool, dst = dst.split("/", 1)
            dest = RBD(await rbd.ioctx.rados.open_ioctx(dpool))
        if cmd == "deep-cp":
            await rbd.deep_copy(args.src, dst, dest=dest)
        else:
            await rbd.migrate(args.src, dst, dest=dest)
        return None
    if cmd == "image-meta":
        img = await rbd.open(args.image)
        if args.meta_cmd == "set":
            await img.meta_set(args.key, args.value)
            return None
        if args.meta_cmd == "get":
            return await img.meta_get(args.key)
        if args.meta_cmd == "ls":
            return await img.meta_list()
        if args.meta_cmd == "rm":
            await img.meta_remove(args.key)
            return None
    if cmd == "bench":
        img = await rbd.open(args.image)
        import secrets as _secrets
        import time as _time

        if args.io_size <= 0 or args.io_size > img.size:
            raise RBDError("--io-size must be in [1, image size]")
        payload = b"\xa5" * args.io_size
        rng = _secrets.SystemRandom()
        nops = args.io_total // args.io_size
        lat = []
        t0 = _time.perf_counter()
        for _ in range(nops):
            off = rng.randrange(
                max(1, img.size - args.io_size)
            ) // 512 * 512
            t1 = _time.perf_counter()
            if args.io_type == "write":
                await img.write(off, payload)
            else:
                await img.read(off, args.io_size)
            lat.append(_time.perf_counter() - t1)
        elapsed = _time.perf_counter() - t0
        await img.close()
        lat.sort()
        return {
            "ops": nops, "seconds": round(elapsed, 3),
            "iops": round(nops / elapsed, 1),
            "MiB_per_s": round(nops * args.io_size / elapsed
                               / (1 << 20), 2),
            "lat_p50_ms": round(lat[len(lat) // 2] * 1e3, 3)
            if lat else 0.0,
            "lat_p99_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 3)
            if lat else 0.0,
        }
    if cmd == "trash":
        if args.trash_cmd == "mv":
            return {"id": await rbd.trash_move(args.image,
                                               delay=args.delay)}
        if args.trash_cmd == "ls":
            return await rbd.trash_list()
        if args.trash_cmd == "restore":
            return {"name": await rbd.trash_restore(
                args.image_id, args.name or None)}
        if args.trash_cmd == "rm":
            await rbd.trash_remove(args.image_id, force=args.force)
            return None
    if cmd == "lock":
        img = await rbd.open(args.image)
        if args.lock_cmd == "ls":
            info = await img.lock_info()
            return [{"locker": lk, **v}
                    for lk, v in sorted(info.get("lockers",
                                                 {}).items())]
        if args.lock_cmd == "break":
            await img.break_lock(args.locker,
                                 blocklist=args.blocklist)
            return None
    raise RBDError(f"unknown command {cmd!r}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="rbd", description=__doc__)
    p.add_argument("--conf", default="cluster.json")
    p.add_argument("--pool", default="rbd")
    p.add_argument("--namespace", default="",
                   help="rados namespace scoping every image op")
    p.add_argument("--timeout", type=float, default=15.0)
    sub = p.add_subparsers(dest="cmd", required=True)

    grp = sub.add_parser("group")
    grp.add_argument("group_cmd", choices=[
        "create", "ls", "rm", "rename", "image-add", "image-rm",
        "image-ls", "snap-create", "snap-ls", "snap-rm",
        "snap-rollback",
    ])
    grp.add_argument("group_args", nargs="*",
                     help="group [image|snap|new-name]")

    ns = sub.add_parser("namespace")
    ns.add_argument("ns_cmd", choices=["create", "ls", "rm"])
    ns.add_argument("ns_name", nargs="?", default="")

    c = sub.add_parser("create")
    c.add_argument("image")
    c.add_argument("--size", type=int, required=True)
    c.add_argument("--order", type=int, default=22)
    c.add_argument("--no-object-map", action="store_true")
    sub.add_parser("ls")
    for name in ("info", "rm", "flatten"):
        x = sub.add_parser(name)
        x.add_argument("image")
    r = sub.add_parser("resize")
    r.add_argument("image")
    r.add_argument("--size", type=int, required=True)
    om = sub.add_parser("object-map")
    om.add_argument("om_cmd", choices=["rebuild"])
    om.add_argument("image")
    ch = sub.add_parser("children")
    ch.add_argument("snap_spec", help="image@snap")
    cl = sub.add_parser("clone")
    cl.add_argument("snap_spec", help="parent image@snap")
    cl.add_argument("child")
    for name in ("export", "import"):
        x = sub.add_parser(name)
        x.add_argument("image")
        x.add_argument("path")
        if name == "import":
            x.add_argument("--order", type=int, default=22)
    for name in ("deep-cp", "migrate"):
        x = sub.add_parser(name)
        x.add_argument("src")
        x.add_argument("dst")
    im = sub.add_parser("image-meta")
    im_sub = im.add_subparsers(dest="meta_cmd", required=True)
    for name in ("set", "get", "rm", "ls"):
        x = im_sub.add_parser(name)
        x.add_argument("image")
        if name != "ls":
            x.add_argument("key")
        if name == "set":
            x.add_argument("value")
    bn = sub.add_parser("bench")
    bn.add_argument("image")
    bn.add_argument("--io-type", choices=["write", "read"],
                    default="write")
    bn.add_argument("--io-size", type=int, default=4096)
    bn.add_argument("--io-total", type=int, default=4 << 20)
    tr = sub.add_parser("trash")
    tr_sub = tr.add_subparsers(dest="trash_cmd", required=True)
    trm = tr_sub.add_parser("mv")
    trm.add_argument("image")
    trm.add_argument("--delay", type=float, default=0.0)
    tr_sub.add_parser("ls")
    trr = tr_sub.add_parser("restore")
    trr.add_argument("image_id")
    trr.add_argument("--name", default="")
    trx = tr_sub.add_parser("rm")
    trx.add_argument("image_id")
    trx.add_argument("--force", action="store_true")
    lk = sub.add_parser("lock")
    lk_sub = lk.add_subparsers(dest="lock_cmd", required=True)
    lkl = lk_sub.add_parser("ls")
    lkl.add_argument("image")
    lkb = lk_sub.add_parser("break")
    lkb.add_argument("image")
    lkb.add_argument("locker")
    lkb.add_argument("--blocklist", action="store_true",
                     help="fence the owner's client instance at the "
                          "OSDs before breaking (reference default)")
    sn = sub.add_parser("snap")
    sn.add_argument("snap_cmd", choices=[
        "create", "ls", "rm", "protect", "unprotect", "rollback",
    ])
    sn.add_argument("snap_spec", help="image[@snap]")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return asyncio.run(_run(args))


if __name__ == "__main__":
    sys.exit(main())
