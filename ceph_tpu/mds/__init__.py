"""CephFS-lite: metadata service + POSIX-ish client (reference src/mds +
src/client, SURVEY.md §2.8)."""

from ceph_tpu.mds.daemon import MDSDaemon  # noqa: F401
