"""MDS-lite: the CephFS metadata server on RADOS objects.

The essentials of reference src/mds (MDSRank.h:133, MDCache.cc,
Server.cc, MDLog.h:61) at -lite scale:

- The file NAMESPACE lives in RADOS omaps: directory inode ino has a
  dirfrag object ``<ino:x>.dir`` in the metadata pool whose omap maps
  child name -> dentry. Inodes are EMBEDDED in their primary dentry
  (the reference's primary-link inode embedding): type, mode, size,
  mtime, layout.
- Every metadata mutation is JOURNALED first (MDLog/LogEvent role): one
  frame appended to the ``mds_journal`` object, then applied to the
  dirfrag omaps. Replay on startup re-applies whatever a crash left
  unapplied (entries are idempotent); the journal is compacted once
  everything is known applied, persisting the ino allocator watermark
  (InoTable role).
- Clients send metadata requests over the messenger (Server.cc
  handle_client_request); FILE DATA never passes through the MDS —
  clients stripe it straight to the data pool (the defining CephFS
  property). Lookup/readdir replies carry a lease TTL (the caps/lease
  model reduced to read-caching: mutations are always MDS round-trips).

File data layout (client side, reference file layout semantics):
``<ino:x>.<blockno:08x>`` objects of ``block_size`` bytes.
"""

from __future__ import annotations

import asyncio
import secrets
import struct
import time

from ceph_tpu.common import failpoint as fp
from ceph_tpu.common.lockdep import DLock
from ceph_tpu.client.rados import IoCtx, ObjectOperation, Rados, RadosError
from ceph_tpu.common.config import ConfigProxy
from ceph_tpu.common.log import Dout
from ceph_tpu.msg.codec import decode, encode
from ceph_tpu.msg.message import Message
from ceph_tpu.msg.messenger import Connection, Messenger, Policy

log = Dout("mds")

ROOT_INO = 1
JOURNAL_OID = "mds_journal"
TABLE_OID = "mds_inotable"
ANCHOR_OID = "mds_anchortab"
SUBTREE_OID = "mds_subtree_map"
# Cross-rank rename commit records (witness-lite slave-commit log):
# omap keys "commit:<token>" / "abort:<token>" (token = per-attempt
# random hex) on one shared object, mutated ONLY through the atomic
# cls rename_wal methods (services/cls.py) so the commit/abort race
# has a single winner.  The DESTINATION claims "commit" in the same
# apply that links the dentry; the SOURCE claims "abort" when
# resolving an ambiguous timeout.  The marker — not the destination
# dirfrag's current state — is what timeout resolution and replay
# repair key off: a dst dentry later unlinked or renamed away must
# still count as COMMITTED.
RENAME_LOG_OID = "mds_rename_log"
ECANCELED = -125
_FRAME = struct.Struct("<I")
# rank r allocates inos from r * RANK_INO_BASE (per-rank InoTable
# partitions; reference preallocates per-rank ino ranges)
RANK_INO_BASE = 1 << 40
EBUSY = -16
EXDEV = -18
EDQUOT = -122
EREMOTE_RANK = -66          # client retries at reply["redirect_rank"]

# errno-style codes shared with the client
ENOENT = -2
EEXIST = -17
ENOTDIR = -20
EISDIR = -21
ENOTEMPTY = -39
ELOOP = -40
EINVAL = -22
EPERM = -1
EROFS = -30


def dirfrag_oid(ino: int) -> str:
    return f"{ino:x}.dir"


def snap_dirfrag_oid(ino: int, snapid: int) -> str:
    return f"{ino:x}.dir.snap.{snapid}"


# -- directory fragmentation (reference CDir::split/merge CDir.cc:994,
# 1096 and MDCache::adjust_dir_fragments MDCache.cc:11187) --------------
# Dentries are partitioned over FRAGMENTS of the 32-bit rjenkins hash
# space (the reference hashes dentry names with ceph_str_hash for the
# same purpose).  The fragtree — the leaf list of (bits, value) pairs,
# where a leaf covers names whose hash's top `bits` bits equal `value`
# — rides a "fragtree" xattr on the BASE dirfrag object <ino>.dir.  The
# base object always exists for a live directory and keeps the metadata
# xattrs (parent, past_snaps); with the trivial tree [(0, 0)] it also
# holds the dentries (the unfragmented layout every pre-frag test and
# tool knows).  A split moves entries into <ino>.dir.<bits>_<value:x>
# sibling objects; snapshot COW copies stay single-object (frozen views
# are read-only, so one omap is the simpler correct layout).  Splits
# and merges are journaled ("fragment" entries) and idempotent under
# crash replay.

ROOT_FRAG = (0, 0)
MAX_FRAG_BITS = 8


def frag_oid(ino: int, bits: int, value: int) -> str:
    if bits == 0:
        return dirfrag_oid(ino)
    return f"{ino:x}.dir.{bits}_{value:x}"


def frag_contains(bits: int, value: int, h: int) -> bool:
    return bits == 0 or (h >> (32 - bits)) == value


def frag_for(tree: list[tuple[int, int]], name: str) -> tuple[int, int]:
    """The fragtree leaf covering ``name`` (fragtree_t::operator[])."""
    from ceph_tpu.placement.hashing import ceph_str_hash_rjenkins

    h = ceph_str_hash_rjenkins(name)
    for b, v in tree:
        if frag_contains(b, v, h):
            return (b, v)
    return ROOT_FRAG        # malformed tree: base object fallback


async def fragtree_of(meta, dino: int) -> list[tuple[int, int]]:
    """Read a directory's fragtree (trivial when the xattr or the base
    object is absent — the OSD returns ENOENT for both).  Any other
    error propagates: silently defaulting on e.g. EIO would route a
    write into the base object of a fragmented directory, where no
    lookup would ever find it again.  Module-level so offline tools
    (cephfs-data-scan) share the exact routing the daemon uses."""
    try:
        raw = await meta.get_xattr(dirfrag_oid(dino), "fragtree")
    except RadosError as e:
        if e.rc != ENOENT:
            raise
        return [ROOT_FRAG]
    try:
        tree = [(int(b), int(v)) for b, v in decode(raw)]
        return tree or [ROOT_FRAG]
    except (ValueError, TypeError):
        return [ROOT_FRAG]


async def frag_oid_for_name(meta, dino: int, name: str) -> str:
    """The object holding (or destined to hold) ``name``'s dentry.
    (For the trivial tree frag_for returns ROOT_FRAG and frag_oid maps
    it to the base object — no special case needed.)"""
    tree = await fragtree_of(meta, dino)
    return frag_oid(dino, *frag_for(tree, name))


SNAPTABLE_OID = "mds_snaptable"
QUOTATABLE_OID = "mds_quotatab"


def block_oid(ino: int, blockno: int) -> str:
    return f"{ino:x}.{blockno:08x}"


def backtrace_oid(ino: int) -> str:
    """Per-file backtrace object in the DATA pool (the reference
    stores backtrace xattrs on object 0; a sidecar here keeps
    'block 0 absent' meaning 'no data flushed yet')."""
    return f"{ino:x}.bt"


class MDSError(Exception):
    def __init__(self, rc: int, msg: str = "",
                 missing_dentry: bool = False,
                 redirect_rank: int | None = None):
        super().__init__(f"rc={rc} {msg}")
        self.rc = rc
        # distinguishes "the NAME is absent in an existing directory"
        # (create may proceed) from "the directory itself is absent"
        self.missing_dentry = missing_dentry
        # EREMOTE_RANK: the rank the client should retry at
        self.redirect_rank = redirect_rank


def _dentry(ino: int, dtype: str, mode: int, size: int = 0) -> dict:
    now = time.time()
    return {"ino": ino, "type": dtype, "mode": mode, "size": size,
            "mtime": now, "ctime": now}


class MDSDaemon:
    def __init__(self, name: str, monmap: dict[str, str],
                 conf: ConfigProxy | None = None,
                 addr: str | None = None,
                 meta_pool: str = "cephfs_meta",
                 data_pool: str = "cephfs_data",
                 block_size: int = 1 << 22,
                 fs_name: str = "cephfs"):
        self.name = name
        self.entity = f"mds.{name}"
        self.fs_name = fs_name
        self._beacon_task = None
        self._last_state: str | None = None
        self._rados_dispatch = None
        self.conf = conf or ConfigProxy()
        self.addr = addr or f"local://{self.entity}"
        self.meta_pool = meta_pool
        self.data_pool = data_pool
        self.block_size = block_size
        # the MDS is itself a RADOS client of the metadata/data pools
        self.rados = Rados(monmap, self.conf, name=f"client.{self.entity}")
        self.meta: IoCtx | None = None
        self.data: IoCtx | None = None
        self.msgr = Messenger(self.entity, self.conf)
        self.msgr.set_policy("client", Policy.stateless_server())
        self.msgr.set_dispatcher(self)
        self.next_ino = ROOT_INO + 1
        self.journal_len = 0
        self._mutate = DLock("mds-mutate")  # per-rank serialization
        self.lease_ttl = 2.0
        # multi-active: this daemon's rank (assigned by the MDSMonitor)
        # and the subtree delegation map (dir ino -> authoritative rank;
        # the Migrator/subtree-auth role, reference Migrator.h:50)
        self.rank = 0
        self._subtrees: dict[int, int] = {}
        self._auth_cache: dict[int, int] = {}  # dir ino -> auth rank
        self._subtrees_loaded = 0.0            # refresh throttle stamp
        # rank-to-rank requests (cross-rank rename import): this MDS
        # acts as a CLIENT of the peer rank over the same wire ops
        self._peer_pending: dict[int, "asyncio.Future"] = {}
        self._peer_tid = 0
        # open cross-rank rename intents (token -> intent entry):
        # survive journal compaction, resolved by replay repair
        self._open_intents: dict[str, dict] = {}
        # (parent, name) pairs pinned by an in-flight cross-rank
        # rename (mutations on them get EBUSY — the xlock role)
        self._busy_names: set[tuple[int, str]] = set()
        # directory quotas (reference client/mds vxattr quotas,
        # quota.h quota_info_t): dir ino -> {max_bytes, max_files};
        # usage is accounted lazily per quota root (first enforcement
        # walks the subtree once, then increments ride each op — the
        # rstat propagation role, without the per-ancestor journaling)
        self.quotas: dict[int, dict] = {}
        self._qusage: dict[int, dict] = {}
        # file write caps (Locker.cc/Capability.h reduced to the
        # -lite slice: ONE exclusive buffered-write cap per file ino,
        # granted at open, recalled when anyone else opens the file).
        # Volatile by design — an MDS restart drops grants, like the
        # reference before client reconnect replays them.
        # client sessions (SessionMap role): stable sid -> info; fed
        # by session opens, trimmed on reset, listable/evictable via
        # the admin socket.  Monotonic ids — id(conn) values recycle
        # after GC and a stale sid could evict the wrong client
        self._sessions: dict[int, dict] = {}
        self._next_sid = 0
        self._caps: dict[int, dict] = {}       # ino -> {conn, holder}
        self._cap_waiters: dict[int, list] = {}   # ino -> [futures]
        # forward-scrub damage table (DamageTable.h role): findings
        # survive until explicitly acked (damage rm)
        self._damage: list[dict] = []
        self._damage_seq = 0
        # balancer (MDBalancer.h:33 role): decaying per-directory
        # request popularity (DecayCounter semantics, one shared
        # lazy-decay stamp for the whole map)
        self._pop: dict[int, float] = {}
        self._pop_stamp = time.monotonic()
        self._balance_task = None
        # per-frag entry counts ((ino, bits, value) -> n): lazily
        # initialized, incrementally maintained, drive split/merge
        # (the CDir::fnode fragstat role)
        self._frag_counts: dict[tuple[int, int, int], int] = {}
        # per-ino fragtree cache (CInode dirfragtree role); cleared
        # with _auth_cache and on local split/merge/removal
        self._ftree_cache: dict[int, list[tuple[int, int]]] = {}

    # -- lifecycle ---------------------------------------------------------
    async def start(self, timeout: float = 20.0) -> None:
        fp.apply_conf(self.conf)
        await self.rados.connect(timeout)
        self.meta = await self.rados.open_ioctx(self.meta_pool)
        self.data = await self.rados.open_ioctx(self.data_pool)
        self.snaps: dict[int, dict] = {}
        await self._load_snaptable()
        await self._load_quotatable()
        await self._load_subtrees()
        await self._load_table()
        await self._replay_journal()
        # ensure the root dirfrag exists
        try:
            await self.meta.operate(dirfrag_oid(ROOT_INO),
                                    ObjectOperation().create())
        except RadosError as e:
            if e.rc != EEXIST:
                raise
        await self.msgr.bind(self.addr)
        # intercept beacon acks on the rados mon session (chained
        # dispatcher, the CephFS-client pattern)
        self._rados_dispatch = self.rados.ms_dispatch
        self.rados.msgr.set_dispatcher(self)
        self._beacon_task = asyncio.create_task(self._beacon_loop())
        if self.conf["mds_bal_interval"] > 0:
            self._balance_task = asyncio.create_task(
                self._balance_loop())
        run_dir = self.conf["admin_socket_dir"]
        if run_dir:
            from ceph_tpu.common.admin_socket import AdminSocket

            sock = AdminSocket(self.entity)
            sock.register("status", lambda: {
                "entity": self.entity, "fs": self.fs_name,
                "state": self._last_state or "booting",
                "next_ino": self.next_ino,
                "journal_len": self.journal_len,
            }, "mds state")
            sock.register("config show", self.conf.show,
                          "live configuration")
            sock.register("session ls", self.session_ls,
                          "live client sessions + cap counts")
            sock.register("session evict", self.session_evict,
                          "session evict <id>: revoke caps + close")
            sock.register("scrub start", self.scrub_start,
                          "forward scrub: walk + validate metadata "
                          "(repair=true fixes what it can)")
            sock.register("damage ls", self.damage_ls,
                          "damage table entries")
            sock.register("damage rm", self.damage_rm,
                          "damage rm <id>: ack one entry")
            from ceph_tpu.common.log import recent_lines
            sock.register("log dump", recent_lines,
                          "recent log ring (crash context)")
            fp.register_admin_commands(sock)
            await sock.start(run_dir)
            self.admin_socket = sock
        else:
            self.admin_socket = None
        log.dout(1, "%s: up at %s (meta=%s data=%s)", self.entity,
                 self.msgr.my_addr, self.meta_pool, self.data_pool)

    async def _beacon_loop(self) -> None:
        """MMDSBeacon: announce (name, addr, fs) to the monitor so the
        FSMap tracks this daemon and clients can discover the active
        MDS (reference Beacon.cc)."""
        interval = self.conf["mds_beacon_interval"]
        while True:
            conn = self.rados.monc.conn
            if conn is not None and not conn.is_closed:
                try:
                    conn.send_message(Message("mds_beacon", {
                        "name": self.name,
                        "addr": str(self.msgr.my_addr),
                        "fs": self.fs_name,
                        "load": round(self.my_load(), 3),
                    }))
                except ConnectionError:
                    pass
            await asyncio.sleep(interval)

    async def shutdown(self) -> None:
        if getattr(self, "admin_socket", None) is not None:
            await self.admin_socket.stop()
            self.admin_socket = None
        if self._beacon_task is not None:
            self._beacon_task.cancel()
            self._beacon_task = None
        if self._balance_task is not None:
            self._balance_task.cancel()
            self._balance_task = None
        async with self._mutate:
            await self._compact_journal()
        await self.rados.shutdown()
        await self.msgr.shutdown()

    # -- journal (MDLog) ---------------------------------------------------
    async def _load_snaptable(self) -> None:
        try:
            omap = await self.meta.get_omap(SNAPTABLE_OID)
        except RadosError as e:
            if e.rc != ENOENT:
                raise
            omap = {}
        self.snaps = {int(k): decode(v) for k, v in omap.items()}
        self._apply_snapc()

    async def _load_quotatable(self) -> None:
        try:
            omap = await self.meta.get_omap(QUOTATABLE_OID)
        except RadosError as e:
            if e.rc != ENOENT:
                raise
            omap = {}
        new = {int(k): decode(v) for k, v in omap.items()}
        if new != self.quotas:
            self.quotas = new
            self._qusage.clear()

    def _apply_snapc(self) -> None:
        """Keep the MDS's own data-pool writes (purges) COW-correct
        under the live snap set."""
        ids = sorted(self.snaps)
        self.data.set_snap_context(max(ids, default=0), ids)

    def _snapc_wire(self) -> dict:
        ids = sorted(self.snaps)
        return {"seq": max(ids, default=0), "snaps": ids}

    @property
    def _journal_oid(self) -> str:
        # per-rank journals: two actives must never interleave frames
        # or compact each other's unapplied entries
        return (JOURNAL_OID if self.rank == 0
                else f"{JOURNAL_OID}.{self.rank}")

    @property
    def _table_key(self) -> str:
        return ("next_ino" if self.rank == 0
                else f"next_ino.{self.rank}")

    def _ino_floor(self) -> int:
        return (ROOT_INO + 1 if self.rank == 0
                else self.rank * RANK_INO_BASE + 1)

    async def _load_table(self) -> None:
        self.next_ino = self._ino_floor()
        try:
            raw = await self.meta.get_xattr(TABLE_OID, self._table_key)
            self.next_ino = max(self.next_ino, int(raw))
        except RadosError as e:
            if e.rc != ENOENT:
                raise

    async def _load_subtrees(self) -> None:
        try:
            omap = await self.meta.get_omap(SUBTREE_OID)
        except RadosError as e:
            if e.rc != ENOENT:
                raise
            omap = {}
        self._subtrees = {int(k): int(v) for k, v in omap.items()}
        self._auth_cache.clear()
        self._ftree_cache.clear()
        self._subtrees_loaded = time.monotonic()
        # quota knowledge rides the same refresh cadence: a rank that
        # just imported a realm root must enforce its quota
        await self._load_quotatable()

    async def _replay_journal(self) -> None:
        """Re-apply journaled mutations a crash may have left unapplied
        (idempotent omap writes; MDLog replay role)."""
        try:
            raw = await self.meta.read(self._journal_oid)
        except RadosError as e:
            if e.rc == ENOENT:
                return
            raise
        pos = 0
        entries = []
        while pos + _FRAME.size <= len(raw):
            (n,) = _FRAME.unpack_from(raw, pos)
            pos += _FRAME.size
            if pos + n > len(raw):
                break                    # torn tail
            try:
                entries.append(decode(raw[pos:pos + n]))
            except (ValueError, TypeError):
                break
            pos += n
        lo = self._ino_floor()
        hi = (self.rank + 1) * RANK_INO_BASE if self.rank \
            else RANK_INO_BASE
        for e in entries:
            ino = int(e.get("ino", 0))
            # only inos from OUR partition move the watermark: a journal
            # entry touching a foreign rank's inode (e.g. an unlink
            # after an export round trip) must not teleport this rank's
            # allocator into that partition (duplicate ino allocation)
            if lo <= ino < hi and ino >= self.next_ino:
                self.next_ino = ino + 1
            try:
                await self._apply(e)
            except (RadosError, MDSError) as err:
                log.derr("%s: journal replay of %s failed: %s",
                         self.entity, e.get("op"), err)
        # dangling cross-rank rename intents are only COLLECTED here;
        # resolution waits for _resync (post rank assignment) — a
        # freshly booting daemon replays with the DEFAULT rank and
        # must not abort a live rank's in-flight renames
        self._open_intents = {}
        for e in entries:
            op = e.get("op")
            token = str(e.get("token", ""))
            if op in ("rename_export_intent", "link_export_intent",
                      "unlink_remote_intent",
                      "promote_export_intent", "repoint_intent"):
                self._open_intents[token] = e
            elif op in ("rename_export_finish",
                        "rename_export_abort",
                        "link_export_finish", "link_export_abort",
                        "unlink_remote_finish",
                        "unlink_remote_abort",
                        "promote_export_finish",
                        "promote_export_abort",
                        "repoint_finish", "repoint_abort"):
                self._open_intents.pop(token, None)
        if entries:
            await self._compact_journal()

    async def _repair_rename_intents(self) -> None:
        """Resolve dangling cross-rank rename intents (run from
        _resync, once THIS daemon's rank assignment is known): the
        atomic COMMIT MARKER decides — not the destination dirfrag's
        current state, which later unlinks/renames at the still-live
        destination rank could flip.  Committed: complete the source
        unlink.  Not committed: the abort-unless-committed claim wins
        the race against a still-queued import, roll back."""
        import json as _json

        for token, e in list(self._open_intents.items()):
            op = str(e.get("op"))
            ino = int(e.get("ino", 0))
            committed = await self._rename_resolve_abort(token)
            if not committed:
                abort_op = {"rename_export_intent":
                            "rename_export_abort",
                            "link_export_intent": "link_export_abort",
                            "unlink_remote_intent":
                            "unlink_remote_abort",
                            "promote_export_intent":
                            "promote_export_abort",
                            "repoint_intent": "repoint_abort"}[op]
                await self._journal({"op": abort_op, "ino": ino,
                                     **{k: e[k] for k in
                                        ("src_parent", "src_name")
                                        if k in e},
                                     "token": token})
                continue
            if op == "rename_export_intent":
                fin = {"op": "rename_export_finish",
                       "src_parent": int(e["src_parent"]),
                       "src_name": str(e["src_name"]), "ino": ino,
                       "token": token}
            elif op == "link_export_intent":
                # the destination materialized the remote name before
                # the crash: rebuild the finish from CURRENT primary
                # state (it was never incremented — the finish is what
                # increments, and a journaled finish clears the intent)
                pp, pn = int(e["pp"]), str(e["pn"])
                primary = dict(await self._get_dentry(pp, pn))
                primary["nlink"] = int(primary.get("nlink", 1)) + 1
                rec = await self._anchor_get(ino)
                base = rec or {"primary": [pp, pn], "remotes": []}
                fin = {"op": "link_export_finish", "pp": pp, "pn": pn,
                       "ino": ino, "primary_dentry": primary,
                       "anchor": await self._anchor_next(ino, {
                           "primary": base["primary"],
                           "remotes": list(base["remotes"])
                           + [[int(e["parent"]),
                               str(e["name"])]]}),
                       "token": token}
            elif op == "promote_export_intent":
                # the remote's rank adopted the primary before the
                # crash: drop our old primary NAME (never the data)
                fin = {"op": "promote_export_finish",
                       "parent": int(e["parent"]),
                       "name": str(e["name"]), "ino": ino,
                       "token": token}
            elif op == "repoint_intent":
                # the primary's rank repointed the anchor before the
                # crash: complete the name move
                fin = {"op": "repoint_finish",
                       "src_parent": int(e["src_parent"]),
                       "src_name": str(e["src_name"]),
                       "dst_parent": int(e["dst_parent"]),
                       "dst_name": str(e["dst_name"]), "ino": ino,
                       "dentry": dict(e["dentry"]), "token": token,
                       "pre": e.get("pre"),
                       "purge_ino": int(e.get("purge_ino", 0)),
                       "purge_size": int(e.get("purge_size", 0))}
            else:                       # unlink_remote_intent
                fin = {"op": "unlink_remote_finish",
                       "parent": int(e["parent"]),
                       "name": str(e["name"]), "ino": ino,
                       "token": token}
            await self._journal(fin)
            await self._apply(fin)
            await self._rename_clear(token)
            log.dout(1, "%s: completed dangling %s (token %s)",
                     self.entity, op, token)
        # sweep long-dead markers (aborts whose import never arrived,
        # commits re-created by a destination replay)
        try:
            await self.meta.exec(
                RENAME_LOG_OID, "rename_wal", "gc",
                _json.dumps({"max_age": 3600.0}).encode())
        except RadosError:
            pass

    async def _journal(self, entry: dict) -> None:
        if fp.ACTIVE:
            await fp.fire("mds.journal_flush")
        payload = encode(entry)
        await self.meta.append(self._journal_oid,
                               _FRAME.pack(len(payload)) + payload)
        self.journal_len += 1
        op = entry.get("op")
        if op in ("rename_export_intent", "link_export_intent",
                  "unlink_remote_intent", "promote_export_intent",
                  "repoint_intent"):
            self._open_intents[str(entry.get("token", ""))] = entry
        elif op in ("rename_export_finish", "rename_export_abort",
                    "link_export_finish", "link_export_abort",
                    "unlink_remote_finish", "unlink_remote_abort",
                    "promote_export_finish", "promote_export_abort",
                    "repoint_finish", "repoint_abort"):
            self._open_intents.pop(str(entry.get("token", "")), None)

    async def _maybe_compact(self) -> None:
        """Roll the journal when it has grown past the apply window
        (every mutation is applied synchronously, so anything beyond
        open intents is dead weight)."""
        if self.journal_len >= 256:
            await self._compact_journal()

    async def _compact_journal(self) -> None:
        """Everything is applied synchronously under the mutate lock, so
        compaction persists the ino watermark and resets the log (the
        journal-expire + InoTable save) — EXCEPT open cross-rank rename
        intents, which are rewritten into the fresh log: destroying a
        dangling intent would disarm the replay repair it exists for."""
        if self.meta is None:
            return
        await self.meta.operate(TABLE_OID, ObjectOperation()
                                .create()
                                .set_xattr(self._table_key,
                                           str(self.next_ino).encode()))
        keep = b""
        for e in self._open_intents.values():
            raw = encode(e)
            keep += _FRAME.pack(len(raw)) + raw
        try:
            await self.meta.operate(self._journal_oid,
                                    ObjectOperation().write_full(keep))
        except RadosError:
            pass
        self.journal_len = len(self._open_intents)

    # -- dirfrag helpers ---------------------------------------------------
    async def _fragtree(self, dino: int,
                        refresh: bool = False) -> list[tuple[int, int]]:
        """Per-ino fragtree cache (the CInode dirfragtree role):
        invalidated on local split/merge/removal and wherever the
        auth map changes (an importing rank must re-learn trees the
        exporter reshaped).  ``refresh`` forces a re-read — the read
        paths use it to close the lock-free race with a concurrent
        split/merge."""
        if not refresh:
            t = self._ftree_cache.get(dino)
            if t is not None:
                return t
        t = await fragtree_of(self.meta, dino)
        if len(self._ftree_cache) > 65536:
            self._ftree_cache.clear()
        self._ftree_cache[dino] = t
        return t

    async def _dir_all(self, dino: int) -> dict[str, bytes]:
        """Union of all live dirfrag omaps (the readdir/scrub/empty-
        check view).  Raises RadosError ENOENT exactly when the
        directory's base object is gone (same contract the single-
        object layout had).  A frag ENOENT mid-walk means a concurrent
        split/merge retired the object after we read the tree: re-read
        the tree and restart (bounded)."""
        for attempt in range(3):
            tree = await self._fragtree(dino, refresh=attempt > 0)
            if tree == [ROOT_FRAG]:
                return await self.meta.get_omap(dirfrag_oid(dino))
            out: dict[str, bytes] = {}
            stale = False
            for b, v in tree:
                try:
                    out.update(await self.meta.get_omap(
                        frag_oid(dino, b, v)))
                except RadosError as e:
                    if e.rc != ENOENT:
                        raise
                    stale = True
                    break
            if not stale:
                return out
        # tree still names a missing frag object: a crashed split's
        # hole (scrub's territory) — serve what exists
        out = {}
        for b, v in tree:
            try:
                out.update(await self.meta.get_omap(
                    frag_oid(dino, b, v)))
            except RadosError as e:
                if e.rc != ENOENT:
                    raise
        return out

    async def _get_dentry(self, parent: int, name: str,
                          snapid: int = 0) -> dict:
        if snapid:
            kv = await self._snap_view(parent, snapid, [name])
        else:
            kv = None
            for attempt in range(3):
                tree = await self._fragtree(parent,
                                            refresh=attempt > 0)
                trivial = tree == [ROOT_FRAG]
                oid = frag_oid(parent, *frag_for(tree, name))
                try:
                    kv = await self.meta.get_omap(oid, [name])
                    break
                except RadosError as e:
                    if e.rc != ENOENT:
                        raise
                    if trivial:
                        raise MDSError(ENOENT, f"no dir {parent:x}")
                    # fragmented dir: ENOENT here usually means a
                    # concurrent split/merge retired this frag after
                    # the (cached) tree read — retry with a fresh
                    # tree; if it persists, the name is absent (the
                    # base object, our liveness witness, just served
                    # the fragtree)
                    kv = {}
        if name not in kv and not snapid:
            # name miss through a CACHED tree: a split/merge since the
            # cache fill may have moved the name to a sibling frag that
            # still exists (no ENOENT to trip the retry above) — one
            # forced re-read before declaring the name absent
            fresh = await self._fragtree(parent, refresh=True)
            if frag_for(fresh, name) != frag_for(tree, name):
                oid = frag_oid(parent, *frag_for(fresh, name))
                try:
                    kv = await self.meta.get_omap(oid, [name])
                except RadosError as e:
                    if e.rc != ENOENT:
                        raise
                    kv = {}
        if name not in kv:
            raise MDSError(ENOENT, f"{name!r} not in {parent:x}",
                           missing_dentry=True)
        return decode(kv[name])

    async def _snap_view(self, dino: int, snapid: int,
                         names: list[str] | None = None) -> dict:
        """A directory's omap AS OF a snapshot: the frozen COW copy when
        one exists (the dirfrag diverged since the snap), else the live
        dirfrag (unchanged since — reference SnapRealm resolution).
        Frozen copies are single-object; the live fallback routes
        through the fragtree."""
        try:
            return await self.meta.get_omap(
                snap_dirfrag_oid(dino, snapid), names)
        except RadosError as e:
            if e.rc != ENOENT:
                raise
        try:
            if names is None:
                return await self._dir_all(dino)
            tree = await self._fragtree(dino)
            if tree == [ROOT_FRAG]:
                return await self.meta.get_omap(dirfrag_oid(dino),
                                                names)
            out: dict[str, bytes] = {}
            groups: dict[tuple[int, int], list[str]] = {}
            for n in names:
                groups.setdefault(frag_for(tree, n), []).append(n)
            for (b, v), ns in groups.items():
                try:
                    out.update(await self.meta.get_omap(
                        frag_oid(dino, b, v), ns))
                except RadosError as e2:
                    if e2.rc != ENOENT:
                        raise
            return out
        except RadosError as e:
            raise MDSError(ENOENT, f"no dir {dino:x}") \
                if e.rc == ENOENT else e

    async def _set_dentry(self, parent: int, name: str,
                          dentry: dict) -> None:
        # writing into a directory OUTSIDE this rank's subtrees (a
        # cross-rank rename destination import, replay of a foreign
        # chain): the owning rank may have split/merged the tree
        # without our invalidation hooks firing — force a re-read so
        # the dentry lands in a live frag, not a retired one
        foreign = (await self._auth_rank(parent)) != self.rank
        tree = await self._fragtree(parent, refresh=foreign)
        b, v = frag_for(tree, name)
        oid = frag_oid(parent, b, v)
        # counts track ENTRIES, not operations: an overwrite (setattr,
        # journal-replay re-apply) must not move the split trigger
        try:
            existed = name in await self.meta.get_omap(oid, [name])
        except RadosError as e:
            if e.rc != ENOENT:
                raise
            existed = False
        await self.meta.operate(oid, ObjectOperation()
                                .create()
                                .omap_set({name: encode(dentry)}))
        if not existed:
            await self._frag_note_add(parent, b, v)

    # -- dirfrag split/merge (CDir.cc:994 split / :1096 merge) -------------
    async def _frag_count(self, dino: int, b: int, v: int) -> int:
        """Cached entry count of one frag (initialized by one omap
        read, then maintained incrementally — the reference keeps the
        same count in CDir::fnode fragstat)."""
        key = (dino, b, v)
        c = self._frag_counts.get(key)
        if c is None:
            try:
                c = len(await self.meta.get_omap(frag_oid(dino, b, v)))
            except RadosError as e:
                if e.rc != ENOENT:
                    raise
                c = 0
            self._frag_counts[key] = c
        return c

    async def _frag_note_add(self, dino: int, b: int, v: int) -> None:
        c = await self._frag_count(dino, b, v)
        self._frag_counts[(dino, b, v)] = c = c + 1
        split_bits = int(self.conf["mds_bal_split_bits"])
        if c > int(self.conf["mds_bal_split_size"]) \
                and b + split_bits <= MAX_FRAG_BITS:
            entry = {"op": "fragment", "ino": dino, "bits": b,
                     "value": v, "nbits": split_bits}
            await self._journal(entry)
            await self._apply(entry)

    async def _frag_note_rm(self, dino: int, b: int, v: int) -> None:
        key = (dino, b, v)
        if key in self._frag_counts:
            self._frag_counts[key] = max(0, self._frag_counts[key] - 1)
        if b == 0:
            return
        # merge check: this frag and its sibling together below the
        # merge threshold -> fold back into the parent frag
        sib = (b, v ^ 1)
        tree = await self._fragtree(dino)
        if (b, v) not in tree or sib not in tree:
            return                    # sibling further split: no merge
        total = await self._frag_count(dino, b, v) \
            + await self._frag_count(dino, *sib)
        if total < int(self.conf["mds_bal_merge_size"]):
            entry = {"op": "fragment", "ino": dino, "bits": b - 1,
                     "value": v >> 1, "nbits": -1}
            await self._journal(entry)
            await self._apply(entry)

    async def _apply_fragment(self, dino: int, b: int, v: int,
                              nb: int) -> None:
        """Idempotent split (nb>0: frag (b,v) -> 2^nb children) or
        merge (nb<0: children of (b,v) -> (b,v)).  Journal-replayable:
        a crash between any two steps re-runs to the same state, and a
        completed entry's re-apply only re-runs the source cleanup."""
        from ceph_tpu.placement.hashing import ceph_str_hash_rjenkins

        tree = await self._fragtree(dino)
        if nb > 0:
            children = [(b + nb, (v << nb) + i) for i in range(1 << nb)]
            if (b, v) not in tree:
                # already applied; finish the (idempotent) source
                # cleanup a crash may have cut off
                await self._frag_cleanup(dino, b, v)
                return
            try:
                kv = await self.meta.get_omap(frag_oid(dino, b, v))
            except RadosError as e:
                if e.rc != ENOENT:
                    raise
                kv = {}
            parts: dict[tuple[int, int], dict] = {c: {} for c in children}
            shift = 32 - (b + nb)
            for name, raw in kv.items():
                h = ceph_str_hash_rjenkins(name)
                parts[(b + nb, h >> shift)][name] = raw
            for c, ckv in parts.items():
                op = ObjectOperation().create()
                if ckv:
                    op.omap_set(ckv)
                await self.meta.operate(frag_oid(dino, *c), op)
            newtree = sorted([t for t in tree if t != (b, v)]
                             + children)
            await self.meta.operate(
                dirfrag_oid(dino), ObjectOperation().create().set_xattr(
                    "fragtree", encode([list(t) for t in newtree])))
            await self._frag_cleanup(dino, b, v, keys=list(kv))
        else:
            children = [(b + 1, (v << 1) + i) for i in (0, 1)]
            if not all(c in tree for c in children):
                for c in children:       # completed: re-run cleanup
                    if c not in tree:
                        await self._frag_cleanup(dino, *c)
                return
            union: dict[str, bytes] = {}
            for c in children:
                try:
                    union.update(await self.meta.get_omap(
                        frag_oid(dino, *c)))
                except RadosError as e:
                    if e.rc != ENOENT:
                        raise
            op = ObjectOperation().create()
            if union:
                op.omap_set(union)
            await self.meta.operate(frag_oid(dino, b, v), op)
            newtree = sorted([t for t in tree if t not in children]
                             + [(b, v)])
            if newtree == [ROOT_FRAG]:
                newtree = []             # trivial tree: drop the xattr
            await self.meta.operate(
                dirfrag_oid(dino), ObjectOperation().create().set_xattr(
                    "fragtree", encode([list(t) for t in newtree])))
            for c in children:
                await self._frag_cleanup(dino, *c)
        # stale counters and the cached tree die with the old layout
        for key in [k for k in self._frag_counts if k[0] == dino]:
            del self._frag_counts[key]
        self._ftree_cache.pop(dino, None)

    async def _frag_cleanup(self, dino: int, b: int, v: int,
                            keys: list[str] | None = None) -> None:
        """Remove a retired source frag.  The base object (frag 0/0)
        is never removed — it carries the fragtree/parent xattrs — its
        omap entries are cleared instead."""
        if (b, v) == ROOT_FRAG:
            if keys is None:
                try:
                    keys = list(await self.meta.get_omap(
                        dirfrag_oid(dino)))
                except RadosError as e:
                    if e.rc != ENOENT:
                        raise
                    return
            if keys:
                try:
                    await self.meta.operate(
                        dirfrag_oid(dino),
                        ObjectOperation().omap_rm(keys))
                except RadosError as e:
                    if e.rc != ENOENT:
                        raise
            return
        try:
            await self.meta.remove(frag_oid(dino, b, v))
        except RadosError as e:
            if e.rc != ENOENT:
                raise

    async def _remove_dir_objects(self, ino: int) -> None:
        """Remove every object of a dying directory (rmdir / replaced-
        empty-dir purge): all fragtree leaves, then the base."""
        for b, v in await self._fragtree(ino):
            if (b, v) != ROOT_FRAG:
                await self._frag_cleanup(ino, b, v)
        try:
            await self.meta.remove(dirfrag_oid(ino))
        except RadosError as e:
            if e.rc != ENOENT:
                raise
        for key in [k for k in self._frag_counts if k[0] == ino]:
            del self._frag_counts[key]
        self._ftree_cache.pop(ino, None)

    # -- snap realms (COW; reference src/mds/SnapRealm.h) ------------------
    # mksnap records ONLY the realm (snapid, root ino) — O(1).  The cost
    # moves to the first post-snap mutation of each dirfrag: _cow_freeze
    # copies the pre-mutation omap to the snap suffix exactly once
    # (exclusive create), and snapshot reads resolve frozen-else-live
    # (_snap_view).  A directory renamed out of a realm keeps its
    # membership through a "past_snaps" xattr (the realm past_parents
    # role), merged along the ancestry walk.
    async def _parent_chain(self, dino: int) -> list[int]:
        chain = [dino]
        cur = dino
        hops = 0
        while cur != ROOT_INO and hops < 4096:
            try:
                raw = await self.meta.get_xattr(dirfrag_oid(cur),
                                                "parent")
            except RadosError:
                break
            cur = int(raw)
            chain.append(cur)
            hops += 1
        return chain

    async def _covering_snaps(self, dino: int) -> list[int]:
        """Live snapids whose realm covers directory ``dino``: realm
        root on the ancestry chain, or sticky past_snaps membership
        recorded on any chain member at rename time."""
        if not self.snaps:
            return []
        chain = await self._parent_chain(dino)
        chain_set = set(chain)
        covered = {sid for sid, info in self.snaps.items()
                   if int(info["ino"]) in chain_set}
        remaining = set(self.snaps) - covered
        if remaining:
            for link in chain:
                if not remaining:
                    break
                try:
                    raw = await self.meta.get_xattr(
                        dirfrag_oid(link), "past_snaps")
                except RadosError:
                    continue
                sticky = {int(s) for s in decode(raw)}
                covered |= sticky & remaining
                remaining -= sticky
        return sorted(covered)

    async def _cow_freeze(self, dino: int) -> None:
        """Copy ``dino``'s live dirfrag to every covering snapshot that
        has no frozen copy yet — called BEFORE any mutation of that
        dirfrag.  Idempotent (exclusive create: the first, pre-mutation
        freeze wins), so journal replay re-running a mutation cannot
        re-freeze post-mutation state."""
        if not self.snaps:
            return
        for snapid in await self._covering_snaps(dino):
            oid = snap_dirfrag_oid(dino, snapid)
            try:
                await self.meta.stat(oid)
                continue                      # already frozen
            except RadosError as e:
                if e.rc != ENOENT:
                    raise
            try:
                kv = await self._dir_all(dino)
            except RadosError as e:
                if e.rc != ENOENT:
                    raise
                return                        # no dirfrag to freeze
            frozen: dict[str, bytes] = {}
            for dname, raw in kv.items():
                de = decode(raw)
                if de.get("remote"):
                    # hard-link stubs carry no inode attrs; freeze the
                    # inode resolved AT THE SNAPID — any post-snap attr
                    # change froze the primary's dirfrag first, so the
                    # snap-view resolution returns as-of-snap attrs
                    try:
                        de = dict(await self._resolve_remote(de,
                                                             snapid))
                        de.pop("remote", None)
                    except MDSError:
                        pass                  # racing unlink: keep stub
                frozen[dname] = encode(de)
            op = ObjectOperation().create(exclusive=True)
            if frozen:
                op.omap_set(frozen)
            try:
                await self.meta.operate(oid, op)
            except RadosError as e:
                if e.rc != EEXIST:
                    raise

    # -- mutation application (idempotent; journal replay re-runs these) --
    async def _rm_dentry(self, parent: int, name: str) -> None:
        """Remove one dentry, tolerating an absent dirfrag (journal
        replay re-applies removals idempotently)."""
        tree = await self._fragtree(parent)
        b, v = frag_for(tree, name)
        oid = frag_oid(parent, b, v)
        try:
            existed = name in await self.meta.get_omap(oid, [name])
            if existed:
                await self.meta.operate(
                    oid, ObjectOperation().omap_rm([name]))
        except RadosError as err:
            if err.rc != ENOENT:
                raise
            return
        if existed:
            await self._frag_note_rm(parent, b, v)

    async def _apply(self, e: dict) -> None:
        op = e["op"]
        # COW-freeze every dirfrag this op mutates BEFORE mutating it
        # (snapshot views then resolve frozen-else-live)
        for key in ("parent", "src_parent", "dst_parent", "pp", "np"):
            if key in e:
                await self._cow_freeze(int(e[key]))
        if op == "rmdir":
            await self._cow_freeze(int(e["ino"]))       # doomed dirfrag
        if op == "rename" and int(e.get("purge_dir_ino", 0)):
            await self._cow_freeze(int(e["purge_dir_ino"]))
        if op == "fragment":
            await self._apply_fragment(int(e["ino"]), int(e["bits"]),
                                       int(e["value"]),
                                       int(e["nbits"]))
        elif op in ("mkdir", "create"):
            dentry = dict(e["dentry"])
            await self._set_dentry(int(e["parent"]), str(e["name"]),
                                   dentry)
            if op == "create":
                await self._write_backtrace(int(e["ino"]),
                                            int(e["parent"]),
                                            str(e["name"]), dentry)
            if op == "mkdir":
                # the dirfrag carries a parent back-pointer so rename
                # can walk ancestors (cycle detection)
                await self.meta.operate(
                    dirfrag_oid(int(e["ino"])),
                    ObjectOperation().create().set_xattr(
                        "parent", str(int(e["parent"])).encode()
                    ),
                )
        elif op == "unlink":
            await self._rm_dentry(int(e["parent"]),
                                  str(e["name"]))
            await self._purge_file(int(e["ino"]), int(e.get("size", 0)))
        elif op == "rmdir":
            await self._rm_dentry(int(e["parent"]),
                                  str(e["name"]))
            await self._remove_dir_objects(int(e["ino"]))
            await self._quota_drop(int(e["ino"]))
        elif op == "rename":
            dentry = dict(e["dentry"])
            await self._rm_dentry(int(e["src_parent"]),
                                  str(e["src_name"]))
            await self._set_dentry(int(e["dst_parent"]),
                                   str(e["dst_name"]), dentry)
            if dentry.get("type") in ("file", "symlink") \
                    and not dentry.get("remote"):
                await self._write_backtrace(int(dentry["ino"]),
                                            int(e["dst_parent"]),
                                            str(e["dst_name"]),
                                            dentry)
            if dentry.get("type") == "dir":
                # moved directory: ancestry chains changed
                self._auth_cache.clear()
                self._ftree_cache.clear()
                # refresh its parent back-pointer
                op_x = ObjectOperation().create().set_xattr(
                    "parent", str(int(e["dst_parent"])).encode()
                )
                merged = {int(s) for s in e.get("past_snaps", ())}
                if merged:
                    # sticky realm membership (SnapRealm past_parents)
                    try:
                        raw = await self.meta.get_xattr(
                            dirfrag_oid(int(dentry["ino"])),
                            "past_snaps")
                        merged |= {int(s) for s in decode(raw)}
                    except RadosError:
                        pass
                    op_x.set_xattr("past_snaps",
                                   encode(sorted(merged)))
                await self.meta.operate(
                    dirfrag_oid(int(dentry["ino"])), op_x)
            if int(e.get("purge_ino", 0)):
                await self._purge_file(int(e["purge_ino"]),
                                       int(e.get("purge_size", 0)))
            if int(e.get("purge_dir_ino", 0)):
                # a replaced empty directory leaves its dirfrags behind
                await self._remove_dir_objects(int(e["purge_dir_ino"]))
                await self._quota_drop(int(e["purge_dir_ino"]))
            if int(e.get("anchor_ino", 0)):
                await self._anchor_put(int(e["anchor_ino"]),
                                       e.get("anchor"))
        elif op == "import_dentry":
            # cross-rank rename, destination half.  The ATOMIC commit
            # claim gates the link — in the live path AND on journal
            # replay: a crash after journaling but before apply leaves
            # the claim unmade, the source's timeout wins the abort,
            # and the replayed entry must then link NOTHING (or the
            # file would exist under both names).  The marker is
            # durable even if the dentry is later unlinked/renamed —
            # it is what timeout resolution and replay repair consult.
            ok = True
            if e.get("token"):
                ok = await self._rename_mark_commit(str(e["token"]))
            if ok:
                if e.get("pre"):
                    await self._apply(dict(e["pre"]))
                await self._set_dentry(int(e["parent"]),
                                       str(e["name"]),
                                       dict(e["dentry"]))
                de_imp = dict(e["dentry"])
                if de_imp.get("type") in ("file", "symlink") \
                        and not de_imp.get("remote"):
                    await self._write_backtrace(int(de_imp["ino"]),
                                                int(e["parent"]),
                                                str(e["name"]),
                                                de_imp)
                if dict(e["dentry"]).get("type") == "dir":
                    # imported directory: its ancestry chain now runs
                    # through THIS rank's territory — refresh the
                    # back-pointer and drop stale auth resolutions
                    await self.meta.operate(
                        dirfrag_oid(int(e["ino"])),
                        ObjectOperation().create().set_xattr(
                            "parent", str(int(e["parent"])).encode()
                        ),
                    )
                    self._auth_cache.clear()
                    self._ftree_cache.clear()
                if int(e.get("anchor_ino", 0)):
                    # hardlinked primary imported from another rank:
                    # the anchor's primary pointer follows the inode
                    # under the same commit claim (versioned write —
                    # replay-safe from either rank's journal)
                    await self._anchor_put(int(e["anchor_ino"]),
                                           e.get("anchor"))
                if int(e.get("purge_dir_ino", 0)):
                    await self._remove_dir_objects(
                        int(e["purge_dir_ino"]))
                if int(e.get("purge_ino", 0)):
                    await self._purge_file(int(e["purge_ino"]),
                                           int(e.get("purge_size",
                                                     0)))
        elif op == "rename_export_finish":
            # cross-rank rename, source half: drop the exported name
            # only — the inode lives on under the destination rank
            await self._rm_dentry(int(e["src_parent"]),
                                  str(e["src_name"]))
            # an exported DIRECTORY's descendants now resolve through
            # the destination's chain; cached auths are stale
            self._auth_cache.clear()
            self._ftree_cache.clear()
            self._quota_invalidate()
        elif op in ("rename_export_intent", "rename_export_abort",
                    "link_export_intent", "link_export_abort",
                    "unlink_remote_intent", "unlink_remote_abort",
                    "promote_export_intent", "promote_export_abort",
                    "repoint_intent", "repoint_abort"):
            pass          # journal markers; resolved by replay repair
        elif op == "repoint_remote":
            # remote-name rename, primary-rank half (claim-gated):
            # the anchor's remotes list swaps the old name for the new
            ok = True
            if e.get("token"):
                ok = await self._rename_mark_commit(str(e["token"]))
            if ok:
                await self._anchor_put(int(e["ino"]),
                                       dict(e["anchor"]))
        elif op == "repoint_finish":
            # remote-name rename, name half: the replaced destination
            # (if any) tears down FIRST — it rides inside this entry
            # so an aborted repoint never unlinked it
            if e.get("pre"):
                await self._apply(dict(e["pre"]))
            await self._rm_dentry(int(e["src_parent"]),
                                  str(e["src_name"]))
            await self._set_dentry(int(e["dst_parent"]),
                                   str(e["dst_name"]),
                                   dict(e["dentry"]))
            if int(e.get("purge_ino", 0)):
                await self._purge_file(int(e["purge_ino"]),
                                       int(e.get("purge_size", 0)))
        elif op == "import_link":
            # cross-rank link, destination half: the commit claim
            # gates the remote dentry exactly like import_dentry
            ok = True
            if e.get("token"):
                ok = await self._rename_mark_commit(str(e["token"]))
            if ok:
                await self._set_dentry(int(e["parent"]),
                                       str(e["name"]),
                                       dict(e["remote_dentry"]))
        elif op == "link_export_finish":
            # cross-rank link, primary half: nlink + anchor land only
            # after the destination's commit is known (idempotent
            # absolute writes on replay)
            await self._set_dentry(int(e["pp"]), str(e["pn"]),
                                   dict(e["primary_dentry"]))
            await self._anchor_put(int(e["ino"]), dict(e["anchor"]))
        elif op == "update_primary":
            # cross-rank remote-unlink, primary half (claim-gated)
            ok = True
            if e.get("token"):
                ok = await self._rename_mark_commit(str(e["token"]))
            if ok:
                await self._set_dentry(int(e["pp"]), str(e["pn"]),
                                       dict(e["primary_dentry"]))
                await self._anchor_put(int(e["ino"]), e.get("anchor"))
        elif op == "unlink_remote_finish":
            # cross-rank remote-unlink, name half: drop the remote
            # dentry only — the primary's rank already adjusted
            # nlink/anchor under the commit claim
            await self._rm_dentry(int(e["parent"]),
                                  str(e["name"]))
        elif op == "import_promoted":
            # cross-rank promotion, remote-name half (claim-gated):
            # the remote dentry becomes the inode's primary and the
            # anchor moves with it
            ok = True
            if e.get("token"):
                ok = await self._rename_mark_commit(str(e["token"]))
            if ok:
                await self._set_dentry(int(e["parent"]),
                                       str(e["name"]),
                                       dict(e["primary_dentry"]))
                await self._anchor_put(int(e["ino"]), e.get("anchor"))
                # a stale backtrace would let data-scan resurrect the
                # deleted old primary name (promote_link parity)
                await self._write_backtrace(int(e["ino"]),
                                            int(e["parent"]),
                                            str(e["name"]),
                                            dict(e["primary_dentry"]))
        elif op == "promote_export_finish":
            # cross-rank promotion, old-primary half: drop the NAME
            # only — the inode lives on under the promoted primary
            await self._rm_dentry(int(e["parent"]),
                                  str(e["name"]))
        elif op == "setattr":
            await self._set_dentry(int(e["parent"]), str(e["name"]),
                                   dict(e["dentry"]))
        elif op == "setquota":
            ino = int(e["ino"])
            q = {"max_bytes": int(e["max_bytes"]),
                 "max_files": int(e["max_files"])}
            if not q["max_bytes"] and not q["max_files"]:
                # create() first: clearing against a never-created
                # table object must be a no-op, and the clear must
                # reach the TABLE even when this rank's cache is
                # stale (a realm root imported from another rank)
                await self.meta.operate(
                    QUOTATABLE_OID, ObjectOperation().create()
                    .omap_rm([str(ino)]))
                self.quotas.pop(ino, None)
                self._qusage.pop(ino, None)
            else:
                await self.meta.operate(
                    QUOTATABLE_OID, ObjectOperation().create()
                    .omap_set({str(ino): encode(q)}))
                self.quotas[ino] = q
        elif op == "mksnap":
            await self.meta.operate(SNAPTABLE_OID, ObjectOperation()
                                    .create().omap_set({
                                        str(int(e["snapid"])):
                                        encode(dict(e["info"])),
                                    }))
            self.snaps[int(e["snapid"])] = dict(e["info"])
            self._apply_snapc()
        elif op == "rmsnap":
            # cleanup lives HERE so journal replay after a crash
            # re-runs it (idempotent: removals tolerate ENOENT); the
            # walk follows the snapshot VIEW (frozen-else-live), so a
            # directory renamed out of the subtree after mksnap is
            # still reachable through its frozen parent, and dirfrags
            # that never diverged have nothing to remove
            snapid = int(e["snapid"])
            queue = [int(e["ino"])]
            seen = set()
            while queue:
                dino = queue.pop()
                if dino in seen:
                    continue
                seen.add(dino)
                try:
                    kv = await self._snap_view(dino, snapid)
                except MDSError:
                    kv = {}
                for raw in kv.values():
                    de = decode(raw)
                    if de.get("type") == "dir":
                        queue.append(int(de["ino"]))
                try:
                    await self.meta.remove(
                        snap_dirfrag_oid(dino, snapid))
                except RadosError as err:
                    if err.rc != ENOENT:
                        raise
            try:
                await self.data.selfmanaged_snap_remove(snapid)
            except (RadosError, KeyError, ValueError):
                pass              # already trimmed on a replay
            try:
                await self.meta.operate(
                    SNAPTABLE_OID,
                    ObjectOperation().omap_rm([str(snapid)]),
                )
            except RadosError as err:
                if err.rc != ENOENT:
                    raise
            self.snaps.pop(snapid, None)
            self._apply_snapc()
        elif op == "link":
            await self._set_dentry(int(e["parent"]), str(e["name"]),
                                   dict(e["remote_dentry"]))
            await self._set_dentry(int(e["pp"]), str(e["pn"]),
                                   dict(e["primary_dentry"]))
            await self._anchor_put(int(e["ino"]), dict(e["anchor"]))
        elif op == "unlink_remote":
            await self._rm_dentry(int(e["parent"]),
                                  str(e["name"]))
            await self._set_dentry(int(e["pp"]), str(e["pn"]),
                                   dict(e["primary_dentry"]))
            await self._anchor_put(int(e["ino"]), e.get("anchor"))
            await self._write_backtrace(int(e["ino"]), int(e["pp"]),
                                        str(e["pn"]),
                                        dict(e["primary_dentry"]))
        elif op == "promote_link":
            await self._rm_dentry(int(e["parent"]),
                                  str(e["name"]))
            await self._set_dentry(int(e["np"]), str(e["nn"]),
                                   dict(e["primary_dentry"]))
            await self._anchor_put(int(e["ino"]), e.get("anchor"))
            # the primary dentry moved: a stale backtrace would let a
            # data-scan inject resurrect the DELETED old name
            await self._write_backtrace(int(e["ino"]), int(e["np"]),
                                        str(e["nn"]),
                                        dict(e["primary_dentry"]))

    async def _purge_file(self, ino: int, size: int) -> None:
        """Delete a file's data objects (the PurgeQueue role, inline)."""
        if ino <= 0:
            return
        nblocks = max(1, -(-size // self.block_size))
        for b in range(nblocks):
            try:
                await self.data.remove(block_oid(ino, b))
            except RadosError as e:
                if e.rc != ENOENT:
                    raise
        try:
            await self.data.remove(backtrace_oid(ino))
        except RadosError as e:
            if e.rc != ENOENT:
                raise

    # -- hard links (remote dentries + the reference's anchortable) -------
    # The inode stays EMBEDDED in one primary dentry; other names are
    # remote dentries {"remote": True, "ino": N}.  While nlink > 1 the
    # anchortable omap maps ino -> {"primary": [p, n], "remotes":
    # [[p, n], ...]} so remotes resolve and unlink can promote
    # (reference src/mds/AnchorTable-era design, kept as server state).
    async def _anchor_get_raw(self, ino: int) -> dict | None:
        """The stored record, tombstones included (version source)."""
        try:
            kv = await self.meta.get_omap(ANCHOR_OID, [str(ino)])
        except RadosError as e:
            if e.rc == ENOENT:
                return None
            raise
        return decode(kv[str(ino)]) if str(ino) in kv else None

    async def _anchor_get(self, ino: int) -> dict | None:
        rec = await self._anchor_get_raw(ino)
        return None if rec is None or rec.get("dead") else rec

    async def _anchor_next(self, ino: int,
                           new: dict | None) -> dict:
        """The next anchor state, version-stamped at PLAN time so a
        journal replay re-applies exactly the version it applied live.
        Anchors are written from MORE THAN ONE rank's journal (the
        primary moves ranks on cross-rank promotion), so replay-
        ordering cannot come from one journal's sequence — it comes
        from the record version: _anchor_put keeps the newest write,
        and deletion is a versioned TOMBSTONE (the version must keep
        counting across delete/recreate cycles, so the raw stored
        record — dead or live — is the version source)."""
        raw = await self._anchor_get_raw(ino)
        v = (int(raw.get("v", 0)) if raw else 0) + 1
        if new is None:
            return {"dead": True, "v": v}
        return {**new, "v": v}

    async def _anchor_put(self, ino: int, rec: dict | None) -> None:
        raw = await self._anchor_get_raw(ino)
        cur_v = int(raw.get("v", 0)) if raw else 0
        if rec is None:
            rec = {"dead": True, "v": cur_v + 1}
        elif "v" not in rec:
            rec = {**rec, "v": cur_v + 1}     # unplanned (scrub) write
        elif int(rec["v"]) <= cur_v:
            return        # stale replayed write: a newer state landed
        await self.meta.operate(
            ANCHOR_OID, ObjectOperation().create()
            .omap_set({str(ino): encode(rec)}))

    async def _primary_of(self, ino: int,
                          rec: dict | None = None,
                          snapid: int = 0) -> tuple[int, str, dict]:
        if rec is None:
            rec = await self._anchor_get(ino)
        if rec is None:
            raise MDSError(ENOENT, f"no anchor for {ino:x}")
        pp, pn = int(rec["primary"][0]), str(rec["primary"][1])
        return pp, pn, await self._get_dentry(pp, pn, snapid)

    async def _resolve_remote(self, dentry: dict,
                              snapid: int = 0) -> dict:
        """A remote dentry's visible attrs are the primary's inode.
        With ``snapid``, the primary resolves through the snap view
        (frozen-else-live): any post-snap attr change froze the
        primary's dirfrag first, so the attrs are as-of-snap.  The
        anchor pointer itself is live — a -lite approximation; frozen
        dirfrags store stubs pre-resolved so this path only serves
        not-yet-diverged directories."""
        if not dentry.get("remote"):
            return dentry
        _, _, primary = await self._primary_of(int(dentry["ino"]),
                                               snapid=snapid)
        return {**primary, "remote": True}

    async def _plan_unlink_guard(self, dentry: dict) -> None:
        """_unlink_plan mutates the primary dentry (remote drop) or
        promotes the first remote IN PLACE; decline when that dirfrag
        belongs to another rank — cross-rank link teardown must funnel
        through the update_primary protocol, not a foreign omap
        write."""
        ino = int(dentry.get("ino", 0))
        if dentry.get("remote"):
            rec = await self._anchor_get(ino)
            if rec is not None and await self._auth_rank(
                    int(rec["primary"][0])) != self.rank:
                raise MDSError(
                    EXDEV, "replaces one name of a cross-rank link; "
                    "unlink it first")
        elif int(dentry.get("nlink", 1)) > 1:
            rec = await self._anchor_get(ino)
            if rec is not None and rec["remotes"] and \
                    await self._auth_rank(
                        int(rec["remotes"][0][0])) != self.rank:
                raise MDSError(
                    EXDEV, "would promote a foreign remote; "
                    "remove the remote name first")

    async def _unlink_plan(self, parent: int, name: str,
                           dentry: dict) -> dict:
        """The journal entry that removes one name of a file, hardlink-
        aware: remotes decrement, a linked primary promotes a remote to
        carry the inode, and only the LAST name purges data."""
        ino = int(dentry["ino"])
        if dentry.get("remote"):
            rec = await self._anchor_get(ino)
            pp, pn, primary = await self._primary_of(ino, rec)
            # the primary may be pinned by an in-flight two-phase
            # protocol (cross-rank rename/repoint): mutating nlink or
            # the anchor under it would clobber that protocol's
            # absolute writes
            self._guard_busy((pp, pn))
            primary = dict(primary)
            nl = int(primary.get("nlink", 1)) - 1
            primary["nlink"] = nl
            remotes = [r for r in rec["remotes"]
                       if [int(r[0]), str(r[1])] != [parent, name]]
            new_rec = await self._anchor_next(
                ino, None if nl <= 1 else
                {"primary": [pp, pn], "remotes": remotes})
            return {"op": "unlink_remote", "parent": parent,
                    "name": name, "ino": ino, "pp": pp, "pn": pn,
                    "primary_dentry": primary, "anchor": new_rec}
        nl = int(dentry.get("nlink", 1))
        if nl > 1:
            rec = await self._anchor_get(ino)
            np, nn = int(rec["remotes"][0][0]), str(rec["remotes"][0][1])
            self._guard_busy((np, nn))    # same pin rule as above
            promoted = dict(dentry)
            promoted["nlink"] = nl - 1
            new_rec = await self._anchor_next(
                ino, None if nl - 1 <= 1 else
                {"primary": [np, nn],
                 "remotes": rec["remotes"][1:]})
            return {"op": "promote_link", "parent": parent,
                    "name": name, "ino": ino, "np": np, "nn": nn,
                    "primary_dentry": promoted, "anchor": new_rec}
        return {"op": "unlink", "parent": parent, "name": name,
                "ino": ino, "size": int(dentry.get("size", 0))}

    # -- request handling (Server.cc handle_client_request) ---------------
    def ms_handle_connect(self, conn: Connection) -> None:
        pass

    def ms_handle_reset(self, conn: Connection) -> None:
        for sid, s in list(self._sessions.items()):
            if s["conn"] is conn:
                self._sessions.pop(sid, None)
        # a dead client's caps must not stall later recalls for the
        # full timeout: drop its grants and wake any waiters
        for ino, holder in list(self._caps.items()):
            if holder["conn"] is conn:
                self._caps.pop(ino, None)
                self._cap_resolve(ino)
        if self._rados_dispatch is not None:
            self.rados.ms_handle_reset(conn)

    async def ms_dispatch(self, conn: Connection, msg: Message) -> None:
        if msg.type == "mds_takeover":
            # promotion after a failover: our table/journal view dates
            # from boot — re-sync (at the ASSIGNED rank) before serving
            # mutations, or inos the failed active allocated could be
            # handed out again
            self.rank = int(msg.data.get("rank", self.rank))
            asyncio.get_running_loop().create_task(self._resync())
            return
        if msg.type == "mds_beacon_ack":
            # backup resync trigger: acks report our fsmap state, so a
            # standby->active transition (and our assigned rank) is
            # seen even when the leader's one-shot notify was lost
            state = str(msg.data.get("state", ""))
            rank = int(msg.data.get("rank", self.rank))
            if state == "up:active" and (
                    self._last_state == "up:standby"
                    or (rank >= 0 and rank != self.rank)):
                if rank >= 0:
                    self.rank = rank
                asyncio.get_running_loop().create_task(self._resync())
            self._last_state = state
            return
        if msg.type == "cap_release":
            # fire-and-forget release from a recalled client (the
            # request-path release_cap covers the clean-close case)
            ino = int(msg.data.get("ino", 0))
            holder = self._caps.get(ino)
            if holder is not None and holder["conn"] is conn:
                # only the CURRENT holder's release frees waiters; a
                # late release from an already-revoked holder must
                # not wake recalls aimed at the new grant
                self._caps.pop(ino, None)
                self._cap_resolve(ino)
            return
        if msg.type == "mds_reply" and \
                int(msg.data.get("tid", -1)) in self._peer_pending:
            fut = self._peer_pending.pop(int(msg.data["tid"]))
            if not fut.done():
                fut.set_result(msg.data)
            return
        if msg.type != "mds_request":
            if self._rados_dispatch is not None:
                # mon/rados traffic rides our shared dispatcher hook
                await self._rados_dispatch(conn, msg)
            else:
                log.dout(10, "%s: ignoring %s", self.entity, msg.type)
            return
        asyncio.get_running_loop().create_task(
            self._handle_request(conn, msg.data)
        )

    async def _resync(self) -> None:
        async with self._mutate:
            await self._load_snaptable()
            await self._load_quotatable()
            await self._load_subtrees()
            await self._load_table()
            await self._replay_journal()
            await self._repair_rename_intents()
        log.dout(1, "%s: resynced for takeover (rank=%d next_ino=%d)",
                 self.entity, self.rank, self.next_ino)

    async def _auth_rank(self, dino: int) -> int:
        """The rank authoritative for directory ``dino``: the nearest
        subtree-map entry on its ancestry chain, default rank 0 (the
        CDir subtree-auth resolution).  Memoized — invalidated on map
        reload, export, and directory renames (which change chains)."""
        return (await self._auth_rank_ex(dino))[0]

    async def _auth_rank_ex(self, dino: int) -> tuple[int, bool]:
        """(auth rank, explicit): ``explicit`` is False when resolution
        fell through to the rank-0 default — the caller may want to
        refresh the map before trusting it (a fresh export toward us
        looks exactly like that)."""
        if not self._subtrees and self.rank == 0:
            return 0, True
        hit = self._auth_cache.get(dino)
        if hit is not None:
            return hit, True
        rank, explicit = 0, False
        for link in await self._parent_chain(dino):
            r = self._subtrees.get(link)
            if r is not None:
                rank, explicit = r, True
                break
        if explicit or (rank == self.rank == 0):
            # defaulted results are cacheable for rank 0 (it IS the
            # default); other ranks must keep re-deriving them so a
            # fresh export toward them is noticed (refresh trigger)
            if len(self._auth_cache) > 65536:
                self._auth_cache.clear()
                self._ftree_cache.clear()
            self._auth_cache[dino] = rank
        return rank, explicit

    async def _check_auth(self, d: dict, op: str) -> int:
        """Serve only requests for directories this rank is
        authoritative over; others get a redirect the client follows
        (the reference forwards between MDSs; -lite redirects).
        Returns the directory ino the request was routed by."""
        # rename routes by its SOURCE parent (the rank that owns the
        # dentry being moved); its handler separately declines
        # cross-rank destinations with EXDEV
        dino = int(d.get("src_parent",
                         d.get("parent", d.get("ino", ROOT_INO))))
        if op in ("session", "get_load", "subtree_refresh",
                  "snap_refresh"):
            return dino
        auth, explicit = await self._auth_rank_ex(dino)
        if auth != self.rank and (
                not explicit
                or d.get("refresh_subtrees")
                or time.monotonic() - self._subtrees_loaded > 1.0):
            # maybe our map is stale (a fresh export toward us looks
            # like a default-fallback miss, and a ping-ponging client
            # sends refresh_subtrees): refresh — but when an explicit
            # entry already explains the redirect, throttle; redirecting
            # is the NORMAL case for rank 0 (clients start there) and an
            # omap read per op would tax the hot path
            await self._load_subtrees()
            auth = await self._auth_rank(dino)
        if auth != self.rank:
            raise MDSError(EREMOTE_RANK,
                           f"dir {dino:x} is served by rank {auth}",
                           redirect_rank=auth)
        return dino

    async def _handle_request(self, conn: Connection, d: dict) -> None:
        tid = d.get("tid", 0)
        op = str(d.get("op", ""))
        try:
            handler = getattr(self, f"_req_{op}", None)
            if handler is None:
                raise MDSError(EINVAL, f"unknown mds op {op!r}")
            d["_conn"] = conn       # cap ops key grants on the session
            dino = await self._check_auth(d, op)
            if op not in ("session", "get_load", "export_dir",
                          "subtree_refresh", "snap_refresh"):
                # balancer popularity: the directory the auth check
                # routed by (exports are administrative, not load)
                self._note_pop(dino)
            if op in ("lookup", "readdir", "session", "lssnap",
                      "rename", "link", "unlink", "setattr",
                      "get_load", "open_file", "release_cap",
                      "subtree_refresh", "snap_refresh"):
                # reads need no lock; rename/link/unlink/setattr
                # manage their own (each must release the mutate lock
                # across a cross-rank peer RPC); cap ops await client
                # recalls and touch only the volatile cap table
                result = await handler(d)
            else:
                async with self._mutate:
                    # authority may have moved (a balancer export)
                    # while this op queued on the lock: re-check, or
                    # the mutation would land in a foreign dirfrag
                    await self._check_auth(d, op)
                    result = await handler(d)
                    await self._maybe_compact()
            reply = {"tid": tid, "rc": 0, **result}
            # every reply carries the live snapc: clients must COW
            # data writes under new snaps without a dedicated fetch
            reply.setdefault("snapc", self._snapc_wire())
        except MDSError as e:
            reply = {"tid": tid, "rc": e.rc, "err": str(e)}
            if e.redirect_rank is not None:
                reply["redirect_rank"] = e.redirect_rank
        except RadosError as e:
            reply = {"tid": tid, "rc": e.rc, "err": str(e)}
        try:
            conn.send_message(Message("mds_reply", reply))
        except ConnectionError:
            pass

    # -- ops ---------------------------------------------------------------
    async def _req_session(self, d: dict) -> dict:
        """Session open: hand the client the layout it needs for direct
        data IO (the mdsmap + file-layout handshake)."""
        conn = d.get("_conn")
        if conn is not None and not any(
                s["conn"] is conn for s in self._sessions.values()):
            self._next_sid += 1
            self._sessions[self._next_sid] = {
                "conn": conn,
                "client": conn.peer_name or conn.peer_addr,
                "opened": time.time(),
            }
        return {"root": ROOT_INO, "data_pool": self.data_pool,
                "block_size": self.block_size,
                "lease": self.lease_ttl}

    async def _req_lookup(self, d: dict) -> dict:
        snapid = int(d.get("snapid", 0))
        dentry = await self._get_dentry(int(d["parent"]),
                                        str(d["name"]), snapid)
        if dentry.get("remote"):
            try:
                dentry = await self._resolve_remote(dentry, snapid)
            except MDSError:
                if not snapid:
                    raise          # snap stub mid-unlink: serve as-is
        if dentry.get("type") == "file" \
                and int(dentry["ino"]) in self._caps:
            # a write cap is out on this file: readers use this (it
            # rides the cached dentry) to decide whether an open
            # needs the recall round-trip
            dentry = {**dentry, "cap_held": True}
        return {"dentry": dentry, "lease": self.lease_ttl,
                "snapc": self._snapc_wire()}

    async def _req_fragment(self, d: dict) -> dict:
        """Manual dirfrag split/merge (the 'ceph tell mds.N dirfrag
        split / merge' surface, reference MDSRank command_dirfrag_split
        / command_dirfrag_merge).  nbits > 0 splits leaf (bits, value)
        into 2^nbits children; nbits == -1 merges (bits, value)'s two
        children back."""
        ino = int(d["ino"])
        b, v = int(d.get("bits", 0)), int(d.get("value", 0))
        nb = int(d.get("nbits", 1))
        try:
            await self.meta.stat(dirfrag_oid(ino))
        except RadosError as e:
            raise MDSError(ENOENT, f"no dir {ino:x}") \
                if e.rc == ENOENT else e
        tree = await self._fragtree(ino)
        if nb > 0:
            if b + nb > MAX_FRAG_BITS:
                raise MDSError(EINVAL,
                               f"split past {MAX_FRAG_BITS} bits")
            if (b, v) not in tree:
                raise MDSError(EINVAL, f"no leaf {b}_{v:x} in the "
                               "fragtree")
        elif nb == -1:
            kids = [(b + 1, (v << 1) + i) for i in (0, 1)]
            if not all(c in tree for c in kids):
                raise MDSError(EINVAL,
                               f"{b}_{v:x} has no mergeable children")
        else:
            raise MDSError(EINVAL, f"bad nbits {nb}")
        entry = {"op": "fragment", "ino": ino, "bits": b, "value": v,
                 "nbits": nb}
        await self._journal(entry)
        await self._apply(entry)
        return {"fragtree": [list(t) for t in
                             await self._fragtree(ino)]}

    async def _req_readdir(self, d: dict) -> dict:
        ino = int(d["ino"])
        snapid = int(d.get("snapid", 0))
        if snapid:
            kv = await self._snap_view(ino, snapid)
        else:
            try:
                kv = await self._dir_all(ino)
            except RadosError as e:
                raise MDSError(ENOENT, f"no dir {ino:x}") \
                    if e.rc == ENOENT else e
        entries = {name: decode(raw) for name, raw in kv.items()}
        for name, de in entries.items():
            if de.get("remote"):
                try:
                    entries[name] = await self._resolve_remote(de,
                                                               snapid)
                except MDSError:
                    pass        # racing unlink: show the raw entry
        return {"entries": entries, "lease": self.lease_ttl}

    async def _alloc_ino(self) -> int:
        ino = self.next_ino
        self.next_ino += 1
        return ino

    async def _ensure_absent(self, parent: int, name: str) -> None:
        try:
            await self._get_dentry(parent, name)
        except MDSError as e:
            if e.missing_dentry:
                return
            raise
        raise MDSError(EEXIST, f"{name!r} exists")

    async def _req_mkdir(self, d: dict) -> dict:
        parent, name = int(d["parent"]), str(d["name"])
        self._guard_busy((parent, name))
        await self._ensure_absent(parent, name)
        qroots = await self._quota_check(parent, add_files=1)
        ino = await self._alloc_ino()
        dentry = _dentry(ino, "dir", int(d.get("mode", 0o755)))
        entry = {"op": "mkdir", "parent": parent, "name": name,
                 "ino": ino, "dentry": dentry}
        await self._journal(entry)
        await self._apply(entry)
        self._quota_charge(qroots, files=1)
        return {"dentry": dentry}

    def _cap_grant_if_free(self, ino: int, conn) -> bool:
        """Grant the write cap when uncontended (no recall, no wait —
        safe under the mutate lock).  The reference likewise issues
        caps in the open/create reply; the contended case falls back
        to the client's open_file request, which can wait."""
        holder = self._caps.get(ino)
        if holder is not None and not holder["conn"].is_closed \
                and holder["conn"] is not conn:
            return False
        self._caps[ino] = {"conn": conn, "holder": ""}
        return True

    async def _req_create(self, d: dict) -> dict:
        parent, name = int(d["parent"]), str(d["name"])
        self._guard_busy((parent, name))
        try:
            existing = await self._get_dentry(parent, name)
            if d.get("exclusive"):
                raise MDSError(EEXIST, f"{name!r} exists")
            if existing["type"] == "dir":
                raise MDSError(EISDIR, name)
            if existing["type"] == "symlink":
                # the MDS cannot follow (resolution is client-side):
                # answering with the link dentry would let the client
                # write data blocks under the LINK's inode.  The client
                # re-resolves and retries at the target (a race with a
                # concurrent symlink creation lands here).
                raise MDSError(ELOOP, f"{name!r} is a symlink")
            out = {"dentry": await self._resolve_remote(existing)}
            if d.get("want_cap") and self._cap_grant_if_free(
                    int(existing["ino"]), d.get("_conn")):
                out["cap"] = "w"
            return out
        except MDSError as e:
            if not e.missing_dentry:
                raise
        qroots = await self._quota_check(parent, add_files=1)
        ino = await self._alloc_ino()
        dentry = _dentry(ino, "file", int(d.get("mode", 0o644)))
        entry = {"op": "create", "parent": parent, "name": name,
                 "ino": ino, "dentry": dentry}
        await self._journal(entry)
        await self._apply(entry)
        self._quota_charge(qroots, files=1)
        out = {"dentry": dentry}
        if d.get("want_cap") and self._cap_grant_if_free(
                ino, d.get("_conn")):
            out["cap"] = "w"
        return out

    async def _req_symlink(self, d: dict) -> dict:
        """Server::handle_client_symlink: a dentry of type symlink
        whose target string rides the embedded inode."""
        parent, name = int(d["parent"]), str(d["name"])
        self._guard_busy((parent, name))
        try:
            await self._get_dentry(parent, name)
            raise MDSError(EEXIST, f"{name!r} exists")
        except MDSError as e:
            if not e.missing_dentry:
                raise
        qroots = await self._quota_check(parent, add_files=1)
        ino = await self._alloc_ino()
        dentry = _dentry(ino, "symlink", 0o777)
        dentry["target"] = str(d.get("target", ""))
        entry = {"op": "create", "parent": parent, "name": name,
                 "ino": ino, "dentry": dentry}
        await self._journal(entry)
        await self._apply(entry)
        self._quota_charge(qroots, files=1)
        return {"dentry": dentry}

    async def _walk_subtree(self, ino: int) -> list[int]:
        """Directory inos of the subtree rooted at ``ino`` (BFS; -lite
        scale walks eagerly like the reference's snaprealm open)."""
        out, queue = [], [ino]
        while queue:
            cur = queue.pop()
            out.append(cur)
            try:
                kv = await self._dir_all(cur)
            except RadosError as e:
                if e.rc == ENOENT:
                    continue
                raise
            for raw in kv.values():
                de = decode(raw)
                if de.get("type") == "dir":
                    queue.append(int(de["ino"]))
        return out

    async def _req_mksnap(self, d: dict) -> dict:
        """Snapshot of the subtree at dir ``ino`` (Server::mksnap) as a
        COW SNAP REALM (reference SnapRealm.h): O(1) regardless of
        subtree size — just a snapid + realm record.  Metadata diverges
        lazily (_cow_freeze on first mutation per dirfrag); file data =
        RADOS self-managed snap, COWed by every client's snapc."""
        ino, name = int(d["ino"]), str(d["name"])
        if any(i["name"] == name and int(i["ino"]) == ino
               for i in self.snaps.values()):
            raise MDSError(EEXIST, f"snap {name!r} exists")
        await self._load_subtrees()      # a stale map must not skip a
        realm_ranks = set()              # rank owning realm territory
        for s, r in self._subtrees.items():
            if r != self.rank and (s == ino
                                   or await self._is_ancestor(ino, s)):
                realm_ranks.add(r)
        snapid = await self.data.selfmanaged_snap_create()
        entry = {"op": "mksnap", "snapid": snapid,
                 "info": {"name": name, "ino": ino,
                          "created": time.time()}}
        await self._journal(entry)
        await self._apply(entry)
        if realm_ranks:
            # the realm SPANS delegated subtrees (round-3 weak #5):
            # every owning rank must ADOPT the snapid (reload the
            # shared snaptable into its snapc) before mksnap returns,
            # or its next mutation under the realm would skip the COW
            # freeze.  Adoption is required, not best-effort — a rank
            # that cannot adopt fails the mksnap and the snap rolls
            # back (a restarting rank reloads the table at boot).
            failed = None
            for r in sorted(realm_ranks):
                try:
                    await self._require_snap_adoption(r)
                except MDSError as e:
                    failed = (r, str(e))
                    break
            if failed is not None:
                rollback = {"op": "rmsnap", "snapid": snapid,
                            "ino": ino}
                await self._journal(rollback)
                await self._apply(rollback)
                raise MDSError(
                    EXDEV, f"rank {failed[0]} could not adopt the "
                    f"snapshot ({failed[1]}); mksnap rolled back")
        return {"snapid": snapid, "snapc": self._snapc_wire()}

    async def _require_snap_adoption(self, rank: int) -> None:
        """Required snaptable-adoption push (shared by mksnap on
        spanning realms and export-under-snapshot): the peer rank must
        reload the shared snaptable NOW, or its next mutation under
        the realm would skip the COW freeze.  Raises on any failure —
        adoption is required, never best-effort."""
        reply = await self._peer_request(rank, {"op": "snap_refresh"},
                                         timeout=5.0)
        if int(reply.get("rc", -1)) != 0:
            raise MDSError(EXDEV, str(reply.get("err", "refused")))

    async def _req_snap_refresh(self, d: dict) -> dict:
        """Peer push after mksnap/rmsnap on a realm that spans our
        territory: adopt the shared snaptable NOW so the very next
        mutation COW-freezes under the new snap."""
        await self._load_snaptable()
        return {}

    async def _req_export_dir(self, d: dict) -> dict:
        """Delegate the subtree at dir ``ino`` to another active rank
        (the Migrator.h:50 subtree export, journal-coordinated: every
        mutation this rank made is applied + compacted before the map
        entry commits, so the importing rank starts from durable
        state — the -lite design keeps no dirty MDS cache to migrate).
        """
        ino, rank = int(d["ino"]), int(d["rank"])
        if rank < 0 or rank > 64:
            raise MDSError(EINVAL, f"bad rank {rank}")
        if rank != self.rank and not await self._rank_is_active(rank):
            # a typo'd rank would blackhole the subtree: every client
            # op would redirect to a rank nobody holds
            raise MDSError(EINVAL, f"rank {rank} has no active mds")
        try:
            await self.meta.stat(dirfrag_oid(ino))
        except RadosError as e:
            raise MDSError(ENOENT, f"no dir {ino:x}") \
                if e.rc == ENOENT else e
        if rank != self.rank and await self._covering_snaps(ino):
            # exporting under a LIVE snapshot (formerly declined): the
            # importing rank must adopt the shared snaptable BEFORE
            # authority moves, or its first post-import mutation under
            # the realm would skip the COW freeze — the same required-
            # adoption push mksnap uses for realms that already span
            # ranks (round-4 snaptable adoption; MExportDir + snap
            # realm open in the reference Migrator)
            try:
                await self._require_snap_adoption(rank)
            except MDSError as e:
                raise MDSError(
                    EXDEV, f"rank {rank} could not adopt the live "
                    f"snapshot ({e}); export declined")
        for bp, bn in self._busy_names:
            # a cross-rank rename in flight under the subtree holds
            # only its name pins across the peer RPC; exporting now
            # would let its finish half journal into a foreign dirfrag
            if bp == ino or await self._is_ancestor(ino, bp):
                raise MDSError(
                    EBUSY, f"cross-rank rename in flight under "
                    f"{ino:x} ({bp:x}/{bn})")
        await self._check_no_boundary_anchors(ino)
        for q in self.quotas:
            if q != ino and await self._is_ancestor(q, ino):
                # accounting is single-rank (the setquota EXDEV
                # mirror): a realm must not span the delegation
                raise MDSError(
                    EXDEV, f"subtree lies inside quota realm {q:x}; "
                    "clear the quota or export the realm root")
        # force-revoke EVERY cap this rank granted (no waiting — the
        # holder's flush needs the very lock this export holds): the
        # client flushes on receiving the recall and its setattr
        # follows the post-export redirect.  Conservative (all caps,
        # not just the subtree's) but exports are rare
        for cap_ino in list(self._caps):
            holder = self._caps.pop(cap_ino)
            self._cap_resolve(cap_ino)
            if not holder["conn"].is_closed:
                try:
                    holder["conn"].send_message(
                        Message("cap_recall", {"ino": cap_ino}))
                except ConnectionError:
                    pass
        await self._compact_journal()
        # an entry is only redundant when it matches what the PARENT
        # chain already resolves to; "back to rank 0" under a delegated
        # ancestor needs an explicit {ino: 0} override, not a removal
        parent_auth = 0
        for link in (await self._parent_chain(ino))[1:]:
            r = self._subtrees.get(link)
            if r is not None:
                parent_auth = r
                break
        if rank == parent_auth:
            if ino in self._subtrees:
                await self.meta.operate(
                    SUBTREE_OID, ObjectOperation().omap_rm([str(ino)]))
                self._subtrees.pop(ino, None)
        else:
            await self.meta.operate(
                SUBTREE_OID, ObjectOperation().create()
                .omap_set({str(ino): str(rank).encode()}))
            self._subtrees[ino] = rank
        self._auth_cache.clear()
        self._ftree_cache.clear()
        self._quota_invalidate()
        # the subtree's popularity belongs to the importing rank now —
        # stale pops would inflate my_load (and the balancer's "need")
        # with load this rank no longer serves
        if rank != self.rank:
            for dino in list(self._pop):
                if dino == ino or await self._is_ancestor(ino, dino):
                    self._pop.pop(dino, None)
        # PUSH the new map to every other active rank (MExportDirNotify
        # role): peers adopt the delegation immediately instead of
        # discovering it on their next redirect miss (round-3 weak #5:
        # propagation was refresh-on-redirect only).  Best-effort —
        # redirect-refresh remains the safety net for missed pushes.
        await self._push_subtree_update()
        log.dout(1, "%s: exported dir %x to rank %d", self.entity,
                 ino, rank)
        return {"rank": rank}

    async def _push_subtree_update(self) -> None:
        try:
            r = await self.rados.mon_command("mds stat")
        except (IOError, ConnectionError):
            return
        if r.get("rc") != 0:
            return
        actives = (r["data"]["filesystems"]
                   .get(self.fs_name, {}).get("actives", ()))
        peers = [int(a["rank"]) for a in actives
                 if int(a["rank"]) != self.rank]
        if not peers:
            return
        replies = await asyncio.gather(
            *(self._peer_request(p, {"op": "subtree_refresh"},
                                 timeout=2.0) for p in peers),
            return_exceptions=True)
        for p, rep in zip(peers, replies):
            if isinstance(rep, BaseException):
                log.dout(5, "%s: subtree push to rank %d missed: %s",
                         self.entity, p, rep)

    async def _req_subtree_refresh(self, d: dict) -> dict:
        """Peer push after an export: adopt the shared subtree map NOW
        (throttle bypassed) so the very next client op routes by the
        new delegation."""
        await self._load_subtrees()
        self._auth_cache.clear()
        self._ftree_cache.clear()
        return {}

    # -- client sessions (SessionMap / session evict) ----------------------
    def session_ls(self) -> list[dict]:
        """Live client sessions with the caps each one holds."""
        out = []
        for sid, s in sorted(self._sessions.items()):
            if s["conn"].is_closed:
                continue
            out.append({
                "id": sid, "client": s["client"],
                "opened": s["opened"],
                "num_caps": sum(1 for h in self._caps.values()
                                if h["conn"] is s["conn"]),
            })
        return out

    async def session_evict(self, sid, blocklist=False) -> dict:
        """Evict one client (Server::kill_session): revoke its caps
        (waking any pending recalls) and close its connection — the
        laggy/misbehaving-client remedy.  ``blocklist`` additionally
        fences the client INSTANCE at the OSDs via the OSDMap
        blocklist (the reference evicts this way by default: caps
        alone cannot stop direct RADOS data writes already in
        flight)."""
        s = self._sessions.pop(int(sid), None)
        if s is None:
            return {"evicted": False}
        conn = s["conn"]
        blocked = False
        if blocklist and conn.peer_name:
            # fence BEFORE releasing caps (which wakes recall waiters
            # and grants a new writer), then wait for the fencing
            # epoch to publish — the reference's
            # wait_for_latest_osdmap step after a blocklist.  OSDs
            # still apply the map asynchronously; because ops carry
            # the sender's epoch and OSDs refuse ops newer than their
            # map, a new holder that has the fencing epoch cannot
            # race the evictee on an OSD that has not seen it.
            ent = f"{conn.peer_name}:{conn.peer_nonce}"
            try:
                r = await self.rados.mon_command(
                    "osd blocklist", action="add", entity=ent)
                blocked = r.get("rc") == 0
                if blocked:
                    await self._wait_blocklist_published(ent)
            except (RadosError, ConnectionError, OSError):
                pass          # eviction still proceeds unfenced
        for ino, holder in list(self._caps.items()):
            if holder["conn"] is conn:
                self._caps.pop(ino, None)
                self._cap_resolve(ino)
        conn.mark_down()      # hard close, no replay (kill_session)
        log.dout(1, "%s: evicted client session %s%s", self.entity,
                 s["client"], " (blocklisted)" if blocked else "")
        return {"evicted": True, "client": s["client"],
                "blocklisted": blocked}

    async def _wait_blocklist_published(self, ent: str,
                                        timeout: float = 5.0) -> None:
        """Poll the mon until the fencing entry is visible in the
        published map (bounded; eviction proceeds either way)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                r = await self.rados.mon_command("osd blocklist ls")
            except (RadosError, ConnectionError, OSError):
                return
            if r.get("rc") == 0 and ent in r["data"]["blocklist"]:
                return
            await asyncio.sleep(0.05)

    async def _write_backtrace(self, ino: int, parent: int,
                               name: str,
                               dentry: dict | None = None) -> None:
        """File backtrace sidecar in the DATA pool (the reference
        writes parent/name backtrace xattrs on object 0):
        cephfs-data-scan rebuilds dentries from these when the
        metadata pool is lost.  Symlinks record their target too —
        they have no data objects, so the sidecar is their ONLY
        recoverable trace.  Best effort: data-plane trouble must not
        fail the metadata op."""
        if self.data is None:
            return
        bt = {"parent": parent, "name": name}
        if dentry is not None:
            bt["type"] = dentry.get("type", "file")
            if dentry.get("type") == "symlink":
                bt["target"] = dentry.get("target", "")
        try:
            await self.data.operate(
                backtrace_oid(ino),
                ObjectOperation().create().set_xattr(
                    "backtrace", encode(bt)))
        except RadosError:
            pass

    # -- forward scrub (MDCache scrub + DamageTable roles) -----------------
    def _note_damage(self, dtype: str, ino: int, **info) -> None:
        """Append unless an identical finding (ignoring id/repaired)
        is already tabled — re-scrubbing an unrepaired defect must
        not grow the table (the reference DamageTable dedupes)."""
        sig = {k: v for k, v in info.items() if k != "repaired"}
        for d in self._damage:
            if d["damage_type"] == dtype and d["ino"] == ino \
                    and {k: v for k, v in d.items()
                         if k not in ("id", "damage_type", "ino",
                                      "repaired")} == sig:
                return
        self._damage_seq += 1
        self._damage.append({"id": self._damage_seq,
                             "damage_type": dtype, "ino": ino,
                             **info})

    def damage_ls(self) -> list[dict]:
        return list(self._damage)

    def damage_rm(self, id) -> dict:
        n = len(self._damage)
        self._damage = [d for d in self._damage
                        if d["id"] != int(id)]
        return {"removed": n - len(self._damage)}

    async def scrub_start(self, path: str = "/",
                          repair=False) -> dict:
        """Forward scrub (`ceph tell mds scrub start` role): walk the
        namespace under ``path`` within THIS rank's authority and
        validate the metadata invariants the -lite design maintains —
        dirfrag parent back-pointers match the containing directory,
        child dirfrags exist, remote dentries resolve through a
        consistent anchortable record, and quota-table records/usage
        match a fresh subtree recount.  ``repair=true`` fixes what is
        mechanically fixable (back-pointers, usage cache, records for
        dead dirs); everything found lands in the damage table."""
        repair = repair in (True, "true", "1", 1)
        async with self._mutate:
            return await self._scrub_locked(path, repair)

    async def _scrub_locked(self, path: str, repair: bool) -> dict:
        root = ROOT_INO
        if path not in ("", "/"):
            for part in path.strip("/").split("/"):
                d = await self._get_dentry(root, part)
                if d.get("type") != "dir":
                    raise MDSError(EINVAL,
                                   f"{path!r}: not a directory")
                root = int(d["ino"])
        checked = dirs = 0
        found: list[dict] = []

        def note(dtype: str, ino: int, **info):
            self._note_damage(dtype, ino, **info)
            found.append({"damage_type": dtype, "ino": ino, **info})

        subtree = await self._walk_subtree(root)
        for dino in subtree:
            if await self._auth_rank(dino) != self.rank:
                continue             # a peer rank scrubs its own
            try:
                kv = await self._dir_all(dino)
            except RadosError as e:
                if e.rc != ENOENT:
                    raise
                continue
            dirs += 1
            tree = await self._fragtree(dino)
            if tree != [ROOT_FRAG]:
                # every fragtree leaf must have its object (a crashed
                # split's journal replay normally rebuilds these; a
                # lost journal leaves the hole for scrub)
                for fb, fv in tree:
                    try:
                        await self.meta.stat(frag_oid(dino, fb, fv))
                    except RadosError as e:
                        if e.rc != ENOENT:
                            raise
                        note("missing_dirfrag_fragment", dino,
                             frag=f"{fb}_{fv:x}", repaired=repair)
                        if repair:
                            await self.meta.operate(
                                frag_oid(dino, fb, fv),
                                ObjectOperation().create())
            for name, raw in kv.items():
                de = decode(raw)
                checked += 1
                if de.get("type") == "dir":
                    await self._scrub_dir_child(dino, name, de,
                                                repair, note)
                elif de.get("remote"):
                    await self._scrub_remote(dino, name, de,
                                             repair, note)
        await self._scrub_quotas(set(subtree), repair, note)
        return {"scrubbed_dirs": dirs, "checked_dentries": checked,
                "damage": found, "repaired": repair}

    async def _scrub_dir_child(self, parent: int, name: str,
                               de: dict, repair: bool,
                               note) -> None:
        """Child dirfrag must exist and its parent back-pointer must
        name the dirfrag that holds its dentry (the backtrace
        invariant renames maintain)."""
        cino = int(de["ino"])
        corrupt = False
        try:
            raw = await self.meta.get_xattr(dirfrag_oid(cino),
                                            "parent")
            back = int(raw)
        except RadosError as e:
            if e.rc != ENOENT:
                raise
            back = None
        except (ValueError, TypeError):
            # garbage in the xattr is exactly the corruption class
            # scrub exists to find — table it, never abort the walk
            back, corrupt = None, True
        if corrupt:
            note("corrupt_backtrace", cino, parent=parent,
                 name=name, repaired=repair)
            if repair:
                await self.meta.operate(
                    dirfrag_oid(cino),
                    ObjectOperation().set_xattr(
                        "parent", str(parent).encode()))
            return
        if back is None:
            note("missing_dirfrag_or_backtrace", cino,
                 parent=parent, name=name,
                 repaired=repair)
            if repair:
                await self.meta.operate(
                    dirfrag_oid(cino),
                    ObjectOperation().create().set_xattr(
                        "parent", str(parent).encode()))
        elif back != parent:
            note("bad_backtrace", cino, parent=parent, name=name,
                 points_at=back, repaired=repair)
            if repair:
                await self.meta.operate(
                    dirfrag_oid(cino),
                    ObjectOperation().set_xattr(
                        "parent", str(parent).encode()))

    async def _scrub_remote(self, parent: int, name: str, de: dict,
                            repair: bool, note) -> None:
        """A remote dentry must resolve through its anchortable
        record, and the record's primary dentry must really exist
        (the reference scrub's remote-link pass)."""
        ino = int(de["ino"])
        rec = await self._anchor_get(ino)
        listed = rec is not None and (
            [parent, name] in [list(r) for r in
                               rec.get("remotes", ())])
        primary_ok = False
        if rec is not None and rec.get("primary"):
            pp, pn = rec["primary"]
            try:
                pd = await self._get_dentry(int(pp), str(pn))
                primary_ok = int(pd.get("ino", 0)) == ino                     and not pd.get("remote")
            except MDSError:
                primary_ok = False
        if rec is not None and primary_ok and not listed:
            # primary fine, this name just fell off the listing: the
            # LEAST destructive repair is to restore the listing
            note("unlisted_remote", ino, parent=parent, name=name,
                 repaired=repair)
            if repair:
                rec.setdefault("remotes", []).append([parent, name])
                rec.pop("v", None)      # live repair: bump past stored
                await self._anchor_put(ino, rec)
            return
        if rec is not None and listed and not primary_ok:
            # the PRIMARY is the casualty, not this name: deleting a
            # working remote would orphan the data — promote it
            note("dead_primary", ino, parent=parent, name=name,
                 repaired=repair)
            if repair:
                size = await self._size_from_data(ino)
                promoted = _dentry(ino, "file", 0o644, size)
                await self._set_dentry(parent, name, promoted)
                # the promoted name is the backtraced home now — a
                # stale sidecar would let data-scan resurrect the
                # dead primary's old name
                await self._write_backtrace(ino, parent, name,
                                            promoted)
                rec["primary"] = [parent, name]
                rec["remotes"] = [
                    r for r in rec.get("remotes", ())
                    if list(r) != [parent, name]]
                rec.pop("v", None)      # live repair: bump past stored
                if rec["remotes"]:
                    await self._anchor_put(ino, rec)
                else:
                    await self._anchor_put(ino, None)
            return
        if rec is None or (not listed and not primary_ok):
            # no anchor record at all, or a record that neither lists
            # this name nor backs a live primary: nothing resolvable
            # remains behind the remote — it is dead weight
            note("dangling_remote", ino, parent=parent, name=name,
                 anchored=rec is not None, repaired=repair)
            if repair:
                await self._rm_dentry(parent, name)

    async def _size_from_data(self, ino: int) -> int:
        """Recover a file's size from its data blocks (repair-path
        only: O(pool listing))."""
        best = 0
        prefix = f"{ino:x}."
        for oid in await self.data.list_objects():
            if not oid.startswith(prefix) or oid.endswith(".bt"):
                continue
            try:
                block = int(oid[len(prefix):], 16)
            except ValueError:
                continue
            st = await self.data.stat(oid)
            best = max(best, block * self.block_size
                       + int(st.get("size", 0)))
        return best

    async def _scrub_quotas(self, subtree: set[int], repair: bool,
                            note) -> None:
        """Quota records must point at live directories and cached
        usage must match a fresh recount (rstat consistency).  Only
        realms inside the scrubbed subtree are touched — a scoped
        scrub must not mutate state it was not asked to visit.
        Exception: a record for a DEAD directory is checked from any
        scope that could never walk to it anyway."""
        for qino, lim in list(self.quotas.items()):
            if await self._auth_rank(qino) != self.rank:
                continue
            if qino not in subtree and ROOT_INO not in subtree:
                continue
            try:
                await self.meta.stat(dirfrag_oid(qino))
                alive = True
            except RadosError as e:
                if e.rc != ENOENT:
                    raise
                alive = False
            if not alive:
                note("quota_record_for_dead_dir", qino,
                     limits=dict(lim), repaired=repair)
                if repair:
                    await self._quota_drop(qino)
                continue
            cached = self._qusage.get(qino)
            fresh = await self._compute_usage(qino)
            if cached is not None and cached != fresh:
                note("quota_usage_drift", qino, cached=dict(cached),
                     actual=dict(fresh), repaired=repair)
                if repair:
                    self._qusage[qino] = fresh

    # -- balancer (MDBalancer.h:33 + MHeartbeat load exchange) -------------
    def _decay_pops(self) -> None:
        """Lazy exponential decay of the whole popularity map
        (DecayCounter role with a single shared stamp)."""
        now = time.monotonic()
        half = self.conf["mds_decay_halflife"]
        dt = now - self._pop_stamp
        if dt < half / 8:
            return
        f = 0.5 ** (dt / half)
        self._pop = {i: p * f for i, p in self._pop.items()
                     if p * f > 0.01}
        self._pop_stamp = now

    def _note_pop(self, dino: int) -> None:
        self._decay_pops()
        self._pop[dino] = self._pop.get(dino, 0.0) + 1.0

    def my_load(self) -> float:
        """This rank's decayed request load (mds_load_t role)."""
        self._decay_pops()
        return sum(self._pop.values())

    async def _req_get_load(self, d: dict) -> dict:
        """Rank-to-rank load exchange (the MHeartbeat role: the
        balancing rank polls instead of every rank broadcasting)."""
        return {"load": self.my_load()}

    # -- directory quotas (quota_info_t + rstat accounting, -lite) ---------
    async def _quota_drop(self, ino: int) -> None:
        """A quota'd directory was removed (rmdir / replaced-empty-dir
        purge): its record must die with it, or the table leaks an
        entry the realm-split export check iterates forever."""
        if ino not in self.quotas:
            return
        await self.meta.operate(
            QUOTATABLE_OID,
            ObjectOperation().create().omap_rm([str(ino)]))
        self.quotas.pop(ino, None)
        self._qusage.pop(ino, None)

    async def _quota_roots(self, dino: int) -> list[int]:
        """Quota realms covering directory ``dino`` (every ancestor
        with a quota record, itself included)."""
        if not self.quotas:
            return []
        return [link for link in await self._parent_chain(dino)
                if link in self.quotas]

    async def _quota_usage(self, qino: int) -> dict:
        """Cached {bytes, files} under quota root ``qino``; first use
        walks the subtree (files + dirs count as entries, like
        rfiles+rsubdirs), then per-op increments keep it current."""
        u = self._qusage.get(qino)
        if u is not None:
            return u
        u = await self._compute_usage(qino)
        self._qusage[qino] = u
        return u

    async def _compute_usage(self, qino: int) -> dict:
        total = files = 0
        for dino in await self._walk_subtree(qino):
            try:
                kv = await self._dir_all(dino)
            except RadosError as e:
                if e.rc != ENOENT:
                    raise
                continue
            for raw in kv.values():
                de = decode(raw)
                files += 1
                if de.get("type") == "file" \
                        and not de.get("remote"):
                    total += int(de.get("size", 0))
        return {"bytes": total, "files": files}

    async def _quota_check(self, dino: int, add_files: int = 0,
                           add_bytes: int = 0,
                           roots: list[int] | None = None
                           ) -> list[int]:
        """EDQUOT when the op would push any covering realm over its
        limit; returns the realms so the caller can charge them after
        the apply.  ``roots``: check these realms instead of dino's
        full chain (renames charge only the NET-GAINING realms)."""
        if roots is None:
            roots = await self._quota_roots(dino)
        for q in roots:
            lim = self.quotas[q]
            u = await self._quota_usage(q)
            if add_files > 0 and int(lim.get("max_files", 0)) \
                    and u["files"] + add_files > lim["max_files"]:
                raise MDSError(EDQUOT,
                               f"quota max_files exceeded on {q:x}")
            if add_bytes > 0 and int(lim.get("max_bytes", 0)) \
                    and u["bytes"] + add_bytes > lim["max_bytes"]:
                raise MDSError(EDQUOT,
                               f"quota max_bytes exceeded on {q:x}")
        return roots

    def _quota_charge(self, roots: list[int], files: int = 0,
                      nbytes: int = 0) -> None:
        for q in roots:
            u = self._qusage.get(q)
            if u is not None:
                u["files"] += files
                u["bytes"] += nbytes

    def _quota_invalidate(self) -> None:
        """Renames/imports/exports move whole subtrees between realms:
        recount lazily instead of computing subtree deltas."""
        self._qusage.clear()

    async def _req_setquota(self, d: dict) -> dict:
        """Set/clear a directory quota (the client setfattr
        ceph.quota.* surface)."""
        ino = int(d["ino"])
        try:
            await self.meta.stat(dirfrag_oid(ino))
        except RadosError as e:
            raise MDSError(ENOENT, f"no dir {ino:x}") \
                if e.rc == ENOENT else e
        max_bytes = max(0, int(d.get("max_bytes", 0)))
        max_files = max(0, int(d.get("max_files", 0)))
        for s, r in self._subtrees.items():
            if r != self.rank and await self._is_ancestor(ino, s):
                raise MDSError(
                    EXDEV, f"subtree {s:x} inside the quota realm is "
                    f"delegated to rank {r}; quota accounting is "
                    "single-rank")
        entry = {"op": "setquota", "ino": ino,
                 "max_bytes": max_bytes, "max_files": max_files}
        await self._journal(entry)
        await self._apply(entry)
        return {"quota": self.quotas.get(ino,
                                         {"max_bytes": 0,
                                          "max_files": 0})}

    async def _req_getquota(self, d: dict) -> dict:
        ino = int(d["ino"])
        q = self.quotas.get(ino)
        if q is None:
            # usage is still answered (uncached walk): resize
            # --no-shrink and `subvolume info` need it regardless of
            # whether a limit is currently set
            return {"quota": {"max_bytes": 0, "max_files": 0},
                    "usage": await self._compute_usage(ino)}
        return {"quota": q, "usage": await self._quota_usage(ino)}

    # -- file write caps (Locker/Capability, the -lite slice) --------------
    async def _cap_recall(self, ino: int,
                          timeout: float = 3.0) -> None:
        """Ask the holder to flush + release; force-revoke on timeout
        or a dead connection (the reference's laggy-client cap
        revocation)."""
        holder = self._caps.get(ino)
        if holder is None:
            return
        conn = holder["conn"]
        if not conn.is_closed:
            fut = asyncio.get_running_loop().create_future()
            waiters = self._cap_waiters.setdefault(ino, [])
            waiters.append(fut)
            try:
                conn.send_message(Message("cap_recall", {"ino": ino}))
                await asyncio.wait_for(fut, timeout)
            except (ConnectionError, asyncio.TimeoutError):
                pass
            finally:
                # remove only OUR future; a concurrent recall of the
                # same ino keeps its own (single-slot clobbering made
                # the second opener burn the full timeout)
                if fut in self._cap_waiters.get(ino, ()):
                    self._cap_waiters[ino].remove(fut)
                if not self._cap_waiters.get(ino):
                    self._cap_waiters.pop(ino, None)
        if self._caps.get(ino) is holder:
            # pop only the grant WE recalled: the table may already
            # carry a fresh grant made while this recall waited
            self._caps.pop(ino, None)

    def _cap_resolve(self, ino: int) -> None:
        for fut in self._cap_waiters.pop(ino, ()):
            if not fut.done():
                fut.set_result(None)

    async def _req_open_file(self, d: dict) -> dict:
        """Open-time cap negotiation: a WRITE open takes the file's
        exclusive buffered-write cap (recalling any other holder
        first); a READ open just recalls — the holder's buffered bytes
        and size must be flushed before the reader looks."""
        parent, name = int(d["parent"]), str(d["name"])
        conn = d.get("_conn")

        async def fresh() -> dict:
            # reply attrs must be the PRIMARY's (a remote stub has no
            # size), post-flush when a recall just happened
            de = await self._get_dentry(parent, name)
            return (await self._resolve_remote(de)
                    if de.get("remote") else de)

        dentry = await self._get_dentry(parent, name)
        if dentry["type"] != "file":
            raise MDSError(EISDIR, name)
        ino = int(dentry["ino"])    # remote stub shares the link ino
        if not d.get("write"):
            holder = self._caps.get(ino)
            if holder is not None and holder["conn"] is not conn:
                await self._cap_recall(ino)
            return {"cap": "r", "dentry": await fresh()}
        for _ in range(8):        # bounded: each pass evicts a holder
            holder = self._caps.get(ino)
            if holder is None or holder["conn"].is_closed \
                    or holder["conn"] is conn:
                self._caps[ino] = {"conn": conn,
                                   "holder": str(d.get("who", ""))}
                return {"cap": "w", "dentry": await fresh()}
            await self._cap_recall(ino)
        raise MDSError(EBUSY, f"cap on {ino:x} cannot be claimed")

    async def _req_release_cap(self, d: dict) -> dict:
        ino = int(d.get("ino", 0))
        holder = self._caps.get(ino)
        if holder is not None and holder["conn"] is d.get("_conn"):
            self._caps.pop(ino, None)
            self._cap_resolve(ino)
        return {}

    async def _balance_loop(self) -> None:
        interval = self.conf["mds_bal_interval"]
        while True:
            await asyncio.sleep(interval)
            try:
                await self.balance_once()
            except (MDSError, RadosError, ConnectionError, OSError):
                pass              # transient peer/mon trouble: next tick

    async def balance_once(self) -> dict | None:
        """One balancer pass (MDBalancer::tick + prep_rebalance): poll
        the other actives' loads; when this rank carries more than its
        share of the decayed request load, export the subtree whose
        aggregated popularity best matches the excess to the
        least-loaded rank.  Returns {ino, rank, load} on export."""
        r = await self.rados.mon_command("mds stat")
        if r.get("rc") != 0:
            return None
        actives = (r["data"]["filesystems"]
                   .get(self.fs_name, {}).get("actives", ()))
        if len(actives) < 2:
            return None
        peers = [int(a["rank"]) for a in actives
                 if int(a["rank"]) != self.rank]
        replies = await asyncio.gather(
            *(self._peer_request(r, {"op": "get_load"}, timeout=5.0)
              for r in peers), return_exceptions=True)
        loads: dict[int, float] = {self.rank: self.my_load()}
        for rank, rep in zip(peers, replies):
            if isinstance(rep, BaseException) or rep.get("rc") != 0:
                return None   # a blind rebalance could thrash: skip
            loads[rank] = float(rep.get("load", 0.0))
        if not any(int(a["rank"]) == self.rank for a in actives):
            return None
        mean = sum(loads.values()) / len(loads)
        need = loads[self.rank] - mean
        if need < max(self.conf["mds_bal_min_start"],
                      mean * self.conf["mds_bal_min_rebalance"]):
            return None
        target = min((r for r in loads if r != self.rank),
                     key=lambda r: (loads[r], r))
        return await self._export_for_balance(need, target)

    async def _export_for_balance(self, need: float,
                                  target: int) -> dict | None:
        """Aggregate per-directory popularity up the ancestry (within
        this rank's authority) and export the subtree whose load is
        closest to ``need``.  Candidates that cannot export (live
        snapshot realm, boundary anchors, a concurrent rename) are
        skipped, not fatal."""
        self._decay_pops()
        agg: dict[int, float] = {}
        for dino, p in list(self._pop.items()):
            for link in await self._parent_chain(dino):
                if await self._auth_rank(link) != self.rank:
                    break         # left our territory
                agg[link] = agg.get(link, 0.0) + p
        # strict improvement only: moving load L changes this rank's
        # deviation from need to |need - L|, so 0 < L < 2*need shrinks
        # it — and the < bound is the anti-ping-pong hysteresis (once
        # balanced, re-exporting the same subtree can't improve)
        cands = [(i, load) for i, load in agg.items()
                 if i != ROOT_INO and need * 0.25 <= load < need * 2]
        cands.sort(key=lambda kv: (abs(kv[1] - need), kv[0]))
        for ino, load in cands:
            try:
                async with self._mutate:
                    await self._req_export_dir(
                        {"ino": ino, "rank": target})
            except (MDSError, RadosError):
                continue          # snaps/anchors/rename races: next
            log.dout(1, "%s: balancer exported %x (load %.1f) to "
                     "rank %d", self.entity, ino, load, target)
            return {"ino": ino, "rank": target, "load": load}
        return None

    async def _active_entry(self, rank: int) -> dict | None:
        """This fs's fsmap entry for an active ``rank``, or None."""
        try:
            r = await self.rados.mon_command("mds stat")
        except (ConnectionError, OSError):
            return None
        if r.get("rc") != 0:
            return None
        for a in (r["data"]["filesystems"]
                  .get(self.fs_name, {}).get("actives", ())):
            if int(a.get("rank", -1)) == rank:
                return a
        return None

    async def _rank_addr(self, rank: int) -> str:
        a = await self._active_entry(rank)
        if a is None:
            raise MDSError(EXDEV, f"rank {rank} has no active mds")
        return str(a["addr"])

    # -- cross-rank rename commit log (atomic cls rename_wal ops) ----------
    async def _rename_mark_commit(self, token: str) -> bool:
        """Atomically claim the commit marker; False when the source
        already claimed abort.  Errors other than the abort verdict
        propagate — a transient read failure must retry, not silently
        decide the race."""
        import json as _json

        try:
            await self.meta.exec(
                RENAME_LOG_OID, "rename_wal", "commit",
                _json.dumps({"token": token}).encode())
            return True
        except RadosError as e:
            if e.rc == ECANCELED:
                return False
            raise

    async def _rename_resolve_abort(self, token: str) -> bool:
        """Atomically: claim the abort marker unless the commit marker
        exists.  Returns True when the rename COMMITTED."""
        import json as _json

        out = await self.meta.exec(
            RENAME_LOG_OID, "rename_wal", "abort",
            _json.dumps({"token": token}).encode())
        return bool(_json.loads(out)["committed"])

    async def _rename_marker_state(self, token: str) -> dict:
        import json as _json

        out = await self.meta.exec(
            RENAME_LOG_OID, "rename_wal", "get",
            _json.dumps({"token": token}).encode())
        return _json.loads(out)

    async def _rename_clear(self, token: str) -> None:
        import json as _json

        try:
            await self.meta.exec(
                RENAME_LOG_OID, "rename_wal", "clear",
                _json.dumps({"token": token}).encode())
        except RadosError:
            pass                      # gc sweeps leaks

    async def _peer_request(self, rank: int, payload: dict,
                            timeout: float = 10.0) -> dict:
        """One request to a peer active rank (slave-request role,
        reference MMDSSlaveRequest): same wire op surface a client
        uses, awaited by tid.  The timeout also breaks the theoretical
        deadlock of two opposite-direction cross-rank renames each
        holding its own rank's mutate lock."""
        addr = await self._rank_addr(rank)
        self._peer_tid += 1
        tid = self._peer_tid
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._peer_pending[tid] = fut
        try:
            await self.msgr.send_to(
                addr, Message("mds_request", {**payload, "tid": tid}),
                f"mds-rank{rank}",
            )
            return await asyncio.wait_for(fut, timeout)
        except (ConnectionError, asyncio.TimeoutError) as e:
            raise MDSError(EXDEV,
                           f"rank {rank} unreachable: {e!r}") from None
        finally:
            self._peer_pending.pop(tid, None)

    async def _rank_is_active(self, rank: int) -> bool:
        return await self._active_entry(rank) is not None

    async def _check_no_boundary_anchors(self, ino: int) -> None:
        """Hard links whose names straddle the export boundary would
        put the primary and remotes under different authorities (the
        same hazard the EXDEV link guard prevents going forward)."""
        try:
            omap = await self.meta.get_omap(ANCHOR_OID)
        except RadosError as e:
            if e.rc == ENOENT:
                return
            raise
        for raw in omap.values():
            rec = decode(raw)
            if rec.get("dead"):
                continue          # versioned tombstone, not a link
            names = [rec["primary"]] + list(rec.get("remotes", ()))
            inside = []
            for p, _ in names:
                p = int(p)
                inside.append(p == ino
                              or await self._is_ancestor(ino, p))
            if any(inside) and not all(inside):
                raise MDSError(
                    EBUSY, "a hard link spans the export boundary; "
                    "unlink it first")

    async def _req_rmsnap(self, d: dict) -> dict:
        ino, name = int(d["ino"]), str(d["name"])
        snapid = next((sid for sid, i in self.snaps.items()
                       if i["name"] == name and int(i["ino"]) == ino),
                      None)
        if snapid is None:
            raise MDSError(ENOENT, f"no snap {name!r}")
        entry = {"op": "rmsnap", "snapid": snapid, "ino": ino}
        await self._journal(entry)
        await self._apply(entry)
        # drop the dead snapid from spanning ranks' snapc too (best-
        # effort: a stale entry only costs wasted freezes, never
        # correctness; boot reload heals it)
        for s, r in self._subtrees.items():
            if r != self.rank and (s == ino
                                   or await self._is_ancestor(ino, s)):
                try:
                    await self._peer_request(
                        r, {"op": "snap_refresh"}, timeout=2.0)
                except MDSError:
                    pass
        return {"snapc": self._snapc_wire()}

    async def _req_lssnap(self, d: dict) -> dict:
        ino = int(d["ino"])
        return {"snaps": {
            i["name"]: {"snapid": sid, "created": i["created"]}
            for sid, i in self.snaps.items() if int(i["ino"]) == ino
        }, "snapc": self._snapc_wire()}

    async def _req_link(self, d: dict) -> dict:
        """Hard link (Server::handle_client_link): a REMOTE dentry at
        (parent, name) referencing the primary's inode.  Routed by the
        SOURCE parent, so the primary's rank runs this; a foreign
        destination parent runs the witness-lite export protocol
        (an import_link peer request gated by the atomic commit
        marker), keeping every anchor write on the primary's rank."""
        sp, sn = int(d["src_parent"]), str(d["src_name"])
        dp, dn = int(d["parent"]), str(d["name"])
        async with self._mutate:
            # authority may have moved while this op queued on the
            # lock (a balancer export): re-check, as the locked
            # handler branch does for other mutations
            await self._check_auth(d, "link")
            self._guard_busy((sp, sn), (dp, dn))
            dentry = await self._get_dentry(sp, sn)
            if dentry.get("remote"):
                # keep link chains flat: always link to the primary
                sp, sn, dentry = await self._primary_of(
                    int(dentry["ino"]))
                if await self._auth_rank(sp) != self.rank:
                    raise MDSError(
                        EXDEV, "link through a foreign primary; "
                        "link from the primary name instead")
                # the primary name itself may be pinned by another
                # in-flight cross-rank link/unlink
                self._guard_busy((sp, sn))
            if dentry["type"] != "file":
                raise MDSError(EPERM, "hard links are file-only")
            ino = int(dentry["ino"])
            primary = dict(dentry)
            primary["nlink"] = int(dentry.get("nlink", 1)) + 1
            rec = await self._anchor_get(ino)
            base = rec or {"primary": [sp, sn], "remotes": []}
            anchor = await self._anchor_next(ino, {
                "primary": base["primary"],
                "remotes": list(base["remotes"]) + [[dp, dn]],
            })
            dst_rank = await self._auth_rank(dp)
            if dst_rank == self.rank:
                await self._ensure_absent(dp, dn)
                qroots = await self._quota_check(dp, add_files=1)
                entry = {"op": "link", "parent": dp, "name": dn,
                         "ino": ino,
                         "remote_dentry": {"type": "file",
                                           "remote": True,
                                           "ino": ino},
                         "pp": sp, "pn": sn,
                         "primary_dentry": primary, "anchor": anchor}
                await self._journal(entry)
                await self._apply(entry)
                self._quota_charge(qroots, files=1)
                await self._maybe_compact()
                return {"dentry": {**primary, "remote": True}}
            # cross-rank: intent first, RPC without the lock
            token = secrets.token_hex(8)
            await self._journal({
                "op": "link_export_intent", "pp": sp, "pn": sn,
                "parent": dp, "name": dn, "ino": ino,
                "token": token})
            self._busy_names.add((sp, sn))
        try:
            return await self._link_cross_rank_finish(
                sp, sn, dp, dn, ino, primary, anchor, dst_rank, token)
        finally:
            self._busy_names.discard((sp, sn))

    async def _link_cross_rank_finish(self, sp, sn, dp, dn, ino,
                                      primary, anchor, dst_rank,
                                      token) -> dict:
        await self._two_phase_finish(
            dst_rank,
            {"op": "import_link", "parent": dp, "name": dn,
             "remote_dentry": {"type": "file", "remote": True,
                               "ino": ino},
             "token": token},
            token,
            {"op": "link_export_abort", "ino": ino, "token": token},
            {"op": "link_export_finish", "pp": sp, "pn": sn,
             "ino": ino, "primary_dentry": primary,
             "anchor": anchor, "token": token},
            "destination rank unreachable; link rolled back")
        return {"dentry": {**primary, "remote": True}}

    async def _req_import_link(self, d: dict) -> dict:
        """Cross-rank link, DESTINATION half: materialize the remote
        dentry in a directory this rank serves, gated by the commit
        marker exactly like import_dentry."""
        dp, dn = int(d["parent"]), str(d["name"])
        token = str(d.get("token", ""))
        try:
            dst = await self._get_dentry(dp, dn)
            if int(dst.get("ino", 0)) == \
                    int(dict(d["remote_dentry"])["ino"]) \
                    and dst.get("remote") and token and \
                    (await self._rename_marker_state(token)
                     ).get("committed"):
                # a RETRY of this very request (token committed);
                # a fresh link() to an occupied name is EEXIST like
                # the same-rank path — treating it as done would
                # double-count nlink at the primary's finish
                return {"dentry": dst}
            raise MDSError(EEXIST, dn)
        except MDSError as e:
            if not e.missing_dentry:
                raise
        qroots = await self._quota_check(dp, add_files=1)
        entry = {"op": "import_link", "parent": dp, "name": dn,
                 "ino": int(dict(d["remote_dentry"])["ino"]),
                 "remote_dentry": dict(d["remote_dentry"]),
                 "token": token}
        await self._journal(entry)
        await self._apply(entry)
        self._quota_charge(qroots, files=1)
        if token:
            state = await self._rename_marker_state(token)
            if not state.get("committed"):
                raise MDSError(EXDEV,
                               "link aborted by the source rank")
        return {"dentry": dict(d["remote_dentry"])}

    async def _req_unlink(self, d: dict) -> dict:
        """Unlink — self-managed locking: a remote dentry whose
        primary lives on another rank runs the witness-lite
        update_primary protocol (nlink/anchor mutate on the primary's
        rank, name removal here), releasing the lock across the RPC."""
        parent, name = int(d["parent"]), str(d["name"])
        cross = None
        async with self._mutate:
            # re-check: a balancer export may have moved authority
            # while this op queued on the lock
            await self._check_auth(d, "unlink")
            self._guard_busy((parent, name))
            dentry = await self._get_dentry(parent, name)
            if dentry["type"] == "dir":
                raise MDSError(EISDIR, name)
            ino = int(dentry["ino"])
            if dentry.get("remote"):
                rec = await self._anchor_get(ino)
                if rec is not None:
                    pp, pn = int(rec["primary"][0]), \
                        str(rec["primary"][1])
                    prim_rank = await self._auth_rank(pp)
                    if prim_rank != self.rank:
                        token = secrets.token_hex(8)
                        await self._journal({
                            "op": "unlink_remote_intent",
                            "parent": parent, "name": name,
                            "ino": ino, "pp": pp, "pn": pn,
                            "token": token})
                        self._busy_names.add((parent, name))
                        cross = (token, prim_rank, pp)
            elif int(dentry.get("nlink", 1)) > 1:
                # unlinking a PRIMARY whose first remote lives on a
                # foreign rank: the promotion (primary dentry + anchor
                # move to the remote's rank) runs the witness-lite
                # two-phase protocol instead of declining (round-3
                # weak #5 closed for the direct-unlink case)
                rec = await self._anchor_get(ino)
                if rec is not None and rec["remotes"]:
                    np, nn = int(rec["remotes"][0][0]), \
                        str(rec["remotes"][0][1])
                    rem_rank = await self._auth_rank(np)
                    if rem_rank != self.rank:
                        nl = int(dentry.get("nlink", 1))
                        promoted = dict(dentry)
                        promoted["nlink"] = nl - 1
                        promoted.pop("remote", None)
                        new_rec = await self._anchor_next(
                            ino, None if nl - 1 <= 1 else
                            {"primary": [np, nn],
                             "remotes": rec["remotes"][1:]})
                        token = secrets.token_hex(8)
                        await self._journal({
                            "op": "promote_export_intent",
                            "parent": parent, "name": name,
                            "ino": ino, "np": np, "nn": nn,
                            "token": token})
                        self._busy_names.add((parent, name))
                        cross = ("promote", token, rem_rank, np, nn,
                                 promoted, new_rec)
            if cross is None:
                await self._plan_unlink_guard(dentry)
                entry = await self._unlink_plan(parent, name, dentry)
                await self._journal(entry)
                await self._apply(entry)
                if entry["op"] == "promote_link":
                    # the primary dentry (and its bytes) moved into
                    # the promoted remote's directory: realms crossed
                    self._quota_invalidate()
                else:
                    self._quota_charge(
                        await self._quota_roots(parent), files=-1,
                        nbytes=-(int(entry.get("size", 0))
                                 if entry["op"] == "unlink" else 0))
                await self._maybe_compact()
                return {"ino": ino}
        if cross[0] == "promote":
            _, token, rem_rank, np, nn, promoted, new_rec = cross
            try:
                return await self._promote_export_cross(
                    parent, name, ino, rem_rank, np, nn, promoted,
                    new_rec, token)
            finally:
                self._busy_names.discard((parent, name))
        token, prim_rank, pp = cross
        try:
            return await self._unlink_remote_cross(
                parent, name, ino, pp, prim_rank, token)
        finally:
            self._busy_names.discard((parent, name))

    async def _promote_export_cross(self, parent: int, name: str,
                                    ino: int, rem_rank: int, np: int,
                                    nn: str, promoted: dict,
                                    new_rec, token: str) -> dict:
        """Cross-rank link promotion: the remote's rank adopts the
        primary dentry + anchor under the commit claim; this rank's
        finish drops the old primary NAME only (the inode lives on
        under the new primary — no purge)."""
        await self._two_phase_finish(
            rem_rank,
            {"op": "import_promoted", "parent": np, "name": nn,
             "ino": ino, "primary_dentry": promoted,
             "anchor": new_rec, "token": token},
            token,
            {"op": "promote_export_abort", "ino": ino,
             "token": token},
            {"op": "promote_export_finish", "parent": parent,
             "name": name, "ino": ino, "token": token},
            "remote rank unreachable; unlink rolled back")
        # the primary (and its bytes) moved into the remote's realm
        self._quota_invalidate()
        return {"ino": ino}

    async def _req_import_promoted(self, d: dict) -> dict:
        """Peer half of the cross-rank promotion (routed by the remote
        name's parent, so _check_auth enforces OUR authority): replace
        the remote dentry with the promoted primary, adopt the anchor.
        Claim-gated exactly like import_dentry/import_link."""
        np, nn = int(d["parent"]), str(d["name"])
        token = str(d.get("token", ""))
        try:
            cur = await self._get_dentry(np, nn)
        except MDSError as e:
            if not e.missing_dentry:
                raise
            raise MDSError(ENOENT, f"remote name {nn!r} vanished")
        if int(cur.get("ino", 0)) != int(d["ino"]):
            raise MDSError(EINVAL,
                           "dentry no longer names the expected inode")
        if not cur.get("remote"):
            return {"dentry": cur}      # retried import: already done
        entry = {"op": "import_promoted", "parent": np, "name": nn,
                 "ino": int(d["ino"]),
                 "primary_dentry": dict(d["primary_dentry"]),
                 "anchor": d.get("anchor"), "token": token}
        await self._journal(entry)
        await self._apply(entry)
        self._quota_invalidate()
        if token:
            state = await self._rename_marker_state(token)
            if not state.get("committed"):
                raise MDSError(EXDEV,
                               "promotion aborted by the source rank")
        return {"dentry": dict(d["primary_dentry"])}

    async def _unlink_remote_cross(self, parent: int, name: str,
                                   ino: int, pp: int, prim_rank: int,
                                   token: str) -> dict:
        await self._two_phase_finish(
            prim_rank,
            {"op": "update_primary", "parent": pp, "ino": ino,
             "drop_remote": [parent, name], "token": token},
            token,
            {"op": "unlink_remote_abort", "ino": ino,
             "token": token},
            {"op": "unlink_remote_finish", "parent": parent,
             "name": name, "ino": ino, "token": token},
            "primary rank unreachable; unlink rolled back")
        self._quota_charge(await self._quota_roots(parent), files=-1)
        return {"ino": ino}

    async def _req_update_primary(self, d: dict) -> dict:
        """Cross-rank remote-unlink, PRIMARY half: decrement nlink and
        drop the remote name from the anchor, gated by the commit
        marker (slave-commit role).  Routed by ``parent`` (the
        primary's directory) so this rank's authority is enforced;
        runs under the normal handler lock."""
        ino = int(d["ino"])
        drop = [int(d["drop_remote"][0]), str(d["drop_remote"][1])]
        token = str(d.get("token", ""))
        rec = await self._anchor_get(ino)
        if rec is None:
            raise MDSError(ENOENT, f"no anchor for {ino:x}")
        pp, pn = int(rec["primary"][0]), str(rec["primary"][1])
        self._guard_busy((pp, pn))
        primary = dict(await self._get_dentry(pp, pn))
        remotes = [[int(r[0]), str(r[1])] for r in rec["remotes"]]
        if drop not in remotes:
            # retried request whose first attempt already applied
            if token and (await self._rename_marker_state(token)
                          ).get("committed"):
                return {"dentry": primary}
            raise MDSError(ENOENT, f"{drop} not a link of {ino:x}")
        nl = int(primary.get("nlink", 1)) - 1
        primary["nlink"] = nl
        kept = [r for r in remotes if r != drop]
        anchor = await self._anchor_next(
            ino, None if nl <= 1 else
            {"primary": [pp, pn], "remotes": kept})
        entry = {"op": "update_primary", "pp": pp, "pn": pn,
                 "ino": ino, "primary_dentry": primary,
                 "anchor": anchor, "token": token}
        await self._journal(entry)
        await self._apply(entry)
        if token:
            state = await self._rename_marker_state(token)
            if not state.get("committed"):
                raise MDSError(EXDEV,
                               "unlink aborted by the remote's rank")
        return {"dentry": primary}

    async def _req_rmdir(self, d: dict) -> dict:
        parent, name = int(d["parent"]), str(d["name"])
        self._guard_busy((parent, name))
        dentry = await self._get_dentry(parent, name)
        if dentry["type"] != "dir":
            raise MDSError(ENOTDIR, name)
        if int(dentry["ino"]) in self._subtrees:
            raise MDSError(EBUSY, f"{name!r} is a subtree export root")
        kv = await self._dir_all(int(dentry["ino"]))
        if kv:
            raise MDSError(ENOTEMPTY, name)
        entry = {"op": "rmdir", "parent": parent, "name": name,
                 "ino": int(dentry["ino"])}
        await self._journal(entry)
        await self._apply(entry)
        self._quota_charge(await self._quota_roots(parent), files=-1)
        return {}

    async def _is_ancestor(self, ino: int, of: int) -> bool:
        """Walk ``of``'s parent chain to the root looking for ``ino``
        (Server::handle_client_rename's subtree check)."""
        cur = of
        hops = 0
        while cur != ROOT_INO and hops < 4096:
            if cur == ino:
                return True
            try:
                raw = await self.meta.get_xattr(dirfrag_oid(cur),
                                                "parent")
            except RadosError:
                return False
            cur = int(raw)
            hops += 1
        return cur == ino

    async def _req_import_dentry(self, d: dict) -> dict:
        """Cross-rank rename, DESTINATION half (witness-lite slave
        request, reference Server::handle_slave_rename_prep role):
        link an existing inode's dentry into a directory this rank is
        authoritative over, applying POSIX rename overwrite semantics
        to any existing destination.  Routed by ``parent`` so
        _check_auth enforces authority; journaled locally."""
        dp, dn = int(d["parent"]), str(d["name"])
        dentry = dict(d["dentry"])
        token = str(d.get("token", ""))
        is_dir = dentry.get("type") == "dir"
        if is_dir:
            # destination-side re-validation with THIS rank's view:
            # the source checked too, but its snap table only holds
            # realms it serves — a snapshot rooted in OUR territory is
            # invisible to it
            if await self._covering_snaps(dp):
                raise MDSError(
                    EXDEV, "cross-rank directory rename under a "
                    "live snapshot")
            if await self._is_ancestor(int(dentry["ino"]), dp):
                raise MDSError(EINVAL,
                               "cannot move a directory into itself")
        purge_ino = purge_size = purge_dir_ino = 0
        unlinked_ino = 0
        pre = None
        try:
            dst = await self._get_dentry(dp, dn)
        except MDSError as e:
            if not e.missing_dentry:
                raise
            dst = None
        if dst is not None:
            if is_dir:
                if dst["type"] != "dir":
                    raise MDSError(ENOTDIR, dn)
                if int(dst["ino"]) == int(dentry["ino"]):
                    if token and not (await self._rename_marker_state(
                            token)).get("committed"):
                        # FRESH request, not a retry: a same-ino dst
                        # appeared — acking without committing would
                        # make the source drop its name (orphan)
                        raise MDSError(EEXIST,
                                       f"{dn!r} already names the "
                                       "inode")
                    return {"dentry": dst}  # retried import: done
                if int(dst["ino"]) in self._subtrees:
                    raise MDSError(
                        EBUSY, f"{dn!r} is a subtree export root")
                if await self._dir_all(int(dst["ino"])):
                    raise MDSError(ENOTEMPTY, dn)
                purge_dir_ino = int(dst["ino"])   # replaced empty dir
            elif dst["type"] == "dir":
                raise MDSError(EISDIR, dn)
            elif int(dst["ino"]) == int(dentry["ino"]):
                if token and not (await self._rename_marker_state(
                        token)).get("committed"):
                    raise MDSError(EEXIST,
                                   f"{dn!r} already names the inode")
                return {"dentry": dst}      # retried import: done
            elif dst.get("remote") or int(dst.get("nlink", 1)) > 1:
                # replaced hardlinked dst: the link-aware unlink rides
                # INSIDE the import entry so it only applies once the
                # commit claim wins (an aborted import must not have
                # unlinked anything)
                await self._plan_unlink_guard(dst)
                unlinked_ino = int(dst["ino"])
                pre = await self._unlink_plan(dp, dn, dst)
            else:
                unlinked_ino = int(dst["ino"])
                purge_ino = int(dst["ino"])
                purge_size = int(dst.get("size", 0))
        await self._quota_check(
            dp, add_files=1,
            add_bytes=int(dentry.get("size", 0))
            if dentry.get("type") == "file" else 0)
        entry = {"op": "import_dentry", "parent": dp, "name": dn,
                 "ino": int(dentry["ino"]), "dentry": dentry,
                 "purge_ino": purge_ino, "purge_size": purge_size,
                 "purge_dir_ino": purge_dir_ino,
                 "token": token, "pre": pre,
                 "anchor": d.get("anchor"),
                 "anchor_ino": int(d.get("anchor_ino", 0) or 0)}
        await self._journal(entry)
        await self._apply(entry)
        self._quota_invalidate()
        if token:
            state = await self._rename_marker_state(token)
            if not state.get("committed"):
                # the source claimed abort first (resolved a timeout):
                # _apply skipped the link; tell the (possibly still
                # listening) source the rename did not happen
                raise MDSError(EXDEV,
                               "rename aborted by the source rank")
        return {"dentry": dentry, "unlinked_ino": unlinked_ino}

    def _guard_busy(self, *pairs: tuple[int, str]) -> None:
        """Mutations on a (parent, name) with a cross-rank rename in
        flight get EBUSY: the source name must stay stable while the
        export protocol runs WITHOUT the rank-wide mutate lock held
        across the peer RPC (the slave-request xlock role)."""
        for pair in pairs:
            if pair in self._busy_names:
                raise MDSError(
                    EBUSY, f"{pair[1]!r}: cross-rank rename in flight")

    async def _rename_cross_rank(self, d: dict,
                                 dst_rank: int) -> dict:
        """Cross-rank FILE rename (witness-lite): journal an export
        intent, ask the destination rank to import the dentry, then
        unlink the source name.  The mutate lock is NOT held across
        the peer RPC — the source name is pinned by the busy-names
        guard instead, so the rank keeps serving.  A dangling intent
        resolves by the atomic commit marker (the slave-commit /
        rollback decision, reference rename two-phase).  DIRECTORY
        renames ride the same protocol (authority follows the new
        ancestry chain; Migrator.h:50 rename-export role) behind the
        invariant checks below.  Hardlinked PRIMARY renames move too:
        the versioned anchor's primary pointer rides the import under
        its commit claim (r5); only REMOTE names headed to a third
        rank still decline.

        Caller holds the mutate lock for THIS phase (validate +
        intent); it is released before the RPC and re-taken for the
        finish."""
        sp, sn = int(d["src_parent"]), str(d["src_name"])
        dp, dn = int(d["dst_parent"]), str(d["dst_name"])
        dentry = await self._get_dentry(sp, sn)
        try:
            dst0 = await self._get_dentry(dp, dn)
        except MDSError as e:
            if not e.missing_dentry and e.rc != ENOENT:
                raise
            dst0 = None
        if dst0 is not None and \
                int(dst0.get("ino", 0)) == int(dentry["ino"]):
            # POSIX: renaming onto another name of the SAME inode does
            # nothing — running the protocol would let the import's
            # retried-request short-circuit ack without committing and
            # the finish would then orphan the inode by dropping the
            # source name
            return {"noop": dict(dentry)}
        if dentry.get("type") == "dir":
            # cross-rank DIRECTORY rename: the same two-phase protocol
            # works because dirfrags live in shared RADOS — only the
            # dentry, the parent back-pointer, and AUTHORITY move.
            # Refuse the shapes whose invariants span ranks:
            ino_d = int(dentry["ino"])
            if ino_d in self._subtrees:
                raise MDSError(EBUSY,
                               f"{sn!r} is a subtree export root")
            for s in self._subtrees:
                if s != ino_d and await self._is_ancestor(ino_d, s):
                    raise MDSError(
                        EXDEV, "a delegated subtree boundary lies "
                        "inside the moved directory")
            await self._check_no_boundary_anchors(ino_d)
            if await self._covering_snaps(ino_d) \
                    or await self._covering_snaps(dp):
                raise MDSError(
                    EXDEV, "cross-rank directory rename under a "
                    "live snapshot")
            if await self._is_ancestor(ino_d, dp):
                raise MDSError(EINVAL,
                               "cannot move a directory into itself")
        elif dentry.get("remote"):
            # moving a REMOTE name into a third rank's directory would
            # nest the anchor repoint (primary's rank) inside the
            # dentry import (destination rank) — a three-party
            # protocol; rename it within its own rank or unlink+relink
            raise MDSError(EXDEV,
                           "moves a remote name across a rank "
                           "boundary; rename within its rank or "
                           "unlink + relink")
        anchor = None
        anchor_ino = 0
        if int(dentry.get("nlink", 1)) > 1:
            # hardlinked PRIMARY moving ranks (formerly declined): the
            # anchor's primary pointer must follow the inode.  The
            # versioned record (put-if-newer + tombstones) makes the
            # write replay-safe from EITHER rank's journal, and the
            # destination applies it under the same commit claim that
            # gates the dentry — an aborted rename leaves the anchor
            # untouched.  Remote names elsewhere stay valid: they
            # resolve by ino through this record.
            rec = await self._anchor_get(int(dentry["ino"]))
            if rec is not None:
                anchor_ino = int(dentry["ino"])
                anchor = await self._anchor_next(anchor_ino, {
                    "primary": [dp, dn],
                    "remotes": [[int(r[0]), str(r[1])]
                                for r in rec.get("remotes", ())],
                })
        token = secrets.token_hex(8)
        intent = {"op": "rename_export_intent", "src_parent": sp,
                  "src_name": sn, "dst_parent": dp, "dst_name": dn,
                  "ino": int(dentry["ino"]), "dentry": dentry,
                  "token": token, "anchor": anchor,
                  "anchor_ino": anchor_ino}
        await self._journal(intent)
        self._busy_names.add((sp, sn))
        return {"_phase2": (d, dst_rank, token, dentry, anchor,
                            anchor_ino)}

    async def _two_phase_finish(self, dst_rank: int, payload: dict,
                                token: str, abort_entry: dict,
                                finish_entry: dict,
                                unreachable: str) -> dict:
        """The shared skeleton of every witness-lite protocol's phases
        2+3 (caller does NOT hold the mutate lock): peer RPC (one
        redirect retry), then under the lock either the journaled
        finish, or — on an AMBIGUOUS no-reply — whatever the atomic
        abort-unless-committed claim decides (exactly one winner; the
        peer may have committed before dying).  Returns the peer
        reply ({"rc": 0} when resolved committed)."""
        reply = None
        try:
            reply = await self._peer_request(dst_rank, payload,
                                             timeout=5.0)
            if int(reply.get("rc", EXDEV)) != 0 and \
                    reply.get("redirect_rank") is not None:
                # target subtree moved mid-flight: one retry at the
                # rank the redirect names
                reply = await self._peer_request(
                    int(reply["redirect_rank"]), payload, timeout=5.0)
        except MDSError:
            reply = None
        async with self._mutate:
            if reply is None:
                committed = await self._rename_resolve_abort(token)
                if not committed:
                    await self._journal(abort_entry)
                    raise MDSError(EXDEV, unreachable)
                reply = {"rc": 0}       # committed after all
            elif int(reply.get("rc", EXDEV)) != 0:
                # unambiguous refusal from the peer
                await self._journal(abort_entry)
                raise MDSError(int(reply.get("rc", EXDEV)),
                               str(reply.get("err", "peer refused")))
            await self._journal(finish_entry)
            await self._apply(finish_entry)
        await self._rename_clear(token)
        return reply

    async def _rename_cross_rank_finish(self, phase1: dict) -> dict:
        """Phases 2+3: peer RPC WITHOUT the mutate lock, then the
        journaled finish/abort under it (caller manages locks)."""
        (d, dst_rank, token, dentry, anchor,
         anchor_ino) = phase1["_phase2"]
        sp, sn = int(d["src_parent"]), str(d["src_name"])
        dp, dn = int(d["dst_parent"]), str(d["dst_name"])
        reply = await self._two_phase_finish(
            dst_rank,
            {"op": "import_dentry", "parent": dp, "name": dn,
             "dentry": dentry, "token": token,
             "anchor": anchor, "anchor_ino": anchor_ino},
            token,
            {"op": "rename_export_abort", "src_parent": sp,
             "src_name": sn, "ino": int(dentry["ino"]),
             "token": token},
            {"op": "rename_export_finish", "src_parent": sp,
             "src_name": sn, "ino": int(dentry["ino"]),
             "token": token},
            "destination rank unreachable; rename rolled back")
        return {"dentry": dentry,
                "unlinked_ino": int(reply.get("unlinked_ino", 0))}

    async def _req_rename(self, d: dict) -> dict:
        """Rename entry point — manages its own locking: same-rank
        renames run wholly under the mutate lock; cross-rank renames
        hold it only for the intent and finish phases, pinning the
        source name with the busy guard across the peer RPC."""
        sp, sn = int(d["src_parent"]), str(d["src_name"])
        dp, dn = int(d["dst_parent"]), str(d["dst_name"])
        repoint = None
        async with self._mutate:
            # re-check: a balancer export may have moved authority
            # while this op queued on the lock
            await self._check_auth(d, "rename")
            self._guard_busy((sp, sn), (dp, dn))
            dst_rank = await self._auth_rank(dp)
            if dst_rank == self.rank:
                repoint = await self._maybe_repoint_remote(d)
                if repoint is None:
                    result = await self._rename_same_rank(d)
                    await self._maybe_compact()
                    return result
            else:
                phase1 = await self._rename_cross_rank(d, dst_rank)
                if "noop" in phase1:
                    # POSIX rename between two names of one inode
                    return {"dentry": phase1["noop"]}
        if repoint is not None:
            if isinstance(repoint, dict) and "noop" in repoint:
                # POSIX rename between two names of one inode
                return {"dentry": repoint["noop"]}
            try:
                return await self._repoint_remote_finish(repoint)
            finally:
                self._busy_names.discard((sp, sn))
                self._busy_names.discard((dp, dn))
                for pin in repoint[-1]:
                    self._busy_names.discard(pin)
        try:
            return await self._rename_cross_rank_finish(phase1)
        finally:
            self._busy_names.discard((sp, sn))

    async def _maybe_repoint_remote(self, d: dict):
        """Rename of a REMOTE name whose primary lives on a foreign
        rank (round-3 weak #5): the anchor repoint runs as a claim-
        gated peer op on the primary's rank, then the name moves here.
        Returns the phase-1 state, or None for every other rename
        shape (caller holds the mutate lock).  A destination with a
        LOCAL teardown is replaced (the plan rides the claim-gated
        finish, r5); only a destination needing its own foreign-rank
        teardown still declines."""
        sp, sn = int(d["src_parent"]), str(d["src_name"])
        dp, dn = int(d["dst_parent"]), str(d["dst_name"])
        if (sp, sn) == (dp, dn):
            return None
        dentry = await self._get_dentry(sp, sn)
        if not dentry.get("remote"):
            return None
        ino = int(dentry["ino"])
        rec = await self._anchor_get(ino)
        if rec is None:
            return None
        pp, pn = int(rec["primary"][0]), str(rec["primary"][1])
        prim_rank = await self._auth_rank(pp)
        if prim_rank == self.rank:
            return None                  # same-rank path handles it
        # rename-REPLACING while repointing (formerly declined): a
        # destination whose teardown is LOCAL rides inside the
        # claim-gated finish entry, exactly like import_dentry's
        # ``pre`` — an aborted repoint must not have unlinked it.  A
        # destination needing its OWN foreign-rank teardown still
        # declines (_plan_unlink_guard): that would nest a second
        # two-phase protocol inside this one.
        purge_ino = purge_size = 0
        pre = None
        try:
            dst = await self._get_dentry(dp, dn)
        except MDSError as e:
            if not e.missing_dentry:
                raise
            dst = None
        if dst is not None:
            if dst.get("type") == "dir":
                raise MDSError(EISDIR, dn)
            if int(dst.get("ino", 0)) == ino:
                # POSIX: renaming between two names of the same inode
                # does nothing (both names stay)
                return {"noop": dict(dentry)}
            await self._plan_unlink_guard(dst)
            if dst.get("remote") or int(dst.get("nlink", 1)) > 1:
                pre = await self._unlink_plan(dp, dn, dst)
            else:
                purge_ino = int(dst["ino"])
                purge_size = int(dst.get("size", 0))
        # the replaced destination's teardown plan holds ABSOLUTE
        # nlink/anchor values: the names it touches must stay pinned
        # across the unlocked RPC window or a concurrent link/unlink
        # on them would be clobbered at finish
        extra_pins = []
        if pre is not None and pre["op"] == "unlink_remote":
            extra_pins.append((int(pre["pp"]), str(pre["pn"])))
        elif pre is not None and pre["op"] == "promote_link":
            extra_pins.append((int(pre["np"]), str(pre["nn"])))
        token = secrets.token_hex(8)
        await self._journal({
            "op": "repoint_intent", "src_parent": sp, "src_name": sn,
            "dst_parent": dp, "dst_name": dn, "ino": ino,
            "dentry": dict(dentry), "token": token, "pre": pre,
            "purge_ino": purge_ino, "purge_size": purge_size})
        self._busy_names.add((sp, sn))
        self._busy_names.add((dp, dn))
        self._busy_names.update(extra_pins)
        return (token, prim_rank, pp, ino, sp, sn, dp, dn,
                dict(dentry), pre, purge_ino, purge_size, extra_pins)

    async def _repoint_remote_finish(self, phase1) -> dict:
        (token, prim_rank, pp, ino, sp, sn, dp, dn, dentry,
         pre, purge_ino, purge_size, extra_pins) = phase1
        await self._two_phase_finish(
            prim_rank,
            {"op": "repoint_remote", "parent": pp, "ino": ino,
             "old": [sp, sn], "new": [dp, dn], "token": token},
            token,
            {"op": "repoint_abort", "ino": ino, "token": token},
            {"op": "repoint_finish", "src_parent": sp,
             "src_name": sn, "dst_parent": dp, "dst_name": dn,
             "ino": ino, "dentry": dentry, "token": token,
             "pre": pre, "purge_ino": purge_ino,
             "purge_size": purge_size},
            "primary rank unreachable; rename rolled back")
        self._quota_invalidate()
        return {"dentry": dentry}

    async def _req_repoint_remote(self, d: dict) -> dict:
        """Primary-rank half of a remote-name rename: swap the name in
        the anchor's remotes list under the commit claim (routed by
        the primary's directory, so authority is enforced)."""
        ino = int(d["ino"])
        old = [int(d["old"][0]), str(d["old"][1])]
        new = [int(d["new"][0]), str(d["new"][1])]
        token = str(d.get("token", ""))
        rec = await self._anchor_get(ino)
        if rec is None:
            raise MDSError(ENOENT, f"no anchor for {ino:x}")
        pp, pn = int(rec["primary"][0]), str(rec["primary"][1])
        self._guard_busy((pp, pn))
        remotes = [[int(r[0]), str(r[1])] for r in rec["remotes"]]
        if old not in remotes:
            if new in remotes and token and (
                    await self._rename_marker_state(token)
            ).get("committed"):
                return {}               # retried request: already done
            raise MDSError(ENOENT, f"{old} not a link of {ino:x}")
        anchor = await self._anchor_next(ino, {
            "primary": [pp, pn],
            "remotes": [new if r == old else r for r in remotes],
        })
        entry = {"op": "repoint_remote", "ino": ino,
                 "anchor": anchor, "token": token}
        await self._journal(entry)
        await self._apply(entry)
        if token:
            state = await self._rename_marker_state(token)
            if not state.get("committed"):
                raise MDSError(EXDEV,
                               "repoint aborted by the name's rank")
        return {}

    async def _rename_same_rank(self, d: dict) -> dict:
        sp, sn = int(d["src_parent"]), str(d["src_name"])
        dp, dn = int(d["dst_parent"]), str(d["dst_name"])
        dentry = await self._get_dentry(sp, sn)
        if dentry.get("type") == "dir" \
                and int(dentry["ino"]) in self._subtrees:
            raise MDSError(EBUSY, f"{sn!r} is a subtree export root")
        unlinked_ino = 0
        if (sp, sn) == (dp, dn):
            # POSIX rename-to-self is a no-op — it must not purge the
            # live object's data blocks or dirfrag
            return {"dentry": dentry}
        if dentry["type"] == "dir" and \
                await self._is_ancestor(int(dentry["ino"]), dp):
            # renaming a directory into its own subtree would orphan it
            # as an unreachable cycle
            raise MDSError(EINVAL, "cannot move a directory into itself")
        if dentry.get("remote"):
            # moving one name of a cross-rank link repoints an anchor
            # another rank owns: decline BEFORE any mutation (a failed
            # rename must leave the destination intact)
            rec0 = await self._anchor_get(int(dentry["ino"]))
            if rec0 is not None and await self._auth_rank(
                    int(rec0["primary"][0])) != self.rank:
                raise MDSError(EXDEV,
                               "renames one name of a cross-rank "
                               "link; unlink + relink instead")
        purge_ino = purge_size = purge_dir_ino = 0
        try:
            dst = await self._get_dentry(dp, dn)
            if dst["type"] == "dir":
                if dentry["type"] != "dir":
                    raise MDSError(EISDIR, dn)
                kv = await self._dir_all(int(dst["ino"]))
                if kv:
                    raise MDSError(ENOTEMPTY, dn)
                if int(dst["ino"]) != int(dentry["ino"]):
                    purge_dir_ino = int(dst["ino"])   # replaced empty dir
            elif dentry["type"] == "dir":
                raise MDSError(ENOTDIR, dn)
            elif int(dst["ino"]) == int(dentry["ino"]):
                # POSIX: renaming between two hard links of the same
                # file does NOTHING (both names stay)
                return {"dentry": dentry}
            else:
                unlinked_ino = int(dst["ino"])
                if dst.get("remote") or int(dst.get("nlink", 1)) > 1:
                    # replacing one name of a hardlinked file: run the
                    # link-aware unlink first — its data must survive
                    # under the other names
                    await self._plan_unlink_guard(dst)
                    pre = await self._unlink_plan(dp, dn, dst)
                    await self._journal(pre)
                    await self._apply(pre)
                else:
                    purge_ino = int(dst["ino"])   # overwritten file
                    purge_size = int(dst.get("size", 0))
        except MDSError as e:
            if not e.missing_dentry:
                raise
        anchor_ino, anchor = 0, None
        if dentry.get("remote") or int(dentry.get("nlink", 1)) > 1:
            # the moved name is one of a hardlinked file's names: its
            # anchortable pointer must follow the rename (the
            # cross-rank-link shape was already declined up top,
            # before any destination mutation)
            anchor_ino = int(dentry["ino"])
            rec = await self._anchor_get(anchor_ino)
            if rec is not None:
                if dentry.get("remote"):
                    anchor = await self._anchor_next(anchor_ino, {
                        "primary": rec["primary"], "remotes": [
                            ([dp, dn]
                             if [int(r[0]), str(r[1])] == [sp, sn]
                             else r) for r in rec["remotes"]
                        ]})
                else:
                    anchor = await self._anchor_next(anchor_ino, {
                        "primary": [dp, dn],
                        "remotes": rec["remotes"]})
            else:
                anchor_ino = 0
        if self.quotas:
            # admission into realms the move NET-GAINS (shared
            # ancestors see no change); matches the cross-rank
            # import_dentry check
            src_roots = set(await self._quota_roots(sp))
            gain = [q for q in await self._quota_roots(dp)
                    if q not in src_roots]
            if gain:
                await self._quota_check(
                    dp, add_files=1,
                    add_bytes=int(dentry.get("size", 0))
                    if dentry.get("type") == "file" else 0,
                    roots=gain)
        past_snaps: list[int] = []
        if dentry["type"] == "dir" and self.snaps:
            # realm membership at the OLD location must stick to the
            # moved subtree (SnapRealm past_parents): its descendants'
            # ancestry walk picks these up through this dirfrag
            past_snaps = await self._covering_snaps(int(dentry["ino"]))
        entry = {"op": "rename", "src_parent": sp, "src_name": sn,
                 "dst_parent": dp, "dst_name": dn, "dentry": dentry,
                 "ino": int(dentry["ino"]),
                 "purge_ino": purge_ino, "purge_size": purge_size,
                 "purge_dir_ino": purge_dir_ino,
                 "anchor_ino": anchor_ino, "anchor": anchor,
                 "past_snaps": past_snaps}
        await self._journal(entry)
        await self._apply(entry)
        # realms changed (cross-dir move) or an overwrite purged the
        # destination (same-dir too): recount lazily
        self._quota_invalidate()
        return {"dentry": dentry, "unlinked_ino": unlinked_ino}

    async def _req_setattr(self, d: dict) -> dict:
        """Setattr — self-managed locking: an attr flush against a
        remote whose primary lives on another rank is FORWARDED there
        (that rank's journal + lock own the primary's dirfrag; writing
        it from here would race them), with our lock released across
        the RPC."""
        forward_rank = None
        async with self._mutate:
            await self._check_auth(d, "setattr")
            parent, name = int(d["parent"]), str(d["name"])
            self._guard_busy((parent, name))
            dentry = await self._get_dentry(parent, name)
            if dentry.get("remote"):
                parent, name, dentry = await self._primary_of(
                    int(dentry["ino"]))
                prim_rank = await self._auth_rank(parent)
                if prim_rank != self.rank:
                    forward_rank = prim_rank
                else:
                    self._guard_busy((parent, name))
            if forward_rank is None:
                old_size = int(dentry.get("size", 0))
                for key in ("size", "mode"):
                    if key in d and d[key] is not None:
                        dentry[key] = int(d[key])
                dentry["mtime"] = float(d.get("mtime", time.time()))
                delta = int(dentry.get("size", 0)) - old_size
                qroots = await self._quota_check(
                    parent, add_bytes=max(0, delta))
                entry = {"op": "setattr", "parent": parent,
                         "name": name, "ino": int(dentry["ino"]),
                         "dentry": dentry}
                await self._journal(entry)
                await self._apply(entry)
                self._quota_charge(qroots, nbytes=delta)
                await self._maybe_compact()
                return {"dentry": dentry}
        payload = {**{k: d[k] for k in ("size", "mode", "mtime")
                      if k in d},
                   "op": "setattr", "parent": parent, "name": name}
        reply = await self._peer_request(forward_rank, payload,
                                         timeout=5.0)
        if int(reply.get("rc", EXDEV)) != 0 and \
                reply.get("redirect_rank") is not None:
            # the primary's subtree moved between resolution and the
            # RPC (balancer export): one retry where the redirect says
            reply = await self._peer_request(
                int(reply["redirect_rank"]), payload, timeout=5.0)
        if int(reply.get("rc", EXDEV)) != 0:
            raise MDSError(int(reply.get("rc", EXDEV)),
                           str(reply.get("err", "setattr failed")))
        return {"dentry": dict(reply["dentry"])}
