"""ceph-objectstore-tool: offline object-store inspection.

The role of reference src/tools/ceph_objectstore_tool.cc: operate
directly on a stopped OSD's store directory — list collections and
objects, dump one object's data/attrs/omap, export/import an object —
without any cluster running.  Works on a WalStore directory (checkpoint
+ WAL replay happens at mount, exactly as the OSD would).

Usage:
    python -m ceph_tpu.objectstore_tool --data-path run/osd.0 \
        --op list
    python -m ceph_tpu.objectstore_tool --data-path run/osd.0 \
        --op dump --pool 1 --ps 3 --name obj-7
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import sys

from ceph_tpu.store.types import NO_GEN, NO_SHARD, CollectionId, GHObject
from ceph_tpu.store.walstore import WalStore


# collection keys use CollectionId.__str__ (hex pg, the store's own
# naming) so listings cross-reference the on-disk collection names;
# --ps is therefore parsed as hex


def _oid_json(oid: GHObject) -> dict:
    out = {"name": oid.name}
    if oid.snap != -2:
        out["snap"] = oid.snap
    if oid.gen != NO_GEN:
        out["gen"] = oid.gen
    if oid.shard != NO_SHARD:
        out["shard"] = oid.shard
    return out


async def _run(args) -> int:
    store = WalStore(args.data_path)
    await store.mount()
    try:
        if args.op == "list":
            out = {}
            for cid in sorted(store.list_collections(),
                              key=lambda c: (c.pool, c.pg, c.shard)):
                out[str(cid)] = [
                    _oid_json(o) for o in store.list_objects(cid)
                ]
            print(json.dumps(out, indent=2))
            return 0
        if args.op in ("dump", "export"):
            cid = CollectionId(args.pool, args.ps, args.shard)
            oid = GHObject(args.pool, args.name, snap=args.snap,
                           shard=args.shard)
            data = store.read(cid, oid)
            if args.op == "export":
                sys.stdout.buffer.write(data)
                return 0
            print(json.dumps({
                "object": _oid_json(oid),
                "size": len(data),
                "data_b64": base64.b64encode(data).decode(),
                "attrs": {
                    k: base64.b64encode(v).decode()
                    for k, v in store.getattrs(cid, oid).items()
                },
                "omap": {
                    k: base64.b64encode(v).decode()
                    for k, v in store.omap_get(cid, oid).items()
                },
            }, indent=2))
            return 0
        if args.op == "info":
            colls = store.list_collections()
            n_objs = sum(len(store.list_objects(c)) for c in colls)
            print(json.dumps({
                "data_path": args.data_path,
                "backend": "native" if store.native else "python",
                "collections": len(colls),
                "objects": n_objs,
            }, indent=2))
            return 0
        print(f"unknown --op {args.op!r}", file=sys.stderr)
        return 2
    except KeyError as e:
        print(f"objectstore-tool: not found: {e}", file=sys.stderr)
        return 1
    finally:
        await store.umount()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ceph-objectstore-tool",
                                description=__doc__)
    p.add_argument("--data-path", required=True,
                   help="a WalStore directory (osd store_dir)")
    p.add_argument("--op", required=True,
                   choices=["list", "dump", "export", "info"])
    p.add_argument("--pool", type=int, default=0)
    p.add_argument("--ps", type=lambda s: int(s, 16),
               default=0, help="pg id (hex, as listed)")
    p.add_argument("--shard", type=int, default=NO_SHARD)
    p.add_argument("--snap", type=int, default=-2)
    p.add_argument("--name", default="")
    args = p.parse_args(argv)
    return asyncio.run(_run(args))


if __name__ == "__main__":
    sys.exit(main())
