"""ceph-objectstore-tool: offline object-store inspection.

The role of reference src/tools/ceph_objectstore_tool.cc: operate
directly on a stopped OSD's store directory — list collections and
objects, dump one object's data/attrs/omap, export/import an object —
without any cluster running.  Works on a WalStore directory (checkpoint
+ WAL replay happens at mount, exactly as the OSD would).

Usage:
    python -m ceph_tpu.objectstore_tool --data-path run/osd.0 \
        --op list
    python -m ceph_tpu.objectstore_tool --data-path run/osd.0 \
        --op dump --pool 1 --ps 3 --name obj-7
"""

from __future__ import annotations

import argparse
import asyncio
import base64
import json
import sys

from ceph_tpu.store.types import NO_GEN, NO_SHARD, CollectionId, GHObject
from ceph_tpu.store.walstore import WalStore


# collection keys use CollectionId.__str__ (hex pg, the store's own
# naming) so listings cross-reference the on-disk collection names;
# --ps is therefore parsed as hex

# The OSD's meta collection (mirrors OSDDaemon._SUPER_CID/_SUPER_OID/
# _MAPS_OID — asserted identical by tests): superblock omap plus the
# bounded OSDMap-epoch history monstore_tool harvests for rebuild.
META_CID = CollectionId(-1, 0)
SUPERBLOCK_OID = GHObject(-1, "_osd_superblock")
MAPS_OID = GHObject(-1, "_osd_maps")


def open_store(data_path: str):
    """Offline store for a stopped OSD's directory: sniff the on-disk
    layout — ``colls/`` marks a FileStore, anything else mounts as a
    WalStore (checkpoint + WAL replay, exactly as the OSD would)."""
    import os

    if os.path.isdir(os.path.join(data_path, "colls")):
        from ceph_tpu.store.filestore import FileStore

        return FileStore(data_path)
    return WalStore(data_path)


async def harvest_meta(data_path: str) -> dict:
    """Read one stopped OSD's DR-harvest material (the update-mon-db
    source): every persisted full OSDMap epoch, the superblock's
    pool->pg_num view, and the last rotating-service-secret snapshot.
    Returns {"epochs": {epoch: map_dict}, "pool_pg_num": {...},
    "service_secrets": {epoch: secret}}."""
    from ceph_tpu.msg.codec import decode

    store = open_store(data_path)
    await store.mount()
    try:
        out = {"epochs": {}, "pool_pg_num": {}, "service_secrets": {}}
        try:
            omap = store.omap_get(META_CID, MAPS_OID)
        except KeyError:
            omap = {}
        for k, v in omap.items():
            if k.startswith("full_"):
                out["epochs"][int(k[len("full_"):])] = decode(v)
            elif k == "service_secrets":
                out["service_secrets"] = {
                    int(e): str(s)
                    for e, s in json.loads(v).items()
                }
        try:
            sb = store.omap_get(META_CID, SUPERBLOCK_OID)
        except KeyError:
            sb = {}
        out["pool_pg_num"] = {int(k): int(v) for k, v in sb.items()}
        return out
    finally:
        await store.umount()


def _oid_json(oid: GHObject) -> dict:
    out = {"name": oid.name}
    if oid.snap != -2:
        out["snap"] = oid.snap
    if oid.gen != NO_GEN:
        out["gen"] = oid.gen
    if oid.shard != NO_SHARD:
        out["shard"] = oid.shard
    return out


async def _run(args) -> int:
    if args.op == "meta":
        meta = await harvest_meta(args.data_path)
        print(json.dumps({
            "data_path": args.data_path,
            "osdmap_epochs": sorted(meta["epochs"]),
            "newest_epoch": max(meta["epochs"], default=0),
            "pool_pg_num": meta["pool_pg_num"],
            "service_secret_epochs": sorted(meta["service_secrets"]),
        }, indent=2))
        return 0
    store = open_store(args.data_path)
    await store.mount()
    try:
        if args.op == "list":
            out = {}
            for cid in sorted(store.list_collections(),
                              key=lambda c: (c.pool, c.pg, c.shard)):
                out[str(cid)] = [
                    _oid_json(o) for o in store.list_objects(cid)
                ]
            print(json.dumps(out, indent=2))
            return 0
        if args.op in ("dump", "export"):
            cid = CollectionId(args.pool, args.ps, args.shard)
            oid = GHObject(args.pool, args.name, snap=args.snap,
                           shard=args.shard)
            data = store.read(cid, oid)
            if args.op == "export":
                sys.stdout.buffer.write(data)
                return 0
            print(json.dumps({
                "object": _oid_json(oid),
                "size": len(data),
                "data_b64": base64.b64encode(data).decode(),
                "attrs": {
                    k: base64.b64encode(v).decode()
                    for k, v in store.getattrs(cid, oid).items()
                },
                "omap": {
                    k: base64.b64encode(v).decode()
                    for k, v in store.omap_get(cid, oid).items()
                },
            }, indent=2))
            return 0
        if args.op == "info":
            colls = store.list_collections()
            n_objs = sum(len(store.list_objects(c)) for c in colls)
            print(json.dumps({
                "data_path": args.data_path,
                "backend": "native" if store.native else "python",
                "collections": len(colls),
                "objects": n_objs,
            }, indent=2))
            return 0
        print(f"unknown --op {args.op!r}", file=sys.stderr)
        return 2
    except KeyError as e:
        print(f"objectstore-tool: not found: {e}", file=sys.stderr)
        return 1
    finally:
        await store.umount()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ceph-objectstore-tool",
                                description=__doc__)
    p.add_argument("--data-path", required=True,
                   help="a WalStore directory (osd store_dir)")
    p.add_argument("--op", required=True,
                   choices=["list", "dump", "export", "info", "meta"])
    p.add_argument("--pool", type=int, default=0)
    p.add_argument("--ps", type=lambda s: int(s, 16),
               default=0, help="pg id (hex, as listed)")
    p.add_argument("--shard", type=int, default=NO_SHARD)
    p.add_argument("--snap", type=int, default=-2)
    p.add_argument("--name", default="")
    args = p.parse_args(argv)
    return asyncio.run(_run(args))


if __name__ == "__main__":
    sys.exit(main())
