"""RadosStriper: RAID-0 striping of large logical objects over RADOS.

The libradosstriper role (reference src/libradosstriper/
RadosStriperImpl.h:30) with the Striper layout math of osdc/Striper.h:26:
a logical object is block-cyclically split over ``stripe_count`` backing
objects of up to ``object_size`` bytes, ``stripe_unit`` bytes at a time;
backing objects are named ``<name>.%016x`` and the logical size lives in
an xattr of the first one — the same on-disk convention as the reference,
so striped layouts are structurally comparable.
"""

from __future__ import annotations

from ceph_tpu.client.rados import IoCtx, ObjectOperation, RadosError

SIZE_XATTR = "striper.size"


class StripeLayout:
    def __init__(self, stripe_unit: int = 64 * 1024, stripe_count: int = 4,
                 object_size: int = 4 * 1024 * 1024):
        if object_size % stripe_unit:
            raise ValueError("object_size must be a stripe_unit multiple")
        self.su = stripe_unit
        self.sc = stripe_count
        self.os = object_size
        self.stripes_per_object = object_size // stripe_unit

    def map_extent(self, off: int, length: int):
        """Yield (objectno, obj_off, length) per touched stripe fragment
        (Striper::file_to_extents semantics)."""
        pos = off
        end = off + length
        while pos < end:
            blockno = pos // self.su           # global stripe-unit index
            stripeno = blockno // self.sc
            stripepos = blockno % self.sc      # which object column
            objectsetno = stripeno // self.stripes_per_object
            objectno = objectsetno * self.sc + stripepos
            block_off = pos % self.su
            obj_off = (stripeno % self.stripes_per_object) * self.su \
                + block_off
            run = min(self.su - block_off, end - pos)
            yield objectno, obj_off, run
            pos += run


class RadosStriper:
    def __init__(self, ioctx: IoCtx, layout: StripeLayout | None = None):
        self.ioctx = ioctx
        self.layout = layout or StripeLayout()

    @staticmethod
    def _obj(name: str, objectno: int) -> str:
        return f"{name}.{objectno:016x}"

    async def _size(self, name: str) -> int:
        try:
            raw = await self.ioctx.get_xattr(self._obj(name, 0), SIZE_XATTR)
            return int(raw)
        except RadosError as e:
            if e.rc == -2:
                raise RadosError(-2, f"no striped object {name!r}") from e
            raise

    async def write(self, name: str, data: bytes, offset: int = 0) -> None:
        """Striped write + logical-size bump."""
        frags: dict[int, ObjectOperation] = {}
        pos = 0
        for objectno, obj_off, run in self.layout.map_extent(
            offset, len(data)
        ):
            op = frags.setdefault(objectno, ObjectOperation())
            op.write(data[pos:pos + run], obj_off)
            pos += run
        try:
            old = await self._size(name)
        except RadosError:
            old = 0
        new_size = max(old, offset + len(data))
        size_op = frags.setdefault(0, ObjectOperation())
        size_op.set_xattr(SIZE_XATTR, str(new_size).encode())
        for objectno, op in sorted(frags.items()):
            await self.ioctx.operate(self._obj(name, objectno), op)

    async def read(self, name: str, length: int | None = None,
                   offset: int = 0) -> bytes:
        size = await self._size(name)
        if length is None:
            length = max(0, size - offset)
        length = max(0, min(length, size - offset))
        if length == 0:
            return b""
        out = bytearray(length)
        pos = 0
        for objectno, obj_off, run in self.layout.map_extent(
            offset, length
        ):
            try:
                frag = await self.ioctx.read(
                    self._obj(name, objectno), run, obj_off
                )
            except RadosError as e:
                if e.rc != -2:
                    raise
                frag = b""
            frag = frag.ljust(run, b"\0")      # sparse regions read as 0
            out[pos:pos + run] = frag
            pos += run
        return bytes(out)

    async def stat(self, name: str) -> dict:
        return {"size": await self._size(name)}

    async def truncate(self, name: str, size: int) -> None:
        """Shrink: zero the dropped range so a later re-extension reads
        holes, not stale bytes (reads clamp to the logical size either
        way)."""
        old = await self._size(name)
        if size < old:
            for objectno, obj_off, run in self.layout.map_extent(
                size, old - size
            ):
                try:
                    await self.ioctx.write(
                        self._obj(name, objectno), b"\0" * run, obj_off
                    )
                except RadosError as e:
                    if e.rc != -2:
                        raise
        await self.ioctx.set_xattr(
            self._obj(name, 0), SIZE_XATTR, str(size).encode()
        )

    async def remove(self, name: str) -> None:
        """Remove every backing object. Enumerated from the pool, not
        derived from the logical size — truncation shrinks the size
        without deleting backing objects."""
        await self._size(name)              # ENOENT if never written
        prefix = f"{name}."
        backing = [
            obj for obj in await self.ioctx.list_objects()
            if obj.startswith(prefix) and len(obj) == len(name) + 17
        ]
        # first object last: its size xattr marks existence
        first = self._obj(name, 0)
        for obj in sorted(backing, key=lambda o: o == first):
            try:
                await self.ioctx.remove(obj)
            except RadosError as e:
                if e.rc != -2:
                    raise
