"""CephFS-lite client: the libcephfs role.

Reference src/client/Client.cc + include/cephfs/libcephfs.h reduced to
the -lite essentials: path resolution walks dentries via MDS lookups
with client-side lease caching (the read side of the caps model);
metadata mutations are MDS round-trips; FILE DATA is read/written
directly against the data pool (``<ino:x>.<blockno:08x>`` objects) —
the MDS never touches data. Open files buffer size/mtime and flush them
to the MDS on close/fsync (the Fc/Fw cap-flush reduced to
setattr-on-close).
"""

from __future__ import annotations

import asyncio
import time

from ceph_tpu.client.rados import IoCtx, ObjectOperation, Rados, RadosError
from ceph_tpu.mds.daemon import (
    EEXIST,
    EINVAL,
    EISDIR,
    ELOOP,
    ENOENT,
    ENOTDIR,
    EROFS,
    block_oid,
)
from ceph_tpu.msg.message import Message
from ceph_tpu.msg.messenger import Connection


class FSError(IOError):
    def __init__(self, rc: int, msg: str = ""):
        super().__init__(f"rc={rc} {msg}")
        self.rc = rc


class FileHandle:
    """An open file (Fh): direct data IO + deferred attr flush.

    With the exclusive write cap (``capped``, the Fw/Fb slice of the
    reference cap model) writes BUFFER client-side per block and flush
    on fsync/close/recall — the MDS recalls the cap when any other
    client opens the file, so readers see flushed bytes.  Without it,
    writes go straight to RADOS (write-through)."""

    def __init__(self, fs: "CephFS", parent: int, name: str,
                 dentry: dict, snapid: int = 0,
                 capped: bool = False):
        self.fs = fs
        self.parent = parent
        self.name = name
        self.ino = int(dentry["ino"])
        self.size = int(dentry.get("size", 0))
        self.snapid = snapid        # >0: read-only snapshot view
        self._dirty = False
        self._closed = False
        self._cap = capped
        self._buf: dict[int, bytearray] = {}   # blockno -> content
        # serializes buffer mutation against recall-driven flushes: a
        # write suspended in a block load must not slip its insert in
        # after the recall already flushed-and-cleared
        self._buf_lock = asyncio.Lock()

    # -- data path (never touches the MDS) -----------------------------
    def _extents(self, offset: int, length: int):
        bs = self.fs.block_size
        pos = offset
        end = offset + length
        while pos < end:
            blockno = pos // bs
            off = pos % bs
            run = min(bs - off, end - pos)
            yield blockno, off, run
            pos += run

    async def write(self, data: bytes, offset: int | None = None) -> int:
        if self._closed:
            raise FSError(EINVAL, "closed")
        if self.snapid:
            raise FSError(EROFS, "snapshots are read-only")
        if offset is None:
            offset = self.size
        buffered = False
        if self._cap:
            async with self._buf_lock:
                if self._cap:     # re-check: a recall may have won
                    buffered = True
                    pos = 0
                    for blockno, off, run in self._extents(
                            offset, len(data)):
                        blk = await self._load_block(blockno)
                        if len(blk) < off + run:
                            blk.extend(b"\x00" * (off + run
                                                  - len(blk)))
                        blk[off:off + run] = data[pos:pos + run]
                        pos += run
        if not buffered:
            pos = 0
            for blockno, off, run in self._extents(offset, len(data)):
                await self.fs.data.write(block_oid(self.ino, blockno),
                                         data[pos:pos + run], off)
                pos += run
        self.size = max(self.size, offset + len(data))
        self._dirty = True
        return len(data)

    async def _load_block(self, blockno: int) -> bytearray:
        blk = self._buf.get(blockno)
        if blk is None:
            try:
                blk = bytearray(await self.fs.data.read(
                    block_oid(self.ino, blockno)))
            except RadosError as e:
                if e.rc != ENOENT:
                    raise
                blk = bytearray()
            self._buf[blockno] = blk
        return blk

    async def _flush_buffer(self) -> None:
        async with self._buf_lock:
            for blockno in sorted(self._buf):
                await self.fs.data.write_full(
                    block_oid(self.ino, blockno),
                    bytes(self._buf[blockno]))
            self._buf.clear()

    async def read(self, length: int | None = None,
                   offset: int = 0) -> bytes:
        if length is None:
            length = self.size - offset
        length = max(0, min(length, self.size - offset))
        out = bytearray(length)
        pos = 0
        data_io = (await self.fs._snap_data(self.snapid)
                   if self.snapid else self.fs.data)
        for blockno, off, run in self._extents(offset, length):
            if blockno in self._buf:
                frag = bytes(self._buf[blockno][off:off + run])
            else:
                try:
                    frag = await data_io.read(
                        block_oid(self.ino, blockno), run, off
                    )
                except RadosError as e:
                    if e.rc != ENOENT:
                        raise
                    frag = b""          # sparse block reads as zeros
            out[pos:pos + len(frag)] = frag
            pos += run
        return bytes(out)

    async def truncate(self, size: int) -> None:
        if self.snapid:
            raise FSError(EROFS, "snapshots are read-only")
        await self._flush_buffer()      # buffered blocks first
        bs = self.fs.block_size
        if size < self.size:
            first_dead = -(-size // bs)
            last = -(-self.size // bs)
            for blockno in range(first_dead, last):
                try:
                    await self.fs.data.remove(block_oid(self.ino,
                                                        blockno))
                except RadosError as e:
                    if e.rc != ENOENT:
                        raise
            boundary = size % bs
            if boundary:
                try:
                    await self.fs.data.truncate(
                        block_oid(self.ino, size // bs), boundary
                    )
                except RadosError as e:
                    if e.rc != ENOENT:
                        raise
        self.size = size
        self._dirty = True

    async def fsync(self) -> None:
        """Flush buffered blocks, then buffered attrs (cap flush)."""
        await self._flush_buffer()
        if self._dirty:
            await self.fs._request("setattr", parent=self.parent,
                                   name=self.name, size=self.size,
                                   mtime=time.time())
            self._dirty = False
            self.fs._invalidate_ino(self.ino)
            self.fs._invalidate(self.parent, self.name)

    async def close(self) -> None:
        if not self._closed:
            await self.fsync()
            self._closed = True
            if self._cap:
                self._cap = False
                siblings = self.fs._open_caps.get(self.ino)
                if siblings is not None:
                    siblings.discard(self)
                    if siblings:
                        return    # another handle still uses the cap
                    self.fs._open_caps.pop(self.ino, None)
                try:
                    await self.fs._request("release_cap",
                                           parent=self.parent,
                                           name=self.name,
                                           ino=self.ino)
                except FSError:
                    pass          # MDS revoked/restarted: same end


class CephFS:
    """A mounted filesystem (ceph_mount)."""

    @classmethod
    async def connect(cls, rados: Rados, fs_name: str = "cephfs",
                   timeout: float = 10.0) -> "CephFS":
        """Discover the active MDS from the monitor's FSMap (``mds
        stat``) instead of a hardcoded address (the reference client's
        mdsmap subscription role)."""
        deadline = asyncio.get_running_loop().time() + timeout
        while True:
            r = await rados.mon_command("mds stat")
            if r["rc"] not in (0, -11):
                # only EAGAIN (no quorum yet) is transient; a cap
                # denial or unknown command must surface, not time out
                raise FSError(r["rc"], r["outs"])
            active = None
            if r["rc"] == 0:
                active = (r["data"]["filesystems"]
                          .get(fs_name, {}).get("active"))
            if active is not None:
                return cls(rados, active["addr"], fs_name=fs_name)
            if asyncio.get_running_loop().time() > deadline:
                raise FSError(
                    -110, f"no active mds for fs {fs_name!r}"
                )
            await asyncio.sleep(0.1)

    def __init__(self, rados: Rados, mds_addr: str,
                 fs_name: str = "cephfs"):
        self.rados = rados
        self.mds_addr = mds_addr
        self.fs_name = fs_name
        # rank -> address (multi-active; refreshed from mds stat on a
        # redirect to a rank we do not know yet)
        self._rank_addrs: dict[int, str] = {0: mds_addr}
        # rank -> snapids from that rank's last reply: each rank only
        # knows its own realms, so the data-pool snap context is the
        # UNION — a snap-unaware rank's reply must not regress it and
        # un-COW another rank's live snapshot
        self._snapc_by_rank: dict[int, set[int]] = {}
        self.root = 1
        self.block_size = 1 << 22
        self.data: IoCtx | None = None
        self.lease_ttl = 2.0
        self._futs: dict[int, asyncio.Future] = {}
        # (parent_ino, name) -> (dentry, lease expiry): the dentry lease
        # cache (Client::Dentry + lease_ttl role)
        self._dcache: dict[tuple[int, str], tuple[dict, float]] = {}
        self._snap_ioctx: dict[int, IoCtx] = {}
        # ino -> set of local FileHandles sharing the conn's exclusive
        # write cap (the MDS grant is per-session; the cap releases
        # only when the LAST handle closes)
        self._open_caps: dict[int, set] = {}
        self._mounted = False
        # session-unique tid space: two mounts sharing one rados
        # messenger must never mistake each other's replies
        import secrets as _secrets

        self._tid = _secrets.randbits(40) << 20
        # ride the rados client's messenger: register our reply hook,
        # CHAINING to whatever dispatcher is already installed (an
        # earlier CephFS mount or the rados client itself) so stacked
        # mounts on one handle all keep receiving their traffic
        self._prev_dispatcher = getattr(rados.msgr, "dispatcher",
                                        None) or rados
        self._orig_dispatch = self._prev_dispatcher.ms_dispatch
        rados.msgr.set_dispatcher(self)

    # -- dispatcher chaining ----------------------------------------------
    async def ms_dispatch(self, conn: Connection, msg: Message) -> None:
        if msg.type == "cap_recall":
            asyncio.get_running_loop().create_task(
                self._handle_cap_recall(conn,
                                        int(msg.data.get("ino", 0))))
            return
        if msg.type == "mds_reply":
            tid = int(msg.data.get("tid", 0))
            fut = self._futs.pop(tid, None)
            if fut is not None and not fut.done():
                fut.set_result(msg.data)
                return
            if fut is None and isinstance(self._prev_dispatcher,
                                          CephFS):
                # not ours: a stacked earlier mount may own this tid
                await self._orig_dispatch(conn, msg)
            return
        await self._orig_dispatch(conn, msg)

    async def _handle_cap_recall(self, conn: Connection,
                                 ino: int) -> None:
        """The MDS wants the write cap back: degrade every local
        handle to write-through FIRST (so racing writes stop
        buffering), then flush blocks and attrs, then ack."""
        for fh in self._open_caps.pop(ino, ()):
            fh._cap = False
            try:
                await fh.fsync()
            except (FSError, RadosError):
                pass              # revocation proceeds regardless
        try:
            conn.send_message(Message("cap_release", {"ino": ino}))
        except ConnectionError:
            pass

    def ms_handle_reset(self, conn: Connection) -> None:
        self.rados.ms_handle_reset(conn)

    def ms_handle_connect(self, conn: Connection) -> None:
        pass

    # -- mount / requests --------------------------------------------------
    async def mount(self, timeout: float = 20.0) -> None:
        reply = await self._request("session", timeout=timeout)
        self.root = int(reply["root"])
        self.block_size = int(reply["block_size"])
        self.lease_ttl = float(reply.get("lease", 2.0))
        self.data = await self.rados.open_ioctx(reply["data_pool"])
        self._mounted = True

    async def unmount(self) -> None:
        self._mounted = False
        if getattr(self.rados.msgr, "dispatcher", None) is self:
            # restore the dispatcher BELOW us; an unmount out of stack
            # order leaves our (inert, forwarding) hook in place
            # rather than cutting a still-live mount out of the chain
            self.rados.msgr.set_dispatcher(self._prev_dispatcher)

    async def _addr_for_rank(self, rank: int) -> str:
        addr = self._rank_addrs.get(rank)
        if addr is not None:
            return addr
        r = await self.rados.mon_command("mds stat")
        if r["rc"] == 0:
            for a in (r["data"]["filesystems"]
                      .get(self.fs_name, {}).get("actives", ())):
                self._rank_addrs[int(a["rank"])] = str(a["addr"])
        addr = self._rank_addrs.get(rank)
        if addr is None:
            raise FSError(-110, f"no active mds for rank {rank}")
        return addr

    async def _request(self, op: str, timeout: float = 30.0,
                       _addr: str | None = None, **args) -> dict:
        rank = 0
        for _hop in range(6):
            self._tid += 1
            tid = self._tid
            fut = asyncio.get_running_loop().create_future()
            self._futs[tid] = fut
            payload = {"tid": tid, "op": op, **args}
            if _hop >= 2:
                # ping-ponging between ranks: tell the server to skip
                # its subtree-map refresh throttle (a fresh export is
                # still propagating)
                payload["refresh_subtrees"] = True
            try:
                await self.rados.msgr.send_to(
                    _addr or self.mds_addr,
                    Message("mds_request", payload),
                    "mds.x",
                )
                reply = await asyncio.wait_for(fut, timeout)
            except (ConnectionError, asyncio.TimeoutError) as e:
                self._futs.pop(tid, None)
                if rank != 0 and _hop < 3:
                    # the redirected-to rank may have failed over to a
                    # new address: drop the cached addr and re-resolve
                    # from the fsmap before giving up
                    stale = self._rank_addrs.pop(rank, None)
                    try:
                        _addr = await self._addr_for_rank(rank)
                    except FSError:
                        raise FSError(-110,
                                      f"mds request {op}: {e}") from e
                    if _addr != stale:
                        continue
                raise FSError(-110, f"mds request {op}: {e}") from e
            if "redirect_rank" in reply:
                # the directory lives in another rank's subtree: retry
                # there (Client follows the mdsmap the same way)
                rank = int(reply["redirect_rank"])
                if _hop >= 2:
                    # ping-pong: either our cached addr is stale
                    # (failover) or the MDSs' subtree maps are still
                    # propagating a fresh export — refresh the addr and
                    # give their refresh throttles a beat
                    self._rank_addrs.pop(rank, None)
                    await asyncio.sleep(0.4)
                _addr = await self._addr_for_rank(rank)
                continue
            break
        if reply["rc"] != 0:
            raise FSError(reply["rc"], reply.get("err", op))
        snapc = reply.get("snapc")
        if snapc and self.data is not None:
            self._snapc_by_rank[rank] = {
                int(x) for x in snapc.get("snaps", ())}
            union = sorted(set().union(*self._snapc_by_rank.values()))
            self.data.set_snap_context(max(union, default=0), union)
        return reply

    # -- path walking ------------------------------------------------------
    def _invalidate(self, parent: int, name: str) -> None:
        self._dcache.pop((parent, name, 0), None)

    def _invalidate_ino(self, ino: int) -> None:
        """Drop every cached dentry of this inode: hard links give one
        inode several (parent, name) cache slots, and an attr flush
        through one name must not leave the others serving stale size."""
        for key in [k for k, v in self._dcache.items()
                    if int(v[0].get("ino", 0)) == ino]:
            self._dcache.pop(key, None)

    async def _lookup(self, parent: int, name: str,
                      snapid: int = 0) -> dict:
        cached = self._dcache.get((parent, name, snapid))
        if cached is not None and cached[1] > time.monotonic():
            return cached[0]
        reply = await self._request("lookup", parent=parent, name=name,
                                    snapid=snapid)
        dentry = reply["dentry"]
        self._dcache[(parent, name, snapid)] = (
            dentry, time.monotonic() + float(reply.get("lease", 0)),
        )
        return dentry

    @staticmethod
    def _split(path: str) -> list[str]:
        return [p for p in path.strip("/").split("/") if p]

    _MAX_SYMLINKS = 10             # ELOOP bound (SYMLOOP_MAX role)

    @staticmethod
    def _normalize(parts: list[str]) -> list[str]:
        """Lexical '.'/'..' collapse ('..' above root stays at root) —
        the MDS stores no dot dentries, so joins must resolve them."""
        out: list[str] = []
        for p in parts:
            if p == "." or not p:
                continue
            if p == "..":
                if out:
                    out.pop()
                continue
            out.append(p)
        return out

    @classmethod
    def _join_link(cls, base_parts: list[str], target: str,
                   rest: list[str]) -> str:
        """New path after substituting a symlink target: absolute
        targets restart at root, relative ones join the link's own
        directory; '.'/'..' collapse lexically."""
        if target.startswith("/"):
            parts = target.split("/") + rest
        else:
            parts = list(base_parts) + target.split("/") + rest
        return "/" + "/".join(cls._normalize(parts))

    async def _resolve_parent(self, path: str) -> tuple[int, str]:
        """Walk to the parent of ``path``; returns (parent_ino, name).
        Symlinks in intermediate components are followed."""
        parts = self._split(path)
        if not parts:
            raise FSError(EINVAL, "root has no parent")
        if len(parts) == 1:
            return self.root, parts[0]
        dirent = await self._resolve("/" + "/".join(parts[:-1]))
        if dirent["type"] != "dir":
            raise FSError(ENOTDIR, f"{path!r}: not a directory")
        return int(dirent["ino"]), parts[-1]

    async def _resolve(self, path: str, follow: bool = True,
                       _depth: int | None = None) -> dict:
        """Path walk with symlink traversal (Client::path_walk role):
        intermediate symlinks always follow; the FINAL component
        follows only when ``follow`` (stat vs lstat semantics)."""
        depth = self._MAX_SYMLINKS if _depth is None else _depth
        parts = self._split(path)
        if not parts:
            return {"ino": self.root, "type": "dir", "mode": 0o755,
                    "size": 0, "mtime": 0.0}
        ino = self.root
        snapid = 0
        i = 0
        while i < len(parts):
            part = parts[i]
            if part == ".snap":
                # entering the snapshot namespace of the CURRENT dir
                # (the CephFS .snap pseudo-directory): the next
                # component names the snapshot; everything after
                # resolves against the frozen dirfrags
                if snapid:
                    raise FSError(EINVAL, ".snap inside a snapshot")
                if i + 1 >= len(parts):
                    return {"ino": ino, "type": "dir", "mode": 0o555,
                            "size": 0, "mtime": 0.0, "snapdir": True,
                            "snap_of": ino}
                reply = await self._request("lssnap", ino=ino)
                info = reply["snaps"].get(parts[i + 1])
                if info is None:
                    raise FSError(ENOENT,
                                  f"no snapshot {parts[i + 1]!r}")
                snapid = int(info["snapid"])
                if i + 1 == len(parts) - 1:
                    return {"ino": ino, "type": "dir", "mode": 0o555,
                            "size": 0,
                            "mtime": float(info["created"]),
                            "snapid": snapid}
                i += 2
                continue
            dentry = await self._lookup(ino, part, snapid)
            last = i == len(parts) - 1
            if dentry["type"] == "symlink" and (follow or not last):
                if depth <= 0:
                    raise FSError(ELOOP, f"{path!r}: symlink loop")
                newpath = self._join_link(
                    parts[:i], str(dentry.get("target", "")),
                    parts[i + 1:],
                )
                return await self._resolve(newpath, follow,
                                           depth - 1)
            if not last:
                if dentry["type"] != "dir":
                    raise FSError(ENOTDIR,
                                  f"{part!r} is not a directory")
                ino = int(dentry["ino"])
            if snapid:
                dentry = {**dentry, "snapid": snapid}
            i += 1
        return dentry

    async def _snap_data(self, snapid: int) -> IoCtx:
        """A data-pool handle whose reads resolve at ``snapid``."""
        io = self._snap_ioctx.get(snapid)
        if io is None:
            io = await self.rados.open_ioctx(self.data.pool_name)
            io.snap_set_read(snapid)
            self._snap_ioctx[snapid] = io
        return io

    async def mksnap(self, path: str, name: str) -> int:
        """ceph_mksnap: snapshot the subtree at ``path`` (readable as
        ``path/.snap/name/...``)."""
        dentry = await self._resolve(path)
        if dentry["type"] != "dir":
            raise FSError(ENOTDIR, path)
        reply = await self._request("mksnap", ino=int(dentry["ino"]),
                                    name=name)
        return int(reply["snapid"])

    async def setquota(self, path: str, max_bytes: int = 0,
                       max_files: int = 0) -> dict:
        """Directory quota (the setfattr ceph.quota.max_bytes/
        max_files surface); both zero clears it."""
        dentry = await self._resolve(path)
        if dentry.get("type") != "dir":
            raise FSError(ENOTDIR, path)
        reply = await self._request("setquota",
                                    ino=int(dentry["ino"]),
                                    parent=int(dentry["ino"]),
                                    max_bytes=max_bytes,
                                    max_files=max_files)
        return reply["quota"]

    async def getquota(self, path: str) -> dict:
        dentry = await self._resolve(path)
        reply = await self._request("getquota",
                                    ino=int(dentry["ino"]),
                                    parent=int(dentry["ino"]))
        return {"quota": reply["quota"], "usage": reply.get("usage")}

    async def export_dir(self, path: str, rank: int) -> None:
        """Delegate the subtree at ``path`` to another active MDS rank
        (the ``ceph mds export dir`` / Migrator role; operator API)."""
        dentry = await self._resolve(path)
        if dentry["type"] != "dir":
            raise FSError(ENOTDIR, path)
        await self._request("export_dir", ino=int(dentry["ino"]),
                            rank=int(rank))
        self._dcache.clear()

    async def rmsnap(self, path: str, name: str) -> None:
        dentry = await self._resolve(path)
        await self._request("rmsnap", ino=int(dentry["ino"]),
                            name=name)

    async def listsnaps(self, path: str) -> dict[str, dict]:
        dentry = await self._resolve(path)
        reply = await self._request("lssnap", ino=int(dentry["ino"]))
        return reply["snaps"]

    # -- the libcephfs-shaped surface --------------------------------------
    async def mkdir(self, path: str, mode: int = 0o755) -> None:
        parent, name = await self._resolve_parent(path)
        await self._request("mkdir", parent=parent, name=name, mode=mode)
        self._invalidate(parent, name)

    async def mkdirs(self, path: str, mode: int = 0o755) -> None:
        built = ""
        for part in self._split(path):
            built += "/" + part
            try:
                await self.mkdir(built, mode)
            except FSError as e:
                if e.rc != EEXIST:
                    raise

    async def rmdir(self, path: str) -> None:
        parent, name = await self._resolve_parent(path)
        await self._request("rmdir", parent=parent, name=name)
        self._invalidate(parent, name)

    async def readdir(self, path: str = "/") -> dict[str, dict]:
        dentry = await self._resolve(path)
        if dentry["type"] != "dir":
            raise FSError(ENOTDIR, path)
        if dentry.get("snapdir"):
            reply = await self._request("lssnap",
                                        ino=int(dentry["snap_of"]))
            return {name: {"ino": dentry["snap_of"], "type": "dir",
                           "mode": 0o555, "size": 0,
                           "mtime": float(info["created"])}
                    for name, info in reply["snaps"].items()}
        reply = await self._request("readdir", ino=int(dentry["ino"]),
                                    snapid=int(dentry.get("snapid",
                                                          0)))
        return reply["entries"]

    async def stat(self, path: str) -> dict:
        return dict(await self._resolve(path))

    async def lstat(self, path: str) -> dict:
        """Like stat but does not follow a final-component symlink."""
        return dict(await self._resolve(path, follow=False))

    async def symlink(self, target: str, linkpath: str) -> None:
        """ceph_symlink: create a symbolic link at ``linkpath``."""
        parent, name = await self._resolve_parent(linkpath)
        await self._request("symlink", parent=parent, name=name,
                            target=target)
        self._invalidate(parent, name)

    async def readlink(self, path: str) -> str:
        dentry = await self._resolve(path, follow=False)
        if dentry["type"] != "symlink":
            raise FSError(EINVAL, f"{path!r} is not a symlink")
        return str(dentry.get("target", ""))

    async def open(self, path: str, flags: str = "r",
                   mode: int = 0o644) -> FileHandle:
        """flags: 'r' read, 'w' create+truncate, 'a' create+append,
        'x' exclusive create."""
        if ".snap" in self._split(path):
            dentry = await self._resolve(path)
            if not dentry.get("snapid"):
                raise FSError(EISDIR, path)
            if flags != "r":
                raise FSError(EROFS, "snapshots are read-only")
            if dentry["type"] == "dir":
                raise FSError(EISDIR, path)
            return FileHandle(self, 0, "", dentry,
                              snapid=int(dentry["snapid"]))
        parent, name = await self._resolve_parent(path)
        if flags in ("w", "a"):
            # POSIX open(O_CREAT) follows an existing final symlink:
            # the create/truncate lands on the TARGET, never on the
            # link's own inode ('x' keeps EEXIST via the MDS)
            try:
                existing = await self._lookup(parent, name)
            except FSError as e:
                if e.rc != ENOENT:
                    raise
                existing = None
            cpath = path
            if existing is not None \
                    and existing["type"] == "symlink":
                cpath, parent, name, _ = await self._follow_link_path(
                    path, existing
                )
        if flags in ("w", "a", "x"):
            for _ in range(3):
                try:
                    reply = await self._request(
                        "create", parent=parent, name=name, mode=mode,
                        exclusive=flags == "x", want_cap=True,
                    )
                    break
                except FSError as e:
                    # ELOOP: a symlink appeared at the name between our
                    # lookup and the create (the MDS refuses to hand a
                    # link dentry out as a file) — re-resolve + retry
                    if e.rc != ELOOP:
                        raise
                    self._invalidate(parent, name)
                    dentry = await self._lookup(parent, name)
                    # the retry's relative-target base is the path we
                    # FOLLOWED to, not the original user path
                    cpath, parent, name, _ = \
                        await self._follow_link_path(cpath, dentry)
            else:
                raise FSError(ELOOP, f"{path!r}: create/symlink race")
            self._invalidate(parent, name)
            capped = reply.get("cap") == "w"    # piggybacked grant
            dentry = reply["dentry"]
            if not capped:
                # contended (another session holds the cap): the
                # explicit open_file can wait for the recall
                try:
                    cap = await self._request(
                        "open_file", parent=parent, name=name,
                        write=True)
                    capped = cap.get("cap") == "w"
                    # post-recall attrs: the evicted holder's flush
                    # may have grown the file past the create reply
                    dentry = dict(cap.get("dentry", dentry))
                except FSError:
                    pass          # cap-less open still works
            fh = FileHandle(self, parent, name, dentry,
                            capped=capped)
            if capped:
                ino = fh.ino
                # register BEFORE the sibling awaits: a recall landing
                # mid-flush then clears this handle's cap too, instead
                # of leaving it buffering against a revoked grant
                siblings = self._open_caps.setdefault(ino, set())
                others = [s for s in siblings if s is not fh]
                siblings.add(fh)
                for sib in others:
                    # share the grant's view: the new handle must see
                    # the siblings' buffered bytes
                    await sib.fsync()
                    fh.size = max(fh.size, sib.size)
                if fh not in self._open_caps.get(ino, ()):
                    fh._cap = False   # recalled while we flushed
            if flags == "w" and fh.size:
                await fh.truncate(0)
            return fh
        dentry = await self._lookup(parent, name)
        if dentry["type"] == "symlink":
            # read-open follows the link chain; the REAL file's
            # (parent, name) is kept so attr flushes (fsync/close)
            # land on the target dentry, not the link's
            resolved, parent, name, dentry = \
                await self._follow_link_path(path, dentry)
            if dentry is None:
                raise FSError(ENOENT, resolved)
        if dentry["type"] == "dir":
            raise FSError(EISDIR, path)
        ino = int(dentry["ino"])
        if ino in self._open_caps:
            # OUR session holds the cap: flush locally (no recall —
            # the MDS skips holders' own connections) so this read
            # handle sees the buffered bytes and true size
            for sib in list(self._open_caps[ino]):
                await sib.fsync()
                dentry = {**dentry,
                          "size": max(int(dentry.get("size", 0)),
                                      sib.size)}
        elif dentry.get("cap_held"):
            # another session may hold a write cap (flag rides the
            # cached dentry): pay the recall round-trip; uncapped
            # files skip it entirely
            try:
                cap = await self._request("open_file", parent=parent,
                                          name=name, write=False)
                dentry = dict(cap.get("dentry", dentry))
                self._invalidate(parent, name)
            except FSError:
                pass
        return FileHandle(self, parent, name, dentry)

    async def _follow_link_path(
        self, path: str, dentry: dict
    ) -> tuple[str, int, str, dict | None]:
        """Resolve a symlink dentry at ``path`` to its FINAL non-link
        location (chains bounded like _resolve).  Returns (path,
        parent_ino, name, dentry-or-None); a None dentry means the
        final target is dangling — creating through it creates the
        TARGET (POSIX O_CREAT-through-symlink)."""
        hops = self._MAX_SYMLINKS
        cur_path = path
        parent, name = await self._resolve_parent(path)
        while dentry["type"] == "symlink":
            if hops <= 0:
                raise FSError(ELOOP, f"{path!r}: symlink loop")
            hops -= 1
            cur_path = self._join_link(
                self._split(cur_path)[:-1],
                str(dentry.get("target", "")), [],
            )
            parent, name = await self._resolve_parent(cur_path)
            try:
                dentry = await self._lookup(parent, name)
            except FSError as e:
                if e.rc == ENOENT:
                    return cur_path, parent, name, None
                raise
        return cur_path, parent, name, dentry

    async def link(self, src: str, dst: str) -> None:
        """ceph_link: hard link — ``dst`` becomes another name for
        ``src``'s inode (symlinks in ``src`` are followed)."""
        sparent, sname = await self._resolve_parent(src)
        sdentry = await self._lookup(sparent, sname)
        if sdentry["type"] == "symlink":
            _, sparent, sname, sdentry = await self._follow_link_path(
                src, sdentry)
            if sdentry is None:
                raise FSError(ENOENT, src)
        dparent, dname = await self._resolve_parent(dst)
        await self._request("link", src_parent=sparent, src_name=sname,
                            parent=dparent, name=dname)
        self._invalidate_ino(int(sdentry["ino"]))
        self._invalidate(sparent, sname)
        self._invalidate(dparent, dname)

    async def unlink(self, path: str) -> None:
        parent, name = await self._resolve_parent(path)
        reply = await self._request("unlink", parent=parent, name=name)
        self._invalidate_ino(int(reply.get("ino", 0)))
        self._invalidate(parent, name)

    async def rename(self, src: str, dst: str) -> None:
        sp, sn = await self._resolve_parent(src)
        dp, dn = await self._resolve_parent(dst)
        reply = await self._request("rename", src_parent=sp,
                                    src_name=sn, dst_parent=dp,
                                    dst_name=dn)
        # a clobbered hardlinked dst changed its inode's nlink: drop
        # every cached name of that inode, not just the two renamed
        self._invalidate_ino(int(reply.get("unlinked_ino", 0) or 0))
        self._invalidate(sp, sn)
        self._invalidate(dp, dn)

    # -- convenience (ceph_write_file-style helpers) -----------------------
    async def write_file(self, path: str, data: bytes) -> None:
        fh = await self.open(path, "w")
        await fh.write(data, 0)
        await fh.close()

    async def read_file(self, path: str) -> bytes:
        fh = await self.open(path, "r")
        try:
            return await fh.read()
        finally:
            await fh.close()
