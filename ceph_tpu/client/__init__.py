"""Client stack: Objecter + librados-shaped API + striper.

The reference's client layers (src/osdc/Objecter.{h,cc} op engine;
src/librados C/C++ API; src/libradosstriper) as asyncio-native Python:
clients compute placement themselves from the osdmap (CRUSH is
client-side — no metadata server in the data path), submit ops to the
primary OSD, resend on map change, and keep watch registrations alive
across intervals.
"""

from ceph_tpu.client.objecter import Objecter
from ceph_tpu.client.rados import IoCtx, ObjectOperation, Rados
from ceph_tpu.client.striper import RadosStriper

__all__ = ["IoCtx", "ObjectOperation", "Objecter", "Rados", "RadosStriper"]
