"""ObjectCacher: client-side write-back object cache.

The role of reference src/osdc/ObjectCacher.{h,cc} (ObjectCacher.h:52,
used by librbd and ceph-fuse): buffer object data client-side, serve
reads from cache, absorb writes as dirty state, and write back lazily —
bounded by a dirty-bytes budget (flush oldest-first when exceeded) and
an object-count budget (LRU-evict clean objects).  Granularity here is
the whole object (rbd blocks are the natural unit); the reference's
finer BufferHead extents collapse to one buffer per object.

The cache sits ABOVE the owner's object IO (librbd's cache sits above
copyup/object-map dispatch): ``fetch(key)`` must return the object's
full current content (including parent COW fallback) and
``writeback(key, data)`` must perform a full-object write with whatever
side effects (object map update) the owner needs.
"""

from __future__ import annotations

import asyncio

from ceph_tpu.common.lockdep import DLock
from collections import OrderedDict
from typing import Awaitable, Callable


class _CachedObject:
    __slots__ = ("data", "dirty")

    def __init__(self, data: bytearray):
        self.data = data
        self.dirty = False


class ObjectCacher:
    def __init__(
        self,
        fetch: Callable[[object], Awaitable[bytes]],
        writeback: Callable[[object, bytes], Awaitable[None]],
        max_dirty: int = 1 << 24,
        max_objects: int = 64,
    ):
        self._fetch = fetch
        self._writeback = writeback
        self.max_dirty = max_dirty
        self.max_objects = max_objects
        self._objects: "OrderedDict[object, _CachedObject]" = \
            OrderedDict()
        self._lock = DLock("object-cacher")
        # stats (perf-counter shaped)
        self.hits = 0
        self.misses = 0
        self.flushes = 0
        self.evictions = 0

    @property
    def dirty_bytes(self) -> int:
        return sum(len(o.data) for o in self._objects.values()
                   if o.dirty)

    async def _get(self, key) -> _CachedObject:
        obj = self._objects.get(key)
        if obj is not None:
            self.hits += 1
            self._objects.move_to_end(key)
            return obj
        self.misses += 1
        # callers hold self._lock across this await, so fetches are
        # fully serialized — no concurrent insert to re-check for
        data = bytearray(await self._fetch(key))
        obj = _CachedObject(data)
        self._objects[key] = obj
        await self._trim_locked()
        return obj

    async def read(self, key, offset: int, length: int) -> bytes:
        async with self._lock:
            obj = await self._get(key)
            out = bytes(obj.data[offset:offset + length])
        # short object: the tail reads as zeros (sparse semantics)
        if len(out) < length:
            out += b"\x00" * (length - len(out))
        return out

    async def write(self, key, offset: int, data: bytes) -> None:
        async with self._lock:
            obj = await self._get(key)
            end = offset + len(data)
            if len(obj.data) < end:
                obj.data.extend(b"\x00" * (end - len(obj.data)))
            obj.data[offset:end] = data
            obj.dirty = True
            self._objects.move_to_end(key)
            if self.dirty_bytes > self.max_dirty:
                await self._flush_locked(oldest_only=True)

    async def discard(self, key) -> None:
        async with self._lock:
            self._objects.pop(key, None)

    async def flush(self, key=None) -> None:
        async with self._lock:
            await self._flush_locked(only_key=key)

    async def _flush_locked(self, oldest_only: bool = False,
                            only_key=None) -> None:
        for k in list(self._objects):
            obj = self._objects[k]
            if not obj.dirty:
                continue
            if only_key is not None and k != only_key:
                continue
            await self._writeback(k, bytes(obj.data))
            obj.dirty = False
            self.flushes += 1
            if oldest_only and self.dirty_bytes <= self.max_dirty:
                return

    async def _trim_locked(self) -> None:
        """LRU-evict CLEAN objects over the count budget (dirty ones
        stay until flushed)."""
        while len(self._objects) > self.max_objects:
            victim = next(
                (k for k, o in self._objects.items() if not o.dirty),
                None,
            )
            if victim is None:
                return
            del self._objects[victim]
            self.evictions += 1

    def stats(self) -> dict:
        return {
            "objects": len(self._objects),
            "dirty_bytes": self.dirty_bytes,
            "hits": self.hits, "misses": self.misses,
            "flushes": self.flushes, "evictions": self.evictions,
        }
