"""Objecter: the client-side op engine.

Reference src/osdc/Objecter.{h,cc}: computes the target from the osdmap
(_calc_target :2759 — CRUSH runs HERE, on the client), submits to the
primary OSD (_op_submit :2369), tracks inflight ops and resends on map
change or connection reset, and maintains linger (watch) registrations
that re-arm whenever the target moves (linger_submit / _linger_ops).
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Awaitable, Callable

import hashlib
import hmac

from ceph_tpu.common.backoff import ExpBackoff
from ceph_tpu.common.log import Dout
from ceph_tpu.common.perf import CounterType, PerfCounters
from ceph_tpu.common.tracing import Tracer, current_span
from ceph_tpu.msg.message import Message
from ceph_tpu.msg.messenger import Connection, Messenger
from ceph_tpu.osd.codes import MISDIRECTED_RC, READ_CLASS_OPS
from ceph_tpu.osd.pg import object_to_ps

log = Dout("objecter")

_READ_OP_NAMES = READ_CLASS_OPS

EAGAIN_RC = -11


class ObjecterError(IOError):
    pass


class LingerOp:
    """A persistent watch registration (reference LingerOp)."""

    def __init__(self, linger_id: int, pool_id: int, oid: str, cookie: int,
                 callback: Callable[[bytes], Awaitable[bytes | None]]):
        self.linger_id = linger_id
        self.pool_id = pool_id
        self.oid = oid
        self.cookie = cookie
        self.callback = callback
        self.registered_osd: int | None = None


class Objecter:
    def __init__(self, monc, msgr: Messenger):
        self.monc = monc
        self.msgr = msgr
        self._tid = 0
        # tid -> (future, osd)
        self._inflight: dict[int, tuple[asyncio.Future, int]] = {}
        self._lingers: dict[int, LingerOp] = {}
        self._next_linger = 0
        self._stopped = False
        # client-unique reqid base (osd_reqid_t role): lets the OSD dedup
        # a resubmitted op that already executed with only the reply lost
        self._reqid_name = f"{msgr.name}.{msgr.nonce:08x}"
        self._reqid_seq = 0
        self.tracer = Tracer(msgr.name)
        # resend/timeout observability (l_osdc_* role), plus the
        # CLIENT-side latency histogram: end-to-end submit latency as
        # the application saw it (queueing + resends + map waits
        # included — the view the OSD-side histograms cannot have)
        self.perf = PerfCounters(f"objecter.{msgr.name}")
        for _k in ("op_resend", "op_timeout", "map_waits", "op_remap",
                   "op_error"):
            self.perf.add(_k, CounterType.U64)
        self.perf.add("op_latency_us", CounterType.HISTOGRAM)
        # primary-lookup memo off the bulk-mapping table: (map object,
        # epoch, {(pool, ps) -> acting_primary}).  Keyed by map identity
        # AND epoch so any new/replayed map drops it wholesale; entries
        # are filled from pg_to_up_acting (itself a cached-table lookup)
        self._primary_memo: tuple = (None, -1, {})
        # cephx: OSD sessions we have presented our service ticket on
        self._osd_authed: set[int] = set()
        self._osd_auth_futs: dict[int, asyncio.Future] = {}
        self._osd_auth_locks: dict[int, asyncio.Lock] = {}

    # -- dispatch hooks (driven by the owning client) ---------------------
    async def handle_message(self, conn: Connection, msg: Message) -> bool:
        """Returns True when the message was ours."""
        if msg.type == "osd_auth_challenge":
            proof = hmac.new(
                self.monc.osd_session_key.encode(),
                str(msg.data.get("nonce", "")).encode(), hashlib.sha256,
            ).hexdigest()
            try:
                conn.send_message(Message("osd_auth", {"proof": proof}))
            except ConnectionError:
                pass
            return True
        if msg.type == "osd_auth_reply":
            fut = self._osd_auth_futs.pop(id(conn), None)
            if fut is not None and not fut.done():
                fut.set_result(bool(msg.data.get("ok")))
            return True
        if msg.type == "osd_op_reply":
            fut_osd = self._inflight.pop(int(msg.data.get("tid", 0)), None)
            if fut_osd is not None and not fut_osd[0].done():
                fut_osd[0].set_result(msg.data)
            return True
        if msg.type == "watch_notify":
            asyncio.get_running_loop().create_task(
                self._handle_watch_notify(conn, msg.data)
            )
            return True
        return False

    def handle_reset(self, conn: Connection) -> None:
        """An OSD session died: fail its inflight ops (the callers'
        retry loops resubmit) and re-arm lingers bound to it."""
        self._osd_authed.discard(id(conn))
        fut = self._osd_auth_futs.pop(id(conn), None)
        if fut is not None and not fut.done():
            fut.set_exception(ObjecterError("osd session reset"))
        for tid, (fut, osd) in list(self._inflight.items()):
            if f"osd.{osd}" == conn.peer_name and not fut.done():
                del self._inflight[tid]
                fut.set_exception(ObjecterError("osd session reset"))
        for linger in self._lingers.values():
            if (linger.registered_osd is not None
                    and f"osd.{linger.registered_osd}" == conn.peer_name):
                linger.registered_osd = None
                asyncio.get_running_loop().create_task(
                    self._rearm_linger(linger)
                )

    async def on_map_change(self, osdmap) -> None:
        """_scan_requests role, run on every new osdmap: fail the
        in-flight ops whose session OSD the new map marks down — their
        reply will never come (the daemon is gone; an in-process
        transport surfaces no reset for a message sent into the gap
        between death and the map recording it), so without this rescan
        they would sit out the whole op deadline. The submit loop
        recomputes the target from the new map and resends; reqid dedup
        on the OSD makes a replay of an executed mutation safe. Lingers
        whose primary moved re-arm on the new one."""
        for tid, (fut, osd) in list(self._inflight.items()):
            if fut.done() or osdmap.is_up(osd):
                continue
            del self._inflight[tid]
            self.perf.inc("op_remap")
            fut.set_exception(ObjecterError(
                f"osd.{osd} went down (map e{osdmap.epoch})"
            ))
        for linger in self._lingers.values():
            target = self._target_for(linger.pool_id, linger.oid)
            if target is not None and target != linger.registered_osd:
                await self._rearm_linger(linger)

    # -- targeting --------------------------------------------------------
    def _pg_primary(self, m, pool_id: int, ps: int) -> int:
        """Memoized acting-primary for one PG on map ``m`` — hot on
        every submit retry, so repeated lookups within an epoch are a
        dict hit instead of even the (cheap) table walk."""
        memo_map, memo_epoch, memo = self._primary_memo
        if memo_map is not m or memo_epoch != m.epoch:
            memo = {}
            self._primary_memo = (m, m.epoch, memo)
        key = (pool_id, ps)
        primary = memo.get(key)
        if primary is None:
            _, _, _, primary = m.pg_to_up_acting(pool_id, ps)
            memo[key] = primary
        return primary

    def _target_for(self, pool_id: int, oid: str) -> int | None:
        m = self.monc.osdmap
        if m is None:
            return None
        pool = m.pools.get(pool_id)
        if pool is None:
            return None
        ps = object_to_ps(oid, pool.pg_num)
        primary = self._pg_primary(m, pool_id, ps)
        return primary if primary >= 0 else None

    # -- submission -------------------------------------------------------
    async def op_submit(self, pool_id: int, oid: str, ops: list[dict],
                        timeout: float | None = None,
                        extra: dict | None = None) -> dict:
        """Submit one op batch; retries across map changes, misdirected
        replies, and session resets until ``timeout``.  A sampled op
        (trace_probability) opens the root span and carries the trace
        context to the OSD (OpRequest/zipkin_trace analog).  When an
        ambient span is already active (an RGW request opened one),
        the submit traces unconditionally UNDER it — downstream of a
        sampled root, everything traces, so a trace is complete."""
        if timeout is None:
            timeout = float(self.monc.conf["client_op_deadline"])
        parent = current_span()
        prob = float(self.monc.conf["trace_probability"] or 0.0)
        t0 = time.monotonic()
        try:
            if parent is not None or (prob and random.random() < prob):
                with self.tracer.span("objecter:op_submit",
                                      parent=parent, oid=oid,
                                      pool=pool_id) as tctx:
                    ret = await self._op_submit_impl(
                        pool_id, oid, ops, timeout, extra, tctx
                    )
            else:
                ret = await self._op_submit_impl(pool_id, oid, ops,
                                                 timeout, extra, None)
        except Exception:
            # cancellation is the caller's doing, not an op failure
            self.perf.inc("op_error")
            raise
        self.perf.hinc("op_latency_us",
                       (time.monotonic() - t0) * 1e6)
        return ret

    async def _op_submit_impl(self, pool_id: int, oid: str,
                              ops: list[dict], timeout: float,
                              extra: dict | None, tctx) -> dict:
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        # one reqid for the whole retry loop: a resend after a session
        # reset is the SAME logical op, so the OSD can answer from its
        # completed-op cache instead of re-executing (reference replays
        # are deduped via osd_reqid_t in the PG log)
        self._reqid_seq += 1
        reqid = f"{self._reqid_name}:{self._reqid_seq}"
        # capped exponential backoff between resends, jitter seeded from
        # the reqid so a run replays the exact sleep schedule
        backoff = ExpBackoff(
            base=float(self.monc.conf["client_backoff_base"]),
            cap=float(self.monc.conf["client_backoff_max"]),
            seed=reqid, name="resend",
        )
        while True:
            if self._stopped:
                raise ObjecterError("objecter stopped")
            m = self.monc.osdmap
            pool = m.pools.get(pool_id) if m is not None else None
            if pool is None:
                raise ObjecterError(f"no pool {pool_id}")
            # cache-tier overlay redirect (Objecter::_calc_target's
            # read_tier/write_tier handling): ops targeting the base
            # pool are sent to the cache pool instead; re-evaluated
            # every retry so an overlay change mid-op takes effect
            mutating = any(op.get("op") not in _READ_OP_NAMES
                           for op in ops)
            tier_id = pool.write_tier if mutating else pool.read_tier
            target_pool_id = pool_id
            if tier_id >= 0 and tier_id in m.pools:
                target_pool_id = tier_id
                pool = m.pools[tier_id]
            ps = object_to_ps(oid, pool.pg_num)
            primary = self._pg_primary(m, target_pool_id, ps)
            if primary < 0:
                await self._await_newer_map(m.epoch, deadline)
                continue
            try:
                await self._ensure_osd_auth(primary, m.osds[primary].addr)
            except (ConnectionError, ObjecterError,
                    asyncio.TimeoutError):
                if loop.time() > deadline:
                    self.perf.inc("op_timeout")
                    raise ObjecterError(
                        f"osd.{primary} auth failed"
                    ) from None
                self.perf.inc("op_resend")
                await asyncio.sleep(min(backoff.next_delay(),
                                        max(0.0, deadline - loop.time())))
                continue
            self._tid += 1
            tid = self._tid
            fut = loop.create_future()
            self._inflight[tid] = (fut, primary)
            try:
                await self.msgr.send_to(
                    m.osds[primary].addr,
                    Message("osd_op", {
                        "tid": tid, "pool": target_pool_id, "ps": ps,
                        "oid": oid,
                        "epoch": m.epoch, "ops": ops, "reqid": reqid,
                        **({"tctx": tctx.to_wire()} if tctx else {}),
                        **(extra or {}),
                    }), f"osd.{primary}",
                )
                reply = await asyncio.wait_for(
                    fut, max(0.05, deadline - loop.time())
                )
            except (ConnectionError, ObjecterError):
                self._inflight.pop(tid, None)
                if loop.time() > deadline:
                    self.perf.inc("op_timeout")
                    raise ObjecterError(
                        f"op on {oid} timed out (osd.{primary} unreachable)"
                    ) from None
                self.perf.inc("op_resend")
                await asyncio.sleep(min(backoff.next_delay(),
                                        max(0.0, deadline - loop.time())))
                continue
            except asyncio.TimeoutError:
                self._inflight.pop(tid, None)
                self.perf.inc("op_timeout")
                raise ObjecterError(f"op on {oid} timed out") from None
            if reply["rc"] == MISDIRECTED_RC:
                await self._await_newer_map(
                    max(m.epoch, int(reply.get("epoch", 0))) , deadline,
                    strict=False,
                )
                continue
            return reply

    async def _ensure_osd_auth(self, osd: int, addr: str) -> None:
        """cephx: present our mon-issued service ticket on this OSD
        session and prove the session key before the first op (the
        CephxAuthorizer handshake). No-op when auth is off."""
        conf = getattr(self.monc, "conf", None)
        if conf is None or conf["auth_cluster_required"] != "cephx":
            return
        conn = await self.msgr.connect(addr, f"osd.{osd}")
        if id(conn) in self._osd_authed:
            return
        lock = self._osd_auth_locks.setdefault(id(conn), asyncio.Lock())
        try:
            await self._osd_auth_locked(conn, lock, osd)
        finally:
            self._osd_auth_futs.pop(id(conn), None)
            if not lock.locked():
                self._osd_auth_locks.pop(id(conn), None)

    async def _osd_auth_locked(self, conn, lock, osd: int) -> None:
        async with lock:
            if id(conn) in self._osd_authed:
                return
            for attempt in range(2):
                ticket = self.monc.osd_ticket
                if (ticket is None
                        or float(ticket.get("expires", 0))
                        < time.time() + 1.0):
                    # expired or missing: renew over the mon session
                    # BEFORE presenting (tickets outlive neither the
                    # secret rotation window nor their own TTL)
                    await self.monc.renew_ticket()
                    ticket = self.monc.osd_ticket
                if ticket is None:
                    raise ObjecterError("no osd service ticket")
                fut = asyncio.get_running_loop().create_future()
                self._osd_auth_futs[id(conn)] = fut
                conn.send_message(Message("osd_auth",
                                          {"ticket": ticket}))
                ok = await asyncio.wait_for(fut, 5.0)
                if ok:
                    self._osd_authed.add(id(conn))
                    break
                if attempt == 0:
                    # possibly a just-rotated secret: one renewed retry
                    await self.monc.renew_ticket()
                    continue
                raise ObjecterError(f"osd.{osd} rejected our ticket")

    async def _await_newer_map(self, epoch: int, deadline: float,
                               strict: bool = True) -> None:
        loop = asyncio.get_running_loop()
        if loop.time() > deadline:
            self.perf.inc("op_timeout")
            raise ObjecterError("timed out waiting for a usable osdmap")
        self.perf.inc("map_waits")
        try:
            await self.monc.wait_for_map(
                epoch + 1, timeout=min(1.0, max(0.05,
                                                deadline - loop.time()))
            )
        except asyncio.TimeoutError:
            if strict:
                pass        # keep retrying until the op deadline
        await asyncio.sleep(0.02)

    # -- watch / notify ---------------------------------------------------
    async def linger_watch(
        self, pool_id: int, oid: str,
        callback: Callable[[bytes], Awaitable[bytes | None]],
    ) -> LingerOp:
        self._next_linger += 1
        linger = LingerOp(self._next_linger, pool_id, oid,
                          cookie=self._next_linger, callback=callback)
        self._lingers[linger.linger_id] = linger
        reply = await self.op_submit(pool_id, oid, [
            {"op": "watch", "cookie": linger.cookie},
        ])
        if reply["rc"] != 0:
            del self._lingers[linger.linger_id]
            raise ObjecterError(f"watch failed: rc {reply['rc']}")
        linger.registered_osd = self._target_for(pool_id, oid)
        return linger

    async def linger_cancel(self, linger: LingerOp) -> None:
        self._lingers.pop(linger.linger_id, None)
        try:
            await self.op_submit(linger.pool_id, linger.oid, [
                {"op": "unwatch", "cookie": linger.cookie},
            ], timeout=5.0)
        except ObjecterError:
            pass

    async def _rearm_linger(self, linger: LingerOp) -> None:
        if linger.linger_id not in self._lingers or self._stopped:
            return
        try:
            reply = await self.op_submit(linger.pool_id, linger.oid, [
                {"op": "watch", "cookie": linger.cookie},
            ], timeout=10.0)
            if reply["rc"] == 0:
                linger.registered_osd = self._target_for(
                    linger.pool_id, linger.oid
                )
        except ObjecterError as e:
            log.dout(5, "linger re-arm for %s failed: %s", linger.oid, e)

    async def _handle_watch_notify(self, conn: Connection,
                                   data: dict) -> None:
        cookie = int(data["cookie"])
        linger = next(
            (lg for lg in self._lingers.values() if lg.cookie == cookie),
            None,
        )
        reply = b""
        if linger is not None:
            try:
                out = await linger.callback(bytes(data.get("payload", b"")))
                reply = out if isinstance(out, (bytes, bytearray)) else b""
            except Exception:                  # noqa: BLE001
                log.derr("watch callback for %s raised", data.get("oid"))
        try:
            conn.send_message(Message("notify_ack", {
                "notify_id": data["notify_id"], "cookie": cookie,
                "reply": bytes(reply),
            }))
        except ConnectionError:
            pass

    def shutdown(self) -> None:
        self._stopped = True
        for tid, (fut, _) in self._inflight.items():
            if not fut.done():
                fut.set_exception(ObjecterError("shutdown"))
        self._inflight.clear()
