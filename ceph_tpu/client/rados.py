"""Rados / IoCtx: the librados-shaped client API.

Mirrors the reference's librados surface (src/include/rados/librados.h C
API names; src/librados/librados_cxx.cc semantics) as asyncio-native
methods: a ``Rados`` cluster handle (connect/shutdown/commands/pools) and
per-pool ``IoCtx`` IO contexts (write/read/append/stat/remove, xattrs,
omap, multi-op ObjectOperation batches, watch/notify, object listing).
Cited reference paths: rados_write librados_c.cc:1174; IoCtx::write
librados_cxx.cc:1238; IoCtxImpl::operate IoCtxImpl.cc:645 ->
objecter->op_submit :672.
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
from typing import Awaitable, Callable

from ceph_tpu.common.config import ConfigProxy
from ceph_tpu.client.objecter import LingerOp, Objecter, ObjecterError
from ceph_tpu.mon.client import MonClient
from ceph_tpu.msg.message import Message
from ceph_tpu.msg.messenger import Connection, Messenger, Policy


class RadosError(IOError):
    def __init__(self, rc: int, msg: str = ""):
        super().__init__(f"rc={rc} {msg}")
        self.rc = rc


def _check(reply: dict, what: str) -> dict:
    if reply["rc"] != 0:
        raise RadosError(reply["rc"], f"{what}: {reply.get('outs', '')}")
    return reply


# CEPH_OSD_FLAG_FULL_TRY analog: ops issued while this is set carry a
# "full_try" wire flag and the OSD lets them through a FULL_QUOTA pool
# (the reference flags delete-flow ops the same way so a full pool can
# still be emptied).  A contextvar, so one `with full_try():` covers an
# entire async delete flow — every nested await inherits it.
_FULL_TRY: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "rados_full_try", default=False
)


@contextlib.contextmanager
def full_try():
    """All ops issued inside carry CEPH_OSD_FLAG_FULL_TRY semantics."""
    tok = _FULL_TRY.set(True)
    try:
        yield
    finally:
        _FULL_TRY.reset(tok)


# tenant/QoS class stamp: ops issued inside `with op_class("gold"):`
# carry a "qclass" field the OSD routes into per-class latency
# histograms (op_class_<label>_latency_us) — the attribution the
# mgr's per-class SLO burn pairs are computed from.  Same contextvar
# shape as full_try: one `with` covers an entire async flow.
_OP_CLASS: contextvars.ContextVar[str] = contextvars.ContextVar(
    "rados_op_class", default=""
)


@contextlib.contextmanager
def op_class(label: str):
    """All ops issued inside are stamped with tenant class ``label``."""
    tok = _OP_CLASS.set(str(label))
    try:
        yield
    finally:
        _OP_CLASS.reset(tok)


class ObjectOperation:
    """Batched multi-op (librados ObjectWriteOperation/ReadOperation)."""

    def __init__(self):
        self.ops: list[dict] = []

    def write(self, data: bytes, offset: int = 0) -> "ObjectOperation":
        self.ops.append({"op": "write", "off": offset,
                         "data": bytes(data)})
        return self

    def write_full(self, data: bytes) -> "ObjectOperation":
        self.ops.append({"op": "writefull", "data": bytes(data)})
        return self

    def append(self, data: bytes) -> "ObjectOperation":
        self.ops.append({"op": "append", "data": bytes(data)})
        return self

    def truncate(self, size: int) -> "ObjectOperation":
        self.ops.append({"op": "truncate", "size": size})
        return self

    def create(self, exclusive: bool = False) -> "ObjectOperation":
        self.ops.append({"op": "create", "exclusive": exclusive})
        return self

    def remove(self) -> "ObjectOperation":
        self.ops.append({"op": "remove"})
        return self

    def read(self, offset: int = 0,
             length: int | None = None) -> "ObjectOperation":
        self.ops.append({"op": "read", "off": offset, "len": length})
        return self

    def stat(self) -> "ObjectOperation":
        self.ops.append({"op": "stat"})
        return self

    def set_xattr(self, name: str, value: bytes) -> "ObjectOperation":
        self.ops.append({"op": "setxattr", "name": name,
                         "value": bytes(value)})
        return self

    def get_xattr(self, name: str) -> "ObjectOperation":
        self.ops.append({"op": "getxattr", "name": name})
        return self

    def get_xattrs(self) -> "ObjectOperation":
        self.ops.append({"op": "getxattrs"})
        return self

    def rm_xattr(self, name: str) -> "ObjectOperation":
        self.ops.append({"op": "rmxattr", "name": name})
        return self

    def omap_set(self, kv: dict[str, bytes]) -> "ObjectOperation":
        self.ops.append({"op": "omap_set",
                         "kv": {k: bytes(v) for k, v in kv.items()}})
        return self

    def omap_get(self, keys: list[str] | None = None) -> "ObjectOperation":
        self.ops.append({"op": "omap_get", "keys": keys})
        return self

    def omap_rm(self, keys: list[str]) -> "ObjectOperation":
        self.ops.append({"op": "omap_rm", "keys": list(keys)})
        return self

    def call(self, cls: str, method: str,
             indata: bytes = b"") -> "ObjectOperation":
        self.ops.append({"op": "call", "cls": cls, "method": method,
                         "in": bytes(indata)})
        return self


class Rados:
    """Cluster handle (librados rados_t / Rados)."""

    def __init__(self, monmap: dict[str, str],
                 conf: ConfigProxy | None = None,
                 name: str = "client.admin"):
        self.conf = conf or ConfigProxy()
        self.name = name
        self.msgr = Messenger(name, self.conf)
        # "entity:nonce" — the OSDMap blocklist key for THIS instance
        self.instance_id = f"{name}:{self.msgr.nonce}"
        self.msgr.set_policy("mon", Policy.lossy_client())
        self.msgr.set_policy("osd", Policy.lossy_client())
        self.msgr.set_dispatcher(self)
        self.monc = MonClient(name, monmap, self.conf, msgr=self.msgr)
        self.objecter = Objecter(self.monc, self.msgr)
        self.monc.on_osdmap = self.objecter.on_map_change
        self._connected = False
        self._daemon_tid = 0
        self._daemon_futs: dict[int, asyncio.Future] = {}

    # -- dispatcher demux --------------------------------------------------
    async def ms_dispatch(self, conn: Connection, msg: Message) -> None:
        if msg.type in ("perf_dump_reply", "dump_ops_reply",
                        "pg_scrub_reply", "dump_traces_reply",
                        "hit_set_ls_reply", "hit_set_contains_reply",
                        "ec_resident_stats_reply",
                        "ec_mesh_stats_reply",
                        "ec_repair_stats_reply",
                        "backfill_stats_reply",
                        "ec_scrub_stats_reply"):
            fut = self._daemon_futs.pop(int(msg.data.get("tid", 0)), None)
            if fut is not None and not fut.done():
                fut.set_result(msg.data)
            return
        if await self.objecter.handle_message(conn, msg):
            return
        await self.monc.ms_dispatch(conn, msg)

    def ms_handle_reset(self, conn: Connection) -> None:
        self.objecter.handle_reset(conn)
        self.monc.ms_handle_reset(conn)

    def ms_handle_connect(self, conn: Connection) -> None:
        pass

    # -- lifecycle ---------------------------------------------------------
    async def connect(self, timeout: float = 20.0) -> None:
        """rados_connect: mon session + map subscription."""
        await self.monc.start(timeout)
        self.monc.sub_want("osdmap")
        self.monc.sub_want("config")
        self.monc.renew_subs()
        await self.monc.wait_for_map(1, timeout)
        self._connected = True

    async def shutdown(self) -> None:
        self.objecter.shutdown()
        await self.monc.shutdown()
        await self.msgr.shutdown()
        self._connected = False

    # -- cluster ops -------------------------------------------------------
    async def mon_command(self, prefix: str, **args) -> dict:
        return await self.monc.command(prefix, **args)

    async def osd_daemon_command(self, osd_id: int, msg_type: str,
                                 timeout: float = 10.0,
                                 **args) -> dict:
        """Send an admin-socket-style request straight to an OSD (the
        `ceph daemon osd.N <cmd>` path): ``perf_dump``, ``dump_ops``,
        ``pg_scrub``."""
        m = self.monc.osdmap
        info = m.osds.get(osd_id) if m is not None else None
        if info is None or not info.up or not info.addr:
            raise RadosError(-2, f"osd.{osd_id} is not up")
        self._daemon_tid += 1
        tid = self._daemon_tid
        fut = asyncio.get_running_loop().create_future()
        self._daemon_futs[tid] = fut
        try:
            await self.msgr.send_to(
                info.addr, Message(msg_type, {"tid": tid, **args}),
                f"osd.{osd_id}",
            )
            return await asyncio.wait_for(fut, timeout)
        except (ConnectionError, asyncio.TimeoutError) as e:
            self._daemon_futs.pop(tid, None)
            raise RadosError(-110, f"daemon command: {e}") from e

    async def pg_scrub(self, pool_id: int, ps: int,
                       repair: bool = False,
                       timeout: float = 60.0) -> dict:
        """Scrub (or repair) one PG on its primary (`ceph pg scrub` /
        `ceph pg repair`)."""
        m = self.monc.osdmap
        if m is None or pool_id not in m.pools:
            raise RadosError(-2, f"no pool {pool_id}")
        primary = self.objecter._pg_primary(m, pool_id, ps)
        if primary < 0:
            raise RadosError(-11, f"pg {pool_id}.{ps} has no primary")
        reply = await self.osd_daemon_command(
            primary, "pg_scrub", timeout=timeout,
            pool=pool_id, ps=ps, repair=repair,
        )
        return reply["report"]

    async def get_cluster_stats(self) -> dict:
        return _check(await self.monc.command("status"), "status")["data"]

    async def list_pools(self) -> list[str]:
        r = _check(await self.monc.command("osd pool ls"), "pool ls")
        return list(r["data"])

    async def pool_create(self, name: str, **kw) -> int:
        r = _check(
            await self.monc.command("osd pool create", pool=name, **kw),
            "pool create",
        )
        await self._wait_pool(name)
        return r["data"]["pool_id"] if r.get("data") else 0

    async def pool_delete(self, name: str) -> None:
        _check(await self.monc.command("osd pool delete", pool=name),
               "pool delete")

    async def _wait_pool(self, name: str, timeout: float = 10.0) -> None:
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while True:
            m = self.monc.osdmap
            if m is not None and any(
                p.name == name for p in m.pools.values()
            ):
                return
            if loop.time() > deadline:
                raise RadosError(-110, f"pool {name!r} never appeared")
            try:
                await self.monc.wait_for_map(
                    (m.epoch if m else 0) + 1, timeout=0.5
                )
            except asyncio.TimeoutError:
                pass

    async def open_ioctx(self, pool_name: str) -> "IoCtx":
        m = self.monc.osdmap
        pool = next(
            (p for p in m.pools.values() if p.name == pool_name), None
        ) if m is not None else None
        if pool is None:
            raise RadosError(-2, f"no pool {pool_name!r}")
        return IoCtx(self, pool.pool_id, pool_name)


# Wire oid of a namespaced object: "\x1d<ns>\x1d<name>".  The leading
# group-separator marker cannot collide with ordinary oids (RGW index
# shards legitimately embed NULs, so "<ns>\x00<name>" would be
# ambiguous); default-namespace oids ride unchanged.
NS_SEP = "\x1d"


class IoCtx:
    """Per-pool IO context (librados rados_ioctx_t / IoCtx)."""

    def __init__(self, rados: Rados, pool_id: int, pool_name: str):
        self.rados = rados
        self.pool_id = pool_id
        self.pool_name = pool_name
        # rados_ioctx_set_namespace: "" = the default namespace.  The
        # namespace rides the wire INSIDE the oid (see NS_SEP) so
        # placement, replication, recovery and scrub treat namespaced
        # objects like any other; the OSD splits it back out for cap
        # enforcement (the hobject_t nspace role).
        self.namespace = ""
        # write SnapContext (rados_ioctx_selfmanaged_snap_set_write_ctx)
        self.snap_seq = 0
        self.snaps: list[int] = []
        # read snap (rados_ioctx_snap_set_read); None = head
        self.read_snap: int | None = None

    def set_namespace(self, namespace: str) -> None:
        """rados_ioctx_set_namespace ('' = default)."""
        if NS_SEP in namespace:
            raise ValueError("namespace may not contain \\x1d")
        self.namespace = str(namespace)

    def _noid(self, oid: str) -> str:
        if oid.startswith(NS_SEP):
            raise ValueError("object name may not start with \\x1d")
        if self.namespace:
            return f"{NS_SEP}{self.namespace}{NS_SEP}{oid}"
        return oid

    def set_snap_context(self, seq: int, snaps: list[int]) -> None:
        """Mutations carry this SnapContext; the OSD clones the head
        before its first write under a newer context (COW)."""
        self.snap_seq = int(seq)
        self.snaps = sorted(int(s) for s in snaps)

    def snap_set_read(self, snapid: int | None) -> None:
        """Reads resolve at this snap (None restores head reads)."""
        self.read_snap = None if snapid is None else int(snapid)

    async def selfmanaged_snap_create(self) -> int:
        """Allocate a pool snap id and adopt it into the write context."""
        r = _check(await self.rados.mon_command(
            "osd pool selfmanaged-snap create", pool=self.pool_name,
        ), "snap create")
        snapid = int(r["data"]["snapid"])
        self.set_snap_context(snapid, [*self.snaps, snapid])
        return snapid

    async def selfmanaged_snap_remove(self, snapid: int) -> None:
        _check(await self.rados.mon_command(
            "osd pool selfmanaged-snap rm", pool=self.pool_name,
            snapid=int(snapid),
        ), "snap rm")
        self.snaps = [s for s in self.snaps if s != snapid]

    async def operate(self, oid: str, op: ObjectOperation,
                      timeout: float = 30.0) -> dict:
        """Submit a batched op (IoCtxImpl::operate)."""
        extra: dict = {}
        if self.snap_seq:
            extra["snapc"] = {"seq": self.snap_seq,
                              "snaps": sorted(self.snaps, reverse=True)}
        if self.read_snap is not None:
            extra["snapid"] = self.read_snap
        if _FULL_TRY.get():
            extra["flags"] = ["full_try"]
        if _OP_CLASS.get():
            extra["qclass"] = _OP_CLASS.get()
        reply = await self.rados.objecter.op_submit(
            self.pool_id, self._noid(oid), op.ops, timeout,
            extra=extra or None
        )
        if reply["rc"] != 0:
            raise RadosError(reply["rc"], f"operate on {oid!r}")
        return reply

    # -- data --------------------------------------------------------------
    async def write(self, oid: str, data: bytes, offset: int = 0) -> None:
        await self.operate(oid, ObjectOperation().write(data, offset))

    async def write_full(self, oid: str, data: bytes) -> None:
        await self.operate(oid, ObjectOperation().write_full(data))

    async def append(self, oid: str, data: bytes) -> None:
        await self.operate(oid, ObjectOperation().append(data))

    async def read(self, oid: str, length: int | None = None,
                   offset: int = 0) -> bytes:
        r = await self.operate(
            oid, ObjectOperation().read(offset, length)
        )
        return r["results"][0]["data"]

    async def stat(self, oid: str) -> dict:
        r = await self.operate(oid, ObjectOperation().stat())
        return r["results"][0]

    async def remove(self, oid: str) -> None:
        await self.operate(oid, ObjectOperation().remove())

    async def truncate(self, oid: str, size: int) -> None:
        await self.operate(oid, ObjectOperation().truncate(size))

    # -- xattr / omap ------------------------------------------------------
    async def set_xattr(self, oid: str, name: str, value: bytes) -> None:
        await self.operate(oid, ObjectOperation().set_xattr(name, value))

    async def get_xattr(self, oid: str, name: str) -> bytes:
        r = await self.operate(oid, ObjectOperation().get_xattr(name))
        return r["results"][0]["value"]

    async def rm_xattr(self, oid: str, name: str) -> None:
        await self.operate(oid, ObjectOperation().rm_xattr(name))

    async def get_xattrs(self, oid: str) -> dict[str, bytes]:
        r = await self.operate(oid, ObjectOperation().get_xattrs())
        return r["results"][0]["attrs"]

    async def get_omap(self, oid: str,
                       keys: list[str] | None = None) -> dict[str, bytes]:
        r = await self.operate(oid, ObjectOperation().omap_get(keys))
        return r["results"][0]["kv"]

    async def set_omap(self, oid: str, kv: dict[str, bytes]) -> None:
        await self.operate(oid, ObjectOperation().omap_set(kv))

    async def rm_omap_keys(self, oid: str, keys: list[str]) -> None:
        await self.operate(oid, ObjectOperation().omap_rm(keys))

    async def exec(self, oid: str, cls: str, method: str,
                   indata: bytes = b"") -> bytes:
        """rados_exec: run a server-side object-class method."""
        r = await self.operate(
            oid, ObjectOperation().call(cls, method, indata)
        )
        return r["results"][0]["out"]

    # -- listing -----------------------------------------------------------
    async def list_objects(self) -> list[str]:
        """Enumerate pool objects (rados_nobjects_list: per-PG pgls,
        targeting each PG directly rather than hashing an object name)."""
        m = self.rados.monc.osdmap
        pool = m.pools[self.pool_id]
        names: set[str] = set()
        for ps in range(pool.pg_num):
            names.update(await self._pgls(ps))
        if self.namespace:
            pre = NS_SEP + self.namespace + NS_SEP
            return sorted(n[len(pre):] for n in names
                          if n.startswith(pre))
        return sorted(n for n in names if not n.startswith(NS_SEP))

    async def _pgls(self, ps: int) -> list[str]:
        objecter = self.rados.objecter
        monc = self.rados.monc
        loop = asyncio.get_running_loop()
        deadline = loop.time() + 10.0
        while True:
            m = monc.osdmap
            primary = objecter._pg_primary(m, self.pool_id, ps)
            if primary < 0:
                await asyncio.sleep(0.05)
                if loop.time() > deadline:
                    raise RadosError(-110, f"pgls {ps}: no primary")
                continue
            objecter._tid += 1
            tid = objecter._tid
            fut = loop.create_future()
            objecter._inflight[tid] = (fut, primary)
            try:
                await objecter.msgr.send_to(
                    m.osds[primary].addr,
                    Message("osd_op", {
                        "tid": tid, "pool": self.pool_id, "ps": ps,
                        "oid": "", "epoch": m.epoch,
                        "ops": [{"op": "pgls"}],
                    }), f"osd.{primary}",
                )
                reply = await asyncio.wait_for(
                    fut, max(0.05, deadline - loop.time())
                )
            except (ConnectionError, ObjecterError, asyncio.TimeoutError):
                objecter._inflight.pop(tid, None)
                if loop.time() > deadline:
                    raise RadosError(-110, f"pgls {ps} timed out") from None
                await asyncio.sleep(0.05)
                continue
            if reply["rc"] == -1000:        # misdirected
                await asyncio.sleep(0.05)
                continue
            if reply["rc"] != 0:
                raise RadosError(reply["rc"], f"pgls {ps}")
            return reply["results"][0]["objects"]

    # -- watch / notify ----------------------------------------------------
    async def watch(self, oid: str,
                    callback: Callable[[bytes], Awaitable[bytes | None]],
                    ) -> LingerOp:
        """Register a watch; callback receives each notify payload and may
        return a reply blob (rados_watch3 semantics)."""
        return await self.rados.objecter.linger_watch(
            self.pool_id, self._noid(oid), callback
        )

    async def unwatch(self, handle: LingerOp) -> None:
        await self.rados.objecter.linger_cancel(handle)

    async def notify(self, oid: str, payload: bytes = b"",
                     timeout: float = 5.0) -> dict:
        """rados_notify2: returns {"acks": {cookie: reply}, "timeouts"}."""
        r = await self.operate(oid, _NotifyOp(payload, timeout),
                               timeout=timeout + 10.0)
        return r["results"][0]


class _NotifyOp(ObjectOperation):
    def __init__(self, payload: bytes, timeout: float):
        super().__init__()
        self.ops = [{"op": "notify", "payload": bytes(payload),
                     "timeout": timeout}]
