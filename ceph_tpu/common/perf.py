"""Perf counters + histograms.

The metric model of reference src/common/perf_counters.h:154 (typed
counters: u64 count, time, averages with (sum,count) pairs) and
src/perf_histogram.h (2D axis-configured histograms), exposed as
``perf dump``-style nested dicts (admin socket / mgr report payloads).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from enum import Enum


class CounterType(Enum):
    U64 = "u64"          # monotonically increasing counter
    GAUGE = "gauge"      # settable level
    TIME = "time"        # accumulated seconds
    LONGRUNAVG = "avg"   # (sum, count) average pair


@dataclass
class _Counter:
    type: CounterType
    value: float = 0.0
    sum: float = 0.0
    count: int = 0


class PerfCounters:
    """One subsystem's counter set (PerfCounters analog); create via
    PerfCountersCollection.create()."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._counters: dict[str, _Counter] = {}

    def add(self, key: str, ctype: CounterType = CounterType.U64) -> None:
        with self._lock:
            self._counters.setdefault(key, _Counter(ctype))

    def inc(self, key: str, by: float = 1) -> None:
        with self._lock:
            c = self._counters[key]
            c.value += by

    def dec(self, key: str, by: float = 1) -> None:
        self.inc(key, -by)

    def set(self, key: str, value: float) -> None:
        with self._lock:
            self._counters[key].value = value

    def tinc(self, key: str, seconds: float) -> None:
        """Accumulate elapsed time (or any (sum,count) sample)."""
        with self._lock:
            c = self._counters[key]
            c.sum += seconds
            c.count += 1
            c.value = c.sum

    def time(self, key: str):
        """Context manager measuring a code section into a TIME/AVG counter."""
        perf = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                perf.tinc(key, time.perf_counter() - self.t0)
                return False

        return _Timer()

    def dump(self) -> dict:
        with self._lock:
            out = {}
            for key, c in self._counters.items():
                if c.type == CounterType.LONGRUNAVG or c.count:
                    out[key] = {"sum": c.sum, "avgcount": c.count}
                else:
                    out[key] = c.value
            return out

    def reset(self) -> None:
        with self._lock:
            for c in self._counters.values():
                c.value = c.sum = 0.0
                c.count = 0


class Histogram:
    """Linear/log-binned histogram (perf_histogram.h analog, 1D form)."""

    def __init__(self, name: str, buckets: list[float]):
        self.name = name
        self.buckets = list(buckets)  # upper bounds, ascending
        self.counts = [0] * (len(buckets) + 1)
        self._lock = threading.Lock()

    def sample(self, value: float) -> None:
        with self._lock:
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def dump(self) -> dict:
        with self._lock:
            return {
                "buckets": self.buckets,
                "counts": list(self.counts),
            }


class PerfCountersCollection:
    """Process-wide registry; the ``perf dump`` aggregation point."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sets: dict[str, PerfCounters] = {}
        self._hists: dict[str, Histogram] = {}

    def create(self, name: str) -> PerfCounters:
        with self._lock:
            return self._sets.setdefault(name, PerfCounters(name))

    def create_histogram(self, name: str, buckets: list[float]) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name, buckets)
            return h

    def dump(self) -> dict:
        with self._lock:
            out = {name: s.dump() for name, s in self._sets.items()}
            for name, h in self._hists.items():
                out[name + "_histogram"] = h.dump()
            return out
