"""Perf counters + histograms.

The metric model of reference src/common/perf_counters.h:154 (typed
counters: u64 count, time, averages with (sum,count) pairs) and
src/perf_histogram.h (2D axis-configured histograms), exposed as
``perf dump``-style nested dicts (admin socket / mgr report payloads).
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from enum import Enum


class CounterType(Enum):
    U64 = "u64"          # monotonically increasing counter
    GAUGE = "gauge"      # settable level
    TIME = "time"        # accumulated seconds
    LONGRUNAVG = "avg"   # (sum, count) average pair
    HISTOGRAM = "hist"   # log2-bucketed distribution (perf_histogram.h)


# log2 histogram layout: bucket i counts samples with value <= 2**i
# (bucket 0 is le=1, bucket 30 is le=2**30); the last bucket is the
# +Inf overflow.  Unit-agnostic — latency instrumentation records
# microseconds by convention (counter names carry a _us suffix), so
# the span is 1us .. ~18min before overflow.
HIST_BUCKETS = 32


def bucket_index(value: float) -> int:
    """The log2 bucket a sample lands in (exact at power-of-2 edges:
    2**k goes to le=2**k, 2**k + eps to le=2**(k+1))."""
    if value <= 1.0:
        return 0
    m, e = math.frexp(value)           # value = m * 2**e, m in [0.5, 1)
    idx = e - 1 if m == 0.5 else e
    return min(idx, HIST_BUCKETS - 1)


def bucket_le(i: int) -> float:
    """Inclusive upper bound of bucket i (+Inf for the overflow)."""
    if i >= HIST_BUCKETS - 1:
        return math.inf
    return float(1 << i)


def _hist_counts(h: dict | None) -> list[int]:
    """Bucket list of a dumped histogram, normalized to ints and padded
    to at least HIST_BUCKETS.  Mixed-version daemons can dump shorter or
    longer bucket arrays (a histogram layout change mid-upgrade) — the
    mgr merges whatever arrives, so mismatched counts must pad, never
    raise or silently drop samples."""
    if not h:
        return [0] * HIST_BUCKETS
    counts = [int(x) for x in h.get("buckets", ())]
    if len(counts) < HIST_BUCKETS:
        counts += [0] * (HIST_BUCKETS - len(counts))
    return counts


def hist_merge(a: dict | None, b: dict | None) -> dict:
    """Merge two dumped histograms (elementwise bucket sum) — the mgr
    aggregates per-daemon dumps into cluster series with this.
    Mismatched bucket counts merge by padding the shorter side with
    zeros (no sample is lost, no IndexError)."""
    if not a:
        a = {"buckets": [], "sum": 0.0, "count": 0}
    if not b:
        b = {"buckets": [], "sum": 0.0, "count": 0}
    ab, bb = _hist_counts(a), _hist_counts(b)
    n = max(len(ab), len(bb))
    ab += [0] * (n - len(ab))
    bb += [0] * (n - len(bb))
    return {
        "buckets": [x + y for x, y in zip(ab, bb)],
        "sum": float(a.get("sum", 0.0)) + float(b.get("sum", 0.0)),
        "count": int(a.get("count", 0)) + int(b.get("count", 0)),
    }


def hist_delta(cur: dict | None, prev: dict | None) -> dict:
    """``cur - prev`` of two cumulative histogram dumps: the
    distribution of ONLY the samples recorded between the two
    snapshots.  This is the sliding-window primitive: counters are
    monotonic, so the window histogram is the elementwise difference
    of its edge snapshots.  Buckets clamp at 0 (a daemon restart
    resets counters; a negative window would corrupt quantiles)."""
    ca, cb = _hist_counts(cur), _hist_counts(prev)
    n = max(len(ca), len(cb))
    ca += [0] * (n - len(ca))
    cb += [0] * (n - len(cb))
    buckets = [max(0, x - y) for x, y in zip(ca, cb)]
    cur = cur or {}
    prev = prev or {}
    return {
        "buckets": buckets,
        "sum": max(0.0, float(cur.get("sum", 0.0))
                   - float(prev.get("sum", 0.0))),
        "count": sum(buckets),
    }


def hist_quantile(h: dict, q: float) -> float | None:
    """Quantile estimate from a dumped histogram: locate the bucket
    holding rank q*count, linearly interpolate inside it (Prometheus
    histogram_quantile semantics).  Overflow bucket returns its lower
    bound.  Exact and deterministic given the bucket counts.  An EMPTY
    histogram has no quantiles: returns ``None`` (callers render it as
    absent/0, but must not mistake it for a measured 0)."""
    counts = list(h.get("buckets", ()))
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    last = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if cum + c >= rank:
            lo = 0.0 if i == 0 else bucket_le(i - 1)
            hi = bucket_le(i)
            if math.isinf(hi):
                return lo
            frac = min(1.0, max(0.0, (rank - cum) / c))
            return lo + (hi - lo) * frac
        cum += c
        last = bucket_le(i)
    return last if not math.isinf(last) else bucket_le(HIST_BUCKETS - 2)


def hist_frac_above(h: dict, threshold: float) -> float:
    """Fraction of recorded samples whose value exceeds ``threshold``
    — the error-budget numerator for a latency SLO (``pXX <= T`` burns
    budget at ``frac_above(T) / (1 - 0.XX)``).  Exact when the
    threshold sits on a log2 bucket edge; inside a bucket the count
    splits by linear interpolation (the same uniform-within-bucket
    assumption hist_quantile makes).  Empty histograms burn nothing."""
    counts = list(h.get("buckets", ()))
    total = sum(counts)
    if total <= 0:
        return 0.0
    above = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        lo = 0.0 if i == 0 else bucket_le(i - 1)
        hi = bucket_le(i)
        if threshold <= lo:
            above += c
        elif threshold < hi:                 # inside this bucket
            if math.isinf(hi):
                continue   # overflow bucket: value == lower bound
            above += c * (hi - threshold) / (hi - lo)
    return above / total


def counter_scalar(val) -> float:
    """Scalar view of ONE dumped counter value, whatever its type.

    perf dumps are not uniformly scalar: LONGRUNAVG dumps
    ``{"sum", "avgcount"}`` and HISTOGRAM ``{"buckets", "sum",
    "count"}`` — code that sums ``dump()[subsys][key]`` across daemons
    breaks the day a key changes type.  This mirrors
    :meth:`PerfCounters.value`: dict forms collapse to their ``sum``.
    """
    if isinstance(val, dict):
        return float(val.get("sum", 0.0))
    return float(val)


def counter_sum(dumps, subsys: str, key: str) -> float:
    """Sum one counter across many daemons' ``dump()`` outputs,
    tolerating daemons without the subsystem or key (mixed-version
    clusters mid-upgrade)."""
    return sum(
        counter_scalar(d.get(subsys, {}).get(key, 0.0)) for d in dumps
    )


@dataclass
class _Counter:
    type: CounterType
    value: float = 0.0
    sum: float = 0.0
    count: int = 0
    buckets: list[int] = field(default_factory=list)


class PerfCounters:
    """One subsystem's counter set (PerfCounters analog); create via
    PerfCountersCollection.create()."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._counters: dict[str, _Counter] = {}

    def add(self, key: str, ctype: CounterType = CounterType.U64) -> None:
        with self._lock:
            if key in self._counters:
                return
            c = _Counter(ctype)
            if ctype == CounterType.HISTOGRAM:
                c.buckets = [0] * HIST_BUCKETS
            self._counters[key] = c

    def hinc(self, key: str, value: float) -> None:
        """Record one sample into a HISTOGRAM counter."""
        with self._lock:
            c = self._counters[key]
            c.buckets[bucket_index(value)] += 1
            c.sum += value
            c.count += 1

    def inc(self, key: str, by: float = 1) -> None:
        with self._lock:
            c = self._counters[key]
            c.value += by

    def dec(self, key: str, by: float = 1) -> None:
        self.inc(key, -by)

    def set(self, key: str, value: float) -> None:
        with self._lock:
            self._counters[key].value = value

    def tinc(self, key: str, seconds: float) -> None:
        """Accumulate elapsed time (or any (sum,count) sample)."""
        with self._lock:
            c = self._counters[key]
            c.sum += seconds
            c.count += 1
            c.value = c.sum

    def time(self, key: str):
        """Context manager measuring a code section into a TIME/AVG counter."""
        perf = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                perf.tinc(key, time.perf_counter() - self.t0)
                return False

        return _Timer()

    def value(self, key: str, default: float = 0.0) -> float:
        """Point read of a scalar counter (U64/GAUGE/TIME); LONGRUNAVG
        and HISTOGRAM return their accumulated sum.  Missing counters
        return ``default`` — bench/smoke assertions poll by name
        without caring whether registration already happened."""
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                return default
            if c.type in (CounterType.LONGRUNAVG, CounterType.HISTOGRAM):
                return c.sum
            return c.value

    def dump(self) -> dict:
        with self._lock:
            out = {}
            for key, c in self._counters.items():
                if c.type == CounterType.HISTOGRAM:
                    out[key] = {"buckets": list(c.buckets),
                                "sum": c.sum, "count": c.count}
                elif c.type == CounterType.LONGRUNAVG or c.count:
                    out[key] = {"sum": c.sum, "avgcount": c.count}
                else:
                    out[key] = c.value
            return out

    def quantile(self, key: str, q: float) -> float:
        """Quantile of a live HISTOGRAM counter (hist_quantile on a
        point-in-time dump); 0.0 when no samples were recorded yet
        (bench/smoke callers poll before traffic lands)."""
        with self._lock:
            c = self._counters[key]
            h = {"buckets": list(c.buckets), "count": c.count}
        got = hist_quantile(h, q)
        return 0.0 if got is None else got

    def reset(self) -> None:
        with self._lock:
            for c in self._counters.values():
                c.value = c.sum = 0.0
                c.count = 0
                if c.buckets:
                    c.buckets = [0] * len(c.buckets)


class Histogram:
    """Linear/log-binned histogram (perf_histogram.h analog, 1D form)."""

    def __init__(self, name: str, buckets: list[float]):
        self.name = name
        self.buckets = list(buckets)  # upper bounds, ascending
        self.counts = [0] * (len(buckets) + 1)
        self._lock = threading.Lock()

    def sample(self, value: float) -> None:
        with self._lock:
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def dump(self) -> dict:
        with self._lock:
            return {
                "buckets": self.buckets,
                "counts": list(self.counts),
            }


class PerfCountersCollection:
    """Process-wide registry; the ``perf dump`` aggregation point."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sets: dict[str, PerfCounters] = {}
        self._hists: dict[str, Histogram] = {}

    def create(self, name: str) -> PerfCounters:
        with self._lock:
            return self._sets.setdefault(name, PerfCounters(name))

    def create_histogram(self, name: str, buckets: list[float]) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name, buckets)
            return h

    def dump(self) -> dict:
        with self._lock:
            out = {name: s.dump() for name, s in self._sets.items()}
            for name, h in self._hists.items():
                out[name + "_histogram"] = h.dump()
            return out
