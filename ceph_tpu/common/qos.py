"""QoS defense plane: the controller core that closes the SLO loop.

The SLO engine (common/slo.py) *detects* burn; nothing before this
module fought back.  Three actuator families turn the burn-rate signal
into defenses, actuating the client/recovery interference pair of
arxiv 1709.05365 (online-EC recovery I/O directly inflates client tail
latency) under the degraded-EC regime arxiv 1906.08602 grades:

- :class:`AIMDController` — additive-increase / multiplicative-decrease
  of the recovery-class mClock limit.  While client latency objectives
  burn, the recovery share backs off multiplicatively (classic
  congestion response: interference is a shared-resource congestion
  signal); when the burn clears it ramps back additively.  The backoff
  never drops below a pacing floor derived from
  ``slo_rebuild_floor_gibs`` — starving rebuild stretches the degraded
  window, which is its own SLO violation.
- :func:`derive_hedge_timeout` — quantile-adaptive hedged reads: each
  OSD's EC hedge timeout tracks a configured quantile (default p95) of
  its own windowed shard-read latency histogram instead of a static
  conf value, with min/max clamps and a widening term when the
  ``hedge_lost`` feedback says hedges fire early and lose the race.
- :class:`TokenBucket` — per-session admission control for the RGW
  front door (rgw_http.py): overload sheds with ``503 Slow Down``
  before OSD queues melt.

Everything here is deterministic: a decision is a pure function of the
evaluation sequence and prior controller state (no wall-clock, no
randomness), so the same seed replays the same retune/shed sequence
through the flight recorder.

:class:`QoSController` composes the pieces into one per-tick decision
the mgr module (services/mgr_qos.py) fans out cluster-wide.
"""

from __future__ import annotations

from ceph_tpu.common.perf import hist_quantile
from ceph_tpu.common.slo import SnapshotWindow

# re-push / re-journal an adaptive hedge timeout only when it moved by
# more than this relative amount: the quantile estimate jitters a few
# percent tick to tick and spamming identical retunes would bury the
# flight recorder
HEDGE_REL_TOL = 0.2
# hedge feedback: if more than this fraction of the window's hedges
# LOST the race (the straggler beat reconstruction), the timeout is
# firing too early — widen it
HEDGE_LOSS_FRAC = 0.5
HEDGE_WIDEN = 2.0


class AIMDController:
    """Additive-increase / multiplicative-decrease value controller
    with raise/clear hysteresis (mirrors ``slo_raise/clear_evals``).

    ``step(burning)`` feeds one evaluation; after ``raise_evals``
    consecutive burning evals the value backs off by ``backoff`` on
    every further burning eval (sustained pressure keeps shrinking it
    toward the floor); after ``clear_evals`` consecutive clean evals it
    ramps by ``ramp`` per eval back toward the ceiling.  A lone noisy
    eval in either direction only resets the opposite streak — no
    flapping."""

    def __init__(self, initial: float, floor: float, ceiling: float,
                 backoff: float = 0.5, ramp: float = 16.0,
                 raise_evals: int = 2, clear_evals: int = 2):
        self.floor = max(0.0, float(floor))
        self.ceiling = max(self.floor, float(ceiling))
        self.value = min(self.ceiling, max(self.floor, float(initial)))
        self.backoff = float(backoff)
        self.ramp = float(ramp)
        self.raise_evals = max(1, int(raise_evals))
        self.clear_evals = max(1, int(clear_evals))
        self._bad = 0
        self._good = 0

    def step(self, burning: bool) -> float | None:
        """One evaluation. Returns the new value when it changed,
        else None."""
        prev = self.value
        if burning:
            self._good = 0
            self._bad += 1
            if self._bad >= self.raise_evals:
                self.value = max(self.floor, self.value * self.backoff)
        else:
            self._bad = 0
            self._good += 1
            if self._good >= self.clear_evals:
                self.value = min(self.ceiling, self.value + self.ramp)
        return self.value if self.value != prev else None


def derive_hedge_timeout(hist: dict, quantile: float,
                         min_s: float, max_s: float, *,
                         hedges_issued: float = 0.0,
                         hedges_lost: float = 0.0,
                         min_samples: int = 16) -> float | None:
    """Adaptive EC hedge timeout (seconds) from one daemon's windowed
    shard-read latency histogram (``ec_shard_read_us``).

    Returns None when no retune should happen: adaptive hedging is off
    (``quantile <= 0``) or the window holds fewer than ``min_samples``
    reads (a thin histogram's quantile is noise — the last pushed
    value stays in force).  When the window's hedge feedback says most
    hedges fired and then LOST the race to the straggler, the timeout
    was too aggressive and the derived value widens before clamping."""
    if quantile <= 0.0:
        return None
    if int(hist.get("count") or 0) < max(1, int(min_samples)):
        return None
    q_us = hist_quantile(hist, quantile)
    if q_us is None:
        return None
    t = q_us / 1e6
    if hedges_issued > 0 and hedges_lost / hedges_issued > HEDGE_LOSS_FRAC:
        t *= HEDGE_WIDEN
    return min(max_s, max(min_s, t))


class TokenBucket:
    """Deterministic token bucket: ``rate`` tokens/s refill up to
    ``burst`` capacity.  The caller supplies the clock reading (the
    RGW frontend passes the event-loop time), so the bucket itself has
    no wall-clock dependence."""

    def __init__(self, rate: float, burst: float, now: float = 0.0):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self.tokens = self.burst
        self._last = float(now)

    def take(self, now: float, n: float = 1.0) -> bool:
        """Refill to ``now`` then try to spend ``n`` tokens."""
        if now > self._last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._last) * self.rate)
            self._last = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def retry_after(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will be available."""
        if self.rate <= 0:
            return 1.0
        return max(0.0, (n - self.tokens) / self.rate)


class QoSController:
    """One closed-loop tick: SLO evaluations + the shared snapshot
    window in, actuator decisions out.

    Decisions are pure functions of the inputs and prior controller
    state — the mgr module journals each one into the flight recorder,
    so identical load (same seed) replays an identical retune
    sequence."""

    def __init__(self, *, recovery_res: float, recovery_max_ops: float,
                 recovery_min_ops: float, recovery_min_share: float,
                 rebuild_floor_gibs: float, gib_per_op: float,
                 backoff: float, ramp_ops: float,
                 raise_evals: int, clear_evals: int,
                 hedge_quantile: float, hedge_min_s: float,
                 hedge_max_s: float, hedge_min_samples: int,
                 backfill_res: float = 5.0,
                 backfill_max_ops: float = 128.0,
                 backfill_min_ops: float = 2.0,
                 backfill_min_share: float = 0.02,
                 scrub_res: float = 1.0,
                 scrub_max_ops: float = 64.0,
                 scrub_min_ops: float = 1.0,
                 scrub_min_share: float = 0.01,
                 replication_max_ops: float = 64.0,
                 replication_min_ops: float = 2.0,
                 replication_min_share: float = 0.05):
        # the pacing floor: never throttle recovery below the largest
        # of (absolute ops floor, share-of-ceiling floor, the ops rate
        # that sustains slo_rebuild_floor_gibs at the assumed GiB/op)
        floor = max(recovery_min_ops,
                    recovery_min_share * recovery_max_ops,
                    (rebuild_floor_gibs / max(gib_per_op, 1e-9))
                    if rebuild_floor_gibs > 0 else 0.0)
        self.recovery = AIMDController(
            initial=recovery_max_ops, floor=floor,
            ceiling=recovery_max_ops, backoff=backoff, ramp=ramp_ops,
            raise_evals=raise_evals, clear_evals=clear_evals)
        self.recovery_res = float(recovery_res)
        # backfill (planned motion) is a SECOND AIMD position driven by
        # the SAME burn signal but with its own floor/ceiling: during
        # rebalance every object still has full redundancy, so there is
        # no rebuild-GiB floor term and backfill may be squeezed much
        # harder than failure recovery before the controller relents
        bf_floor = max(backfill_min_ops,
                       backfill_min_share * backfill_max_ops)
        self.backfill = AIMDController(
            initial=backfill_max_ops, floor=bf_floor,
            ceiling=backfill_max_ops, backoff=backoff, ramp=ramp_ops,
            raise_evals=raise_evals, clear_evals=clear_evals)
        self.backfill_res = float(backfill_res)
        # scrub (verification of data already fully redundant) is the
        # THIRD AIMD position: like backfill it has no rebuild-GiB
        # floor, and its share floor sits lower still — of the three
        # background classes, doubt drains last when clients burn.
        # The daemon additionally PAUSES in-flight sweeps on the
        # burning flag; this position governs the dispatch rate of the
        # sweeps that do run.
        sc_floor = max(scrub_min_ops, scrub_min_share * scrub_max_ops)
        self.scrub = AIMDController(
            initial=scrub_max_ops, floor=sc_floor,
            ceiling=scrub_max_ops, backoff=backoff, ramp=ramp_ops,
            raise_evals=raise_evals, clear_evals=clear_evals)
        self.scrub_res = float(scrub_res)
        # geo-replication (multisite sync throughput) is the FOURTH
        # AIMD position: it is not an mClock class — the decision is
        # actuated as a token-bucket rate on the secondary's sync
        # agents — but it rides the same burn signal and hysteresis.
        # Its floor IS the RPO bound: however hard clients burn, the
        # replication backlog drains at least this fast, so
        # unreplicated bytes cannot grow without limit.
        rp_floor = max(replication_min_ops,
                       replication_min_share * replication_max_ops)
        self.replication = AIMDController(
            initial=replication_max_ops, floor=rp_floor,
            ceiling=replication_max_ops, backoff=backoff,
            ramp=ramp_ops, raise_evals=raise_evals,
            clear_evals=clear_evals)
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_min_s = float(hedge_min_s)
        self.hedge_max_s = float(hedge_max_s)
        self.hedge_min_samples = int(hedge_min_samples)
        self._hedge_last: dict[str, float] = {}
        self.ticks = 0
        self.retunes = 0

    @classmethod
    def from_conf(cls, conf) -> "QoSController":
        return cls(
            recovery_res=float(conf["osd_mclock_recovery_res"]),
            recovery_max_ops=float(conf["qos_recovery_max_ops"]),
            recovery_min_ops=float(conf["qos_recovery_min_ops"]),
            recovery_min_share=float(conf["qos_recovery_min_share"]),
            rebuild_floor_gibs=float(conf["slo_rebuild_floor_gibs"]),
            gib_per_op=float(conf["qos_recovery_gib_per_op"]),
            backoff=float(conf["qos_backoff"]),
            ramp_ops=float(conf["qos_ramp_ops"]),
            raise_evals=int(conf["slo_raise_evals"]),
            clear_evals=int(conf["slo_clear_evals"]),
            hedge_quantile=float(conf["qos_hedge_quantile"]),
            hedge_min_s=float(conf["qos_hedge_min_ms"]) / 1e3,
            hedge_max_s=float(conf["qos_hedge_max_ms"]) / 1e3,
            hedge_min_samples=int(conf["qos_hedge_min_samples"]),
            backfill_res=float(conf["osd_mclock_backfill_res"]),
            backfill_max_ops=float(conf["qos_backfill_max_ops"]),
            backfill_min_ops=float(conf["qos_backfill_min_ops"]),
            backfill_min_share=float(conf["qos_backfill_min_share"]),
            scrub_res=float(conf["osd_mclock_scrub_res"]),
            scrub_max_ops=float(conf["qos_scrub_max_ops"]),
            scrub_min_ops=float(conf["qos_scrub_min_ops"]),
            scrub_min_share=float(conf["qos_scrub_min_share"]),
            replication_max_ops=float(
                conf["qos_replication_max_ops"]),
            replication_min_ops=float(
                conf["qos_replication_min_ops"]),
            replication_min_share=float(
                conf["qos_replication_min_share"]),
        )

    @staticmethod
    def latency_burn(evals: list[dict]) -> float:
        """Worst client-latency burn rate in one evaluation pass (the
        rebuild floor is an objective the controller PROTECTS, not a
        congestion signal to back recovery off for)."""
        worst = 0.0
        for rec in evals:
            obj = str(rec.get("objective", ""))
            if obj.endswith("_ms"):
                worst = max(worst, float(rec.get("burn_rate", 0.0)))
        return worst

    def tick(self, evals: list[dict],
             win: SnapshotWindow) -> dict:
        """One controller evaluation.  Returns::

            {"burning": bool, "burn": float,
             "recovery":    {"limit", "reservation", "floor", "changed"},
             "backfill":    {"limit", "reservation", "floor", "changed"},
             "scrub":       {"limit", "reservation", "floor", "changed"},
             "replication": {"limit", "reservation", "floor", "changed"},
             "hedge": {daemon: timeout_s}}   # only entries that moved

        ``hedge`` keys are daemon names (``osd.N``); an entry appears
        only when the derived timeout moved more than HEDGE_REL_TOL
        from the last pushed value."""
        self.ticks += 1
        burn = self.latency_burn(evals)
        burning = burn > 1.0
        new_limit = self.recovery.step(burning)
        limit = self.recovery.value
        rec = {
            "limit": limit,
            # the reservation (guaranteed ops/s) tracks the limit down
            # so phase-1 dispatch cannot grant above the cap
            "reservation": min(self.recovery_res, limit),
            "floor": self.recovery.floor,
            "changed": new_limit is not None,
        }
        if new_limit is not None:
            self.retunes += 1
        new_bf = self.backfill.step(burning)
        bf = {
            "limit": self.backfill.value,
            "reservation": min(self.backfill_res, self.backfill.value),
            "floor": self.backfill.floor,
            "changed": new_bf is not None,
        }
        if new_bf is not None:
            self.retunes += 1
        new_sc = self.scrub.step(burning)
        sc = {
            "limit": self.scrub.value,
            "reservation": min(self.scrub_res, self.scrub.value),
            "floor": self.scrub.floor,
            "changed": new_sc is not None,
        }
        if new_sc is not None:
            self.retunes += 1
        new_rp = self.replication.step(burning)
        rp = {
            "limit": self.replication.value,
            # the agents actuate a plain rate limit, not an mClock
            # (reservation, limit) pair — reservation mirrors the
            # limit for the journal's uniform retune shape
            "reservation": self.replication.value,
            "floor": self.replication.floor,
            "changed": new_rp is not None,
        }
        if new_rp is not None:
            self.retunes += 1

        hedge: dict[str, float] = {}
        if self.hedge_quantile > 0.0:
            _, per_hist = win.hist("ec_shard_read_us")
            _, per_issued = win.scalar("hedge_issued")
            _, per_lost = win.scalar("hedge_lost")
            for daemon in sorted(per_hist):
                t = derive_hedge_timeout(
                    per_hist[daemon], self.hedge_quantile,
                    self.hedge_min_s, self.hedge_max_s,
                    hedges_issued=per_issued.get(daemon, 0.0),
                    hedges_lost=per_lost.get(daemon, 0.0),
                    min_samples=self.hedge_min_samples)
                if t is None:
                    continue
                last = self._hedge_last.get(daemon)
                if last is not None and abs(t - last) <= \
                        HEDGE_REL_TOL * last:
                    continue
                self._hedge_last[daemon] = t
                hedge[daemon] = t

        return {"burning": burning, "burn": burn, "recovery": rec,
                "backfill": bf, "scrub": sc, "replication": rp,
                "hedge": hedge}

    def state(self) -> dict:
        """Controller state snapshot (digest / forensic bundles)."""
        return {
            "ticks": self.ticks,
            "retunes": self.retunes,
            "recovery_limit": round(self.recovery.value, 3),
            "recovery_floor": round(self.recovery.floor, 3),
            "recovery_ceiling": round(self.recovery.ceiling, 3),
            "backfill_limit": round(self.backfill.value, 3),
            "backfill_floor": round(self.backfill.floor, 3),
            "backfill_ceiling": round(self.backfill.ceiling, 3),
            "scrub_limit": round(self.scrub.value, 3),
            "scrub_floor": round(self.scrub.floor, 3),
            "scrub_ceiling": round(self.scrub.ceiling, 3),
            "replication_limit": round(self.replication.value, 3),
            "replication_floor": round(self.replication.floor, 3),
            "replication_ceiling": round(
                self.replication.ceiling, 3),
            "hedge_timeouts_ms": {
                d: round(t * 1e3, 3)
                for d, t in sorted(self._hedge_last.items())},
        }
