"""Delta-encoded perf collection: the sublinear collect wire format.

The mgr polls every up OSD for a full perf dump each report cycle —
the exact hotspot ROADMAP item 1 predicts walls at 1000 OSDs, because
the payload is O(counters x OSDs) even when almost nothing moved
(idle OSDs, cold pools, registered-but-untouched histograms).  The
fix is classic state-sync:

- the OSD keeps the dump it last shipped plus a monotonically
  increasing **epoch**; each ``perf_dump_delta`` request carries the
  mgr's ``ack_epoch`` (the epoch it last integrated),
- on epoch match the OSD ships only the counters whose value changed
  since the baseline (plus removed keys), stamped with the next epoch,
- on mismatch — first contact, mgr restart, dropped reply, OSD
  restart — the OSD ships a **full resync** and both sides re-anchor.

The decoder replays payloads into the identical full dump the old
path produced, so digest/tsdb contents are bit-identical whichever
mode ran (the cfg16 A/B acceptance criterion).  Both halves are pure
and wire-free: daemon.py and mgr.py wrap them, and bench cfg16 drives
them directly over 200 simulated OSDs for exact payload accounting
via :func:`payload_bytes`.
"""

from __future__ import annotations

import json


def payload_bytes(payload) -> int:
    """Canonical payload size: compact sorted JSON encoding.  Both
    arms of the cfg16 A/B and the mgr byte counters use this one
    function, so the >= 5x claim is counter-verified, not estimated."""
    return len(json.dumps(payload, separators=(",", ":"),
                          sort_keys=True).encode())


class DeltaCollectEncoder:
    """OSD side: turns successive full dumps into delta payloads."""

    def __init__(self):
        self.epoch = 0          # epoch of the last payload shipped
        self._last: dict = {}   # the dump that payload described
        self.full_sends = 0
        self.delta_sends = 0

    def encode(self, dump: dict, ack_epoch: int) -> dict:
        """Encode ``dump`` against the baseline.  A full resync ships
        whenever the collector's ack doesn't match our last-shipped
        epoch (or nothing was ever shipped)."""
        resync = self.epoch == 0 or int(ack_epoch) != self.epoch
        self.epoch += 1
        if resync:
            self.full_sends += 1
            payload = {"epoch": self.epoch, "full": True,
                       "counters": dump}
        else:
            self.delta_sends += 1
            last = self._last
            changed = {k: v for k, v in dump.items()
                       if k not in last or last[k] != v}
            removed = [k for k in last if k not in dump]
            payload = {"epoch": self.epoch, "full": False,
                       "changed": changed, "removed": removed}
        # dump() builds fresh dicts per call, so holding the reference
        # as baseline is safe — the live counters never mutate it
        self._last = dump
        return payload


class DeltaCollectDecoder:
    """Mgr side: replays payloads back into full dumps (one decoder
    per OSD).  ``epoch`` after a decode is the ack to send with the
    next request."""

    def __init__(self):
        self.epoch = 0
        self._state: dict = {}
        self.resyncs = 0
        self.stale_drops = 0

    def decode(self, payload: dict) -> dict:
        epoch = int(payload.get("epoch", 0))
        if payload.get("full"):
            # a full payload re-anchors unconditionally (it IS the
            # state, whatever epoch stream it came from)
            self.resyncs += 1
            self._state = dict(payload.get("counters") or {})
            self.epoch = epoch
        elif epoch == self.epoch + 1:
            st = dict(self._state)
            st.update(payload.get("changed") or {})
            for k in payload.get("removed") or ():
                st.pop(k, None)
            self._state = st
            self.epoch = epoch
        else:
            # a delta is only valid against the exact baseline it was
            # encoded from; an out-of-order/stale one is dropped and
            # the next request's unchanged ack forces a full resync
            self.stale_drops += 1
        return dict(self._state)
