"""Zipkin-lite distributed tracing for the op path.

The role of reference src/common/zipkin_trace.h (:24 ZTracer wrappers)
+ the OpRequest trace hooks (src/osd/OpRequest.h): a sampled client op
carries a trace context on the wire; every hop (objecter submit, OSD
op execution, sub-op fan-out, replica apply) records a timed span
linked by (trace_id, parent span id).  Spans land in a bounded
per-process ring inspectable via the admin socket / ``dump_traces``
message, keyed so a cross-daemon trace tree can be reassembled.

Sampling: the root decides (``trace_probability`` config); everything
downstream of a sampled op traces unconditionally, so a trace is
always complete.
"""

from __future__ import annotations

import contextvars
import secrets
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass

_RING = 4096


@dataclass(frozen=True)
class SpanCtx:
    trace_id: str
    span_id: str

    def to_wire(self) -> dict:
        return {"t": self.trace_id, "s": self.span_id}

    @staticmethod
    def from_wire(d) -> "SpanCtx | None":
        if not isinstance(d, dict) or "t" not in d:
            return None
        return SpanCtx(str(d["t"]), str(d.get("s", "")))


# The task-local active span: set where an op's span is opened (RGW
# request handler, OSD do_op, EC per-op submit) and read at the next
# layer down (objecter, EC coalescer, messenger) so causality crosses
# module boundaries without threading a ctx argument through every
# signature.  A contextvar — each asyncio task sees its own value.
_ACTIVE: contextvars.ContextVar[SpanCtx | None] = contextvars.ContextVar(
    "tracing_active_span", default=None
)


def current_span() -> SpanCtx | None:
    """The ambient SpanCtx of the running task, if any."""
    return _ACTIVE.get()


@contextmanager
def use_span(ctx: SpanCtx | None):
    """Make ``ctx`` the ambient span for the enclosed block."""
    tok = _ACTIVE.set(ctx)
    try:
        yield ctx
    finally:
        _ACTIVE.reset(tok)


class Tracer:
    """Per-process span collector (one per daemon entity)."""

    def __init__(self, entity: str):
        self.entity = entity
        self.spans: deque[dict] = deque(maxlen=_RING)
        #: spans pushed out of the bounded ring before collection —
        #: each eviction is a potential orphan in a later
        #: ``assemble_tree``, so span loss must be visible *before*
        #: a trace is pulled (perf counter / prom gauge)
        self.ring_evictions = 0

    def _append(self, span: dict) -> None:
        if len(self.spans) == self.spans.maxlen:
            self.ring_evictions += 1
        self.spans.append(span)

    @contextmanager
    def span(self, name: str, parent: SpanCtx | None = None, **tags):
        """Record a timed span; yields the child SpanCtx to propagate.
        Works around both sync and async code (it only stamps clocks)."""
        ctx = SpanCtx(
            parent.trace_id if parent else secrets.token_hex(8),
            secrets.token_hex(4),
        )
        # wall-clock start for cross-daemon ordering, monotonic clock
        # for the duration (an NTP step must not yield negative spans)
        start = time.time()
        t0 = time.perf_counter()
        try:
            yield ctx
        finally:
            self._append({
                "trace_id": ctx.trace_id,
                "span_id": ctx.span_id,
                "parent": parent.span_id if parent else "",
                "name": name,
                "entity": self.entity,
                "start": start,
                "duration_ms": round(
                    (time.perf_counter() - t0) * 1e3, 3),
                **({"tags": tags} if tags else {}),
            })

    def record(self, name: str, parent: SpanCtx, start: float,
               duration_ms: float, **tags) -> SpanCtx:
        """Append a pre-measured span (no context manager).  For work
        shared across ops — a coalesced device launch serves many
        traces at once, so the one measured interval is recorded once
        per interested parent."""
        ctx = SpanCtx(parent.trace_id, secrets.token_hex(4))
        self._append({
            "trace_id": ctx.trace_id,
            "span_id": ctx.span_id,
            "parent": parent.span_id,
            "name": name,
            "entity": self.entity,
            "start": start,
            "duration_ms": round(duration_ms, 3),
            **({"tags": tags} if tags else {}),
        })
        return ctx

    def dump(self, trace_id: str | None = None) -> list[dict]:
        return [s for s in self.spans
                if trace_id is None or s["trace_id"] == trace_id]

    def orphan_count(self) -> int:
        """Spans currently in the ring whose parent has already fallen
        out of it — what ``assemble_tree`` would tag ``orphan`` if a
        collection ran now.  O(ring) walk; called at perf-dump time,
        not on the span hot path."""
        ids = {s["span_id"] for s in self.spans}
        return sum(1 for s in self.spans
                   if s.get("parent") and s["parent"] not in ids)


def assemble_tree(spans: list[dict]) -> list[dict]:
    """Merge spans (possibly from several daemons) into parent-linked
    trees sorted by start time — the trace-view the reference gets
    from its zipkin collector."""
    by_id = {s["span_id"]: dict(s) for s in spans}
    roots: list[dict] = []
    for s in sorted(by_id.values(), key=lambda s: s["start"]):
        pid = s.get("parent", "")
        parent = by_id.get(pid)
        if parent is not None:
            parent.setdefault("children", []).append(s)
        else:
            # a span naming a parent that isn't in the set (fell out
            # of the ring, or a daemon wasn't collected) is promoted
            # to a root but marked, so partial traces are
            # distinguishable from genuinely root spans
            if pid:
                s["orphan"] = True
            roots.append(s)
    return roots
