"""Zipkin-lite distributed tracing for the op path.

The role of reference src/common/zipkin_trace.h (:24 ZTracer wrappers)
+ the OpRequest trace hooks (src/osd/OpRequest.h): a sampled client op
carries a trace context on the wire; every hop (objecter submit, OSD
op execution, sub-op fan-out, replica apply) records a timed span
linked by (trace_id, parent span id).  Spans land in a bounded
per-process ring inspectable via the admin socket / ``dump_traces``
message, keyed so a cross-daemon trace tree can be reassembled.

Sampling: the root decides (``trace_probability`` config); everything
downstream of a sampled op traces unconditionally, so a trace is
always complete.
"""

from __future__ import annotations

import secrets
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass

_RING = 4096


@dataclass(frozen=True)
class SpanCtx:
    trace_id: str
    span_id: str

    def to_wire(self) -> dict:
        return {"t": self.trace_id, "s": self.span_id}

    @staticmethod
    def from_wire(d) -> "SpanCtx | None":
        if not isinstance(d, dict) or "t" not in d:
            return None
        return SpanCtx(str(d["t"]), str(d.get("s", "")))


class Tracer:
    """Per-process span collector (one per daemon entity)."""

    def __init__(self, entity: str):
        self.entity = entity
        self.spans: deque[dict] = deque(maxlen=_RING)

    @contextmanager
    def span(self, name: str, parent: SpanCtx | None = None, **tags):
        """Record a timed span; yields the child SpanCtx to propagate.
        Works around both sync and async code (it only stamps clocks)."""
        ctx = SpanCtx(
            parent.trace_id if parent else secrets.token_hex(8),
            secrets.token_hex(4),
        )
        t0 = time.time()
        try:
            yield ctx
        finally:
            self.spans.append({
                "trace_id": ctx.trace_id,
                "span_id": ctx.span_id,
                "parent": parent.span_id if parent else "",
                "name": name,
                "entity": self.entity,
                "start": t0,
                "duration_ms": round((time.time() - t0) * 1e3, 3),
                **({"tags": tags} if tags else {}),
            })

    def dump(self, trace_id: str | None = None) -> list[dict]:
        return [s for s in self.spans
                if trace_id is None or s["trace_id"] == trace_id]


def assemble_tree(spans: list[dict]) -> list[dict]:
    """Merge spans (possibly from several daemons) into parent-linked
    trees sorted by start time — the trace-view the reference gets
    from its zipkin collector."""
    by_id = {s["span_id"]: dict(s) for s in spans}
    roots: list[dict] = []
    for s in sorted(by_id.values(), key=lambda s: s["start"]):
        parent = by_id.get(s.get("parent", ""))
        if parent is not None:
            parent.setdefault("children", []).append(s)
        else:
            roots.append(s)
    return roots
