"""Typed config registry + live proxy.

The shape of the reference's option system (src/common/options.cc — one
typed schema with metadata; src/common/config.h:70 md_config_t;
config_proxy.h ConfigProxy; config_obs.h observers), with sources merged in
the same precedence order: schema defaults < config file < central config db
(mon) < environment < runtime overrides. ~Levels and runtime-changeable
flags are preserved; the 2,000-option catalogue grows as subsystems land.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Mapping

class Level(Enum):
    BASIC = "basic"
    ADVANCED = "advanced"
    DEV = "dev"


@dataclass
class Option:
    name: str
    type: type = str  # str | int | float | bool
    default: Any = None
    description: str = ""
    level: Level = Level.ADVANCED
    min: float | None = None
    max: float | None = None
    enum_values: tuple = ()
    runtime: bool = True  # changeable without restart

    def validate(self, value):
        try:
            if self.type is bool and isinstance(value, str):
                value = value.lower() in ("1", "true", "yes", "on")
            else:
                value = self.type(value)
        except (TypeError, ValueError):
            raise ValueError(
                f"option {self.name}: {value!r} is not {self.type.__name__}"
            ) from None
        if self.min is not None and value < self.min:
            raise ValueError(f"option {self.name}: {value} < min {self.min}")
        if self.max is not None and value > self.max:
            raise ValueError(f"option {self.name}: {value} > max {self.max}")
        if self.enum_values and value not in self.enum_values:
            raise ValueError(
                f"option {self.name}: {value!r} not in {self.enum_values}"
            )
        return value


def global_options() -> list[Option]:
    """The built-in schema (get_global_options analog). Subsystems extend
    via ConfigProxy.register()."""
    return [
        Option("cluster", str, "ceph-tpu", "cluster name", Level.BASIC),
        Option("osd_pool_default_size", int, 3, "replica count", min=1),
        Option("osd_pool_default_min_size", int, 0, "min replicas to serve"),
        Option("osd_pool_default_pg_num", int, 32, "default pg count", min=1),
        Option("osd_heartbeat_interval", float, 0.5, "peer ping interval (s)",
               min=0.01),
        Option("osd_heartbeat_grace", float, 3.0,
               "seconds of silence before reporting a peer down", min=0.1),
        Option("mon_osd_min_down_reporters", int, 1,
               "distinct reporters required to mark an osd down", min=1),
        Option("mon_osd_down_out_interval", float, 30.0,
               "seconds before a down osd is marked out"),
        Option("mon_osdmap_keep_epochs", int, 200,
               "OSDMap full+incremental epochs the mon store retains; "
               "subscribers older than the trim horizon get a full map "
               "(mon_min_osdmap_epochs trim role)", min=1),
        Option("osd_heartbeat_peer_limit", int, 0,
               "max peers each OSD pings (ring successors by id); 0 = "
               "all up OSDs.  The all-to-all default builds an O(n^2) "
               "connection mesh that melts one-process clusters past "
               "~100 OSDs (maybe_update_heartbeat_peers role)", min=0),
        Option("paxos_propose_interval", float, 0.0,
               "delay before committing staged boot/failure map changes "
               "so a burst coalesces into one epoch (0 = immediate)",
               min=0.0),
        Option("osd_erasure_code_plugins", str, "jax_rs lrc shec clay xor",
               "plugins preloaded at osd start"),
        Option("osd_recovery_max_active", int, 8,
               "max concurrent recovery ops", min=1),
        Option("osd_pg_log_max_entries", int, 250,
               "retained pg log entries per PG (trim boundary)", min=8),
        Option("osd_map_history_keep", int, 64,
               "full OSDMap epochs each OSD persists in its meta "
               "collection (the mon-store rebuild harvest source; "
               "0 = off)", min=0),
        Option("osd_op_queue", str, "mclock_scheduler",
               "op scheduler: mclock_scheduler or fifo",
               enum_values=("mclock_scheduler", "fifo")),
        # dmClock per-class QoS knobs (osd_mclock_scheduler_* analogs);
        # limit 0 = uncapped
        Option("osd_mclock_client_res", float, 100.0,
               "client reservation (ops/s)"),
        Option("osd_mclock_client_wgt", float, 10.0, "client weight"),
        Option("osd_mclock_client_lim", float, 0.0, "client limit"),
        Option("osd_mclock_recovery_res", float, 10.0,
               "recovery reservation (ops/s)"),
        Option("osd_mclock_recovery_wgt", float, 1.0, "recovery weight"),
        Option("osd_mclock_recovery_lim", float, 0.0, "recovery limit"),
        Option("osd_scrub_interval", float, 0.0,
               "seconds between automatic PG scrubs (0 = manual only)"),
        Option("osd_scrub_jitter", float, 0.5,
               "randomize each background scrub tick up to this "
               "fraction beyond osd_scrub_interval (per-OSD seeded "
               "rng) so a fleet started together does not deep-scrub "
               "in lockstep"),
        Option("osd_mclock_scrub_res", float, 5.0,
               "scrub reservation (ops/s)"),
        Option("osd_mclock_scrub_wgt", float, 1.0, "scrub weight"),
        Option("osd_mclock_scrub_lim", float, 0.0, "scrub limit"),
        # backfill = PLANNED data motion (topology change), a distinct
        # mClock class from recovery (failure repair) so the QoS plane
        # can pace rebalance and rebuild independently
        Option("osd_mclock_backfill_res", float, 5.0,
               "backfill reservation (ops/s)"),
        Option("osd_mclock_backfill_wgt", float, 1.0,
               "backfill weight"),
        Option("osd_mclock_backfill_lim", float, 0.0, "backfill limit"),
        Option("osd_max_backfills", int, 1,
               "backfill reservation slots per OSD (local + remote): a "
               "PG's planned motion starts only once every participant "
               "granted a slot, so one daemon serves at most this many "
               "concurrent backfills", min=1),
        Option("osd_client_op_priority", int, 63, "client op priority"),
        Option("mon_lease", float, 2.0,
               "peon lease / liveness window (s)", min=0.1),
        Option("mon_lease_interval", float, 0.5,
               "leader lease-renewal period (s)", min=0.05),
        Option("mon_election_timeout", float, 1.0,
               "election round timeout (s)", min=0.05),
        Option("mon_tick_interval", float, 0.5,
               "monitor periodic tick (s)", min=0.05),
        Option("mon_accept_timeout", float, 2.0,
               "paxos accept-phase timeout (s)", min=0.1),
        Option("mon_sync_timeout", float, 5.0,
               "store-sync per-chunk timeout before retrying with "
               "another provider (s)", min=0.1),
        Option("auth_shared_key", str, "",
               "cluster shared auth key ('' = auth disabled)"),
        Option("auth_cluster_required", str, "none",
               "authentication mode: cephx (per-entity keys + tickets) "
               "or none", enum_values=("none", "cephx")),
        Option("auth_admin_key", str, "",
               "bootstrap key for client.admin ('' = generate)"),
        Option("auth_key", str, "",
               "this entity's own secret key (cephx mode)"),
        Option("auth_service_secret_ttl", float, 3600.0,
               "rotating service-secret / ticket lifetime (s)", min=0.5),
        Option("osd_agent_interval", float, 1.0,
               "cache-tier flush/evict agent period (s; 0=off)", min=0.0),
        Option("store_compression_algorithm", str, "",
               "inline at-rest compression of the object store's WAL "
               "records and checkpoint segments ('' = off; zlib, zstd, "
               "lzma, bz2 — the BlueStore compress-on-write role)",
               enum_values=("", "zlib", "zstd", "lzma", "bz2")),
        Option("osd_ec_mesh_cs", int, 0,
               "chunk-sharding axis size of the distributed EC data "
               "plane mesh (0 = single-device EC; >0 = shard encode/"
               "decode batches over all local jax devices with a "
               "('dp','cs') mesh, cs dividing the device count)",
               min=0),
        Option("mds_beacon_interval", float, 0.5,
               "mds -> mon beacon period (s)", min=0.05),
        Option("mds_beacon_grace", float, 3.0,
               "beacon silence before an mds is failed (s)", min=0.1),
        Option("mds_decay_halflife", float, 5.0,
               "halflife of mds dirfrag popularity counters (s)",
               min=0.1),
        Option("mds_bal_interval", float, 0.0,
               "mds balancer tick period (s; 0=off)", min=0.0),
        Option("mds_bal_min_rebalance", float, 0.25,
               "export only when this rank's load exceeds the mean "
               "by this fraction of the mean", min=0.0),
        Option("mds_bal_min_start", float, 8.0,
               "minimum load excess (decayed request counts) worth "
               "exporting a subtree for", min=0.0),
        Option("mds_bal_split_size", int, 10000,
               "dirfrag entry count that triggers a split "
               "(reference mds_bal_split_size)", min=4),
        Option("mds_bal_merge_size", int, 50,
               "combined sibling entry count below which sibling "
               "dirfrags merge back (reference mds_bal_merge_size)",
               min=0),
        Option("mds_bal_split_bits", int, 1,
               "hash bits added per dirfrag split (2^bits children; "
               "reference mds_bal_split_bits)", min=1, max=4),
        Option("trace_probability", float, 0.0,
               "fraction of client ops that carry a trace context "
               "(zipkin_trace analog; 0=off)", min=0.0, max=1.0),
        Option("osd_op_complaint_time", float, 1.0,
               "an op in flight (or finished) past this many seconds "
               "counts as slow: beaconed to the mon for the SLOW_OPS "
               "health check and retained in the forensic ring",
               min=0.01, runtime=True),
        Option("osd_slow_op_history", int, 20,
               "how many of the slowest ops keep their full event "
               "timeline + span tree (dump_historic_slow_ops)",
               Level.ADVANCED, min=1),
        Option("event_journal_size", int, 2048,
               "bound of each daemon's flight-recorder event ring "
               "(common/events.py EventJournal)", Level.ADVANCED,
               min=16),
        Option("forensics_window_s", float, 60.0,
               "trailing seconds of each event journal snapshotted "
               "into a forensic bundle on capture", min=1.0,
               runtime=True),
        Option("forensics_dir", str, "",
               "directory where the mgr persists forensic bundles "
               "('' = <tempdir>/ceph_tpu_forensics)", runtime=True),
        Option("forensics_cooldown_s", float, 30.0,
               "min seconds between automatic forensic captures (a "
               "flapping health check must not storm bundles)",
               Level.ADVANCED, min=0.0, runtime=True),
        Option("ms_secure_mode", bool, False,
               "AES-256-GCM on-wire frame encryption (crypto_onwire "
               "analog); needs a configured auth key on every daemon"),
        Option("ms_dispatch_throttle_bytes", int, 100 << 20,
               "max bytes of in-dispatch messages per peer type before "
               "the reader backpressures (0=unlimited)", min=0),
        Option("osd_client_message_size_cap", int, 500 << 20,
               "max bytes of client op payloads in flight per OSD; "
               "held for each op's LIFETIME (0=unlimited)", min=0),
        Option("admin_socket_dir", str, "",
               "directory for <entity>.asok admin sockets ('' = off)"),
        Option("ms_inject_socket_failures", int, 0,
               "1-in-N artificial connection failures (0=off); alias of "
               "failpoint msgr.send", Level.DEV),
        Option("ms_inject_delay_max", float, 0.0,
               "max artificial delivery delay (s); alias of failpoint "
               "msgr.deliver", Level.DEV),
        Option("failpoint", str, "",
               "failpoint spec applied at daemon start: "
               "name=mode[:arg][:arg],... (see common/failpoint.py)",
               Level.DEV, runtime=True),
        Option("failpoint_seed", int, 0,
               "deterministic seed for failpoint prob/chaos draws "
               "(0 = leave registry seed alone)", Level.DEV),
        Option("client_backoff_base", float, 0.05,
               "initial client resend/hunt backoff (s)", min=0.0),
        Option("client_backoff_max", float, 1.0,
               "cap on client resend/hunt backoff (s)", min=0.0),
        Option("client_op_deadline", float, 30.0,
               "default per-op deadline for Objecter ops (s)", min=0.1),
        Option("osd_ec_hedge_read_timeout", float, 0.0,
               "hedge an EC shard read after this many seconds: fan out "
               "to surviving shards and reconstruct via minimum_to_decode "
               "(0 = off)", Level.ADVANCED, min=0.0),
        Option("ec_stripe_batch", int, 1024,
               "stripes per device encode launch", min=1),
        Option("ec_use_pallas", bool, True,
               "use fused Pallas kernels on TPU"),
        Option("osd_ec_coalesce", bool, True,
               "coalesce concurrent in-flight EC ops' encode/decode "
               "batches into shared device launches (cross-op "
               "micro-batching; amortizes per-launch dispatch cost "
               "for small-write workloads)"),
        Option("osd_ec_coalesce_window_us", float, 200.0,
               "adaptive micro-window an EC op may wait for batchmates "
               "before its coalesced launch flushes (microseconds; "
               "flushes immediately when no other op is in flight)",
               Level.ADVANCED, min=0.0),
        Option("osd_ec_coalesce_max_stripes", int, 4096,
               "pending stripe count that forces an immediate coalesced "
               "flush regardless of the window", Level.ADVANCED, min=1),
        Option("osd_ec_mesh_coalesce", bool, False,
               "promote EC op coalescing to one host-level launcher "
               "shared by every co-located OSD: each micro-window "
               "flushes as a single shard_map launch whose stripe "
               "batch splits across ALL local jax devices (falls back "
               "to the per-OSD launcher on 1-device hosts and for "
               "codecs without a generator matrix); also enables "
               "cross-chip CLAY/LRC sub-chunk degraded reads"),
        Option("ec_pallas_encode_variant", str, "auto",
               "Pallas encode kernel formulation ('' = production "
               "kernel; 'auto' = the perf-lab winner enc_u8_expand on "
               "a TPU backend, production elsewhere; variants are "
               "bit-identical, promoted from the round-5 perf lab for "
               "on-chip timing)", Level.ADVANCED,
               enum_values=("", "auto", "enc_cmp_expand",
                            "enc_u8_expand", "enc_split2",
                            "enc_u8_split2")),
        Option("osd_ec_resident", bool, True,
               "keep EC shard streams device-resident in a shared "
               "DeviceShardCache so repeated ops feed the kernel "
               "without host round-trips (host copies only at the "
               "client boundary and on store persistence)"),
        Option("osd_ec_resident_max_bytes", int, 256 << 20,
               "byte budget of the per-daemon device shard cache; "
               "crossing it evicts LRU entries to the low watermark",
               Level.ADVANCED, min=1 << 20),
        Option("osd_ec_resident_writeback", bool, False,
               "defer shard-data persistence to cache evict/flush "
               "(attrs-only store commit per write); honored only in "
               "lenient (unlogged) mode — logged acks require the "
               "store commit", Level.ADVANCED),
        Option("osd_ec_repair_batch", bool, True,
               "drain PG missing sets through the batched repair "
               "engine: degraded objects grouped by lost-shard "
               "pattern rebuild in shared decode launches with "
               "locality-aware survivor reads (LRC group-local, CLAY "
               "helper sub-chunks); objects the engine cannot serve "
               "fall back to per-object recovery"),
        Option("osd_ec_repair_batch_objects", int, 64,
               "max degraded objects per batched repair launch (one "
               "mClock recovery grant at this cost paces each batch)",
               Level.ADVANCED, min=1),
        Option("slo_put_p99_ms", float, 0.0,
               "SLO: client write p99 latency target in ms, evaluated "
               "from the windowed op_w_latency_us histograms (0 = "
               "objective disabled)", min=0.0),
        Option("slo_get_p999_ms", float, 0.0,
               "SLO: client read p999 latency target in ms "
               "(op_r_latency_us; 0 = disabled)", min=0.0),
        Option("slo_error_rate", float, 0.0,
               "SLO: max fraction of client ops failing with an IO/"
               "protocol error over the window (0 = disabled)",
               min=0.0, max=1.0),
        Option("slo_rebuild_floor_gibs", float, 0.0,
               "SLO: minimum sustained rebuild rate in GiB/s while "
               "recovery is active — a floor, not a ceiling: rebuilding "
               "slower stretches the degraded window (0 = disabled)",
               min=0.0),
        Option("slo_targets", str, "",
               "extra free-form SLO objectives, comma/space separated "
               "name=value pairs (e.g. 'op_p50_ms=5 get_p99_ms=20') "
               "for quantiles outside the typed options"),
        Option("slo_window", float, 30.0,
               "SLO evaluation sliding window in seconds (the error "
               "budget horizon each burn rate is measured over)",
               min=0.1),
        Option("slo_raise_evals", int, 2,
               "consecutive violating evaluations before SLO_VIOLATION "
               "raises (hysteresis: one noisy window must not flap "
               "health)", Level.ADVANCED, min=1),
        Option("slo_clear_evals", int, 2,
               "consecutive clean evaluations before an active "
               "SLO_VIOLATION clears", Level.ADVANCED, min=1),
        Option("slo_class_labels", str, "gold,bronze",
               "tenant/QoS class labels ops may be stamped with "
               "(loadgen --class, RGW access-key mapping); per-class "
               "op_class_<label>_latency_us histograms and burn pairs "
               "are evaluated for exactly these"),
        Option("slo_class_map", str, "",
               "RGW access-key -> tenant class assignments, comma/"
               "space separated key=class pairs (e.g. "
               "'benchkey=gold'); unmapped keys take the LAST label "
               "of slo_class_labels (bronze)", runtime=True),
        Option("slo_burn_fast_s", float, 300.0,
               "fast window of the per-class multiwindow burn pair "
               "(SRE 5m/1h model); scale down in tests/drills so the "
               "pair resolves within a run", min=0.1, runtime=True),
        Option("slo_burn_slow_s", float, 3600.0,
               "slow window of the per-class multiwindow burn pair; "
               "a class violates only while BOTH windows burn > 1.0 "
               "(fast = still happening, slow = material budget "
               "spend)", min=0.1, runtime=True),
        # mgr time-series store (common/tsdb.py): bounded per-series
        # ring buffers fed each digest cycle, three downsample tiers
        Option("tsdb_raw_points", int, 720,
               "raw-tier ring capacity per series (one point per "
               "report cycle; 720 x 5s = 1h)", min=2),
        Option("tsdb_minute_points", int, 1440,
               "minute-tier ring capacity per series (sum/count/min/"
               "max buckets; 1440 x 1m = 24h)", Level.ADVANCED, min=2),
        Option("tsdb_hour_points", int, 336,
               "hour-tier ring capacity per series (336 x 1h = 14d)",
               Level.ADVANCED, min=2),
        Option("tsdb_tier1_s", float, 60.0,
               "minute-tier bucket width in seconds", Level.ADVANCED,
               min=0.1),
        Option("tsdb_tier2_s", float, 3600.0,
               "hour-tier bucket width in seconds", Level.ADVANCED,
               min=0.1),
        Option("tsdb_max_series", int, 4096,
               "catalog bound: series beyond this are dropped and "
               "counted, never grown", Level.ADVANCED, min=1),
        Option("tsdb_digest_points", int, 60,
               "raw-tier tail points per series shipped in the 'tsdb' "
               "digest section (what 'ceph-tpu top' reads through the "
               "mon; bounds digest growth)", Level.ADVANCED, min=1),
        Option("mgr_perf_collect_delta", bool, True,
               "delta-encode mgr perf collection: OSDs ship only "
               "counters changed since the last acked collect "
               "(epoch-stamped, full resync on ack mismatch) — makes "
               "the 1000-OSD collect payload sublinear; digest/tsdb "
               "contents are bit-identical either way"),
        # adaptive QoS defense plane (mgr_qos): closes the SLO loop by
        # actuating mClock recovery shares, hedge timeouts, and RGW
        # admission from the live burn-rate signal
        Option("qos_enable", bool, False,
               "enable the closed-loop QoS controller (mgr_qos): AIMD "
               "recovery-class mClock retuning + quantile-adaptive EC "
               "hedge timeouts driven by the SLO burn signal"),
        Option("qos_backoff", float, 0.5,
               "multiplicative factor applied to the recovery-class "
               "mClock limit on each burning evaluation (after the "
               "raise hysteresis is satisfied)", Level.ADVANCED,
               min=0.05, max=0.95),
        Option("qos_ramp_ops", float, 16.0,
               "additive ops/s restored to the recovery-class limit on "
               "each clean evaluation (after the clear hysteresis)",
               Level.ADVANCED, min=0.1),
        Option("qos_recovery_max_ops", float, 256.0,
               "recovery-class mClock limit ceiling the controller "
               "ramps back to when client SLOs are healthy",
               Level.ADVANCED, min=1.0),
        Option("qos_recovery_min_ops", float, 4.0,
               "absolute floor for the recovery-class mClock limit: "
               "backoff never starves rebuild below this pace",
               Level.ADVANCED, min=0.1),
        Option("qos_recovery_min_share", float, 0.05,
               "recovery pacing floor as a fraction of "
               "qos_recovery_max_ops (combined with the ops floor and "
               "the slo_rebuild_floor_gibs-derived floor via max)",
               Level.ADVANCED, min=0.0, max=1.0),
        Option("qos_recovery_gib_per_op", float, 1e-3,
               "assumed GiB rebuilt per recovery-class mClock grant, "
               "used to translate slo_rebuild_floor_gibs into a "
               "minimum recovery ops/s", Level.ADVANCED, min=1e-9),
        Option("qos_backfill_max_ops", float, 128.0,
               "backfill-class mClock limit ceiling the controller "
               "ramps back to when client SLOs are healthy (planned "
               "motion gets its own AIMD position, separate from "
               "recovery)", Level.ADVANCED, min=1.0),
        Option("qos_backfill_min_ops", float, 2.0,
               "absolute floor for the backfill-class mClock limit: "
               "backoff never parks planned motion below this pace",
               Level.ADVANCED, min=0.1),
        Option("qos_backfill_min_share", float, 0.02,
               "backfill pacing floor as a fraction of "
               "qos_backfill_max_ops (combined with the ops floor via "
               "max; no rebuild-GiB term — redundancy is intact during "
               "planned motion, so backfill may be squeezed harder "
               "than recovery)", Level.ADVANCED, min=0.0, max=1.0),
        Option("qos_scrub_max_ops", float, 64.0,
               "scrub-class mClock limit ceiling the controller ramps "
               "back to when client SLOs are healthy (integrity "
               "verification gets the third AIMD position)",
               Level.ADVANCED, min=1.0),
        Option("qos_scrub_min_ops", float, 1.0,
               "absolute floor for the scrub-class mClock limit: "
               "backoff never parks verification below this pace",
               Level.ADVANCED, min=0.1),
        Option("qos_scrub_min_share", float, 0.01,
               "scrub pacing floor as a fraction of qos_scrub_max_ops "
               "(combined with the ops floor via max; scrub verifies "
               "fully-redundant data, so of the three background "
               "classes it is squeezed hardest when clients burn)",
               Level.ADVANCED, min=0.0, max=1.0),
        Option("qos_replication_max_ops", float, 64.0,
               "multisite replication-class pacing ceiling in sync "
               "ops/s the controller ramps back to when client SLOs "
               "are healthy (the fourth AIMD position; 0 pushed to an "
               "agent means unlimited, the controller never pushes 0)",
               Level.ADVANCED, min=1.0),
        Option("qos_replication_min_ops", float, 2.0,
               "absolute floor for the replication-class pacing rate: "
               "backoff never parks geo-replication below this pace — "
               "this floor is the knob bounding how fast RPO may grow "
               "while clients burn", Level.ADVANCED, min=0.1),
        Option("qos_replication_min_share", float, 0.05,
               "replication pacing floor as a fraction of "
               "qos_replication_max_ops (combined with the ops floor "
               "via max; unlike scrub, replication protects "
               "not-yet-redundant bytes, so its floor sits above the "
               "scrub share)", Level.ADVANCED, min=0.0, max=1.0),
        Option("qos_hedge_quantile", float, 0.95,
               "derive each OSD's EC hedge-read timeout from this "
               "quantile of its windowed shard-read latency histogram "
               "(0 = adaptive hedging off; the static "
               "osd_ec_hedge_read_timeout then applies unchanged)",
               min=0.0, max=0.9999),
        Option("qos_hedge_min_ms", float, 5.0,
               "clamp floor for the adaptive hedge timeout in ms "
               "(hedging below the healthy tail wastes reads)",
               Level.ADVANCED, min=0.1),
        Option("qos_hedge_max_ms", float, 250.0,
               "clamp ceiling for the adaptive hedge timeout in ms",
               Level.ADVANCED, min=1.0),
        Option("qos_hedge_min_samples", int, 16,
               "minimum shard reads in the window before the adaptive "
               "hedge timeout retunes (thin histograms stay on the "
               "last pushed value)", Level.ADVANCED, min=1),
        Option("rgw_max_inflight", int, 0,
               "RGW admission control: max S3 requests in flight per "
               "frontend before new ones shed with 503 Slow Down "
               "(0 = gate disabled)", min=0),
        Option("rgw_session_ops_per_s", float, 0.0,
               "RGW admission control: per-session (access key) "
               "token-bucket refill rate in requests/s (0 = throttle "
               "disabled)", min=0.0),
        Option("rgw_session_burst", float, 8.0,
               "RGW admission control: per-session token-bucket "
               "capacity (burst size)", Level.ADVANCED, min=1.0),
        Option("rgw_retry_after_s", float, 1.0,
               "Retry-After header value (seconds) on 503 Slow Down "
               "responses", Level.ADVANCED, min=0.0),
        Option("rgw_datalog_shards", int, 1,
               "number of bucket-datalog shards per bucket: mutations "
               "hash by object key onto a shard log, multisite sync "
               "agents keep one replication cursor per shard so replay "
               "and trim parallelise (1 = single legacy log object)",
               min=1, max=4096),
        Option("rgw_gc_obj_min_wait", float, 0.0,
               "defer RGW data-object deletion this many seconds "
               "(rgw_gc_obj_min_wait): >0 routes overwrites through "
               "unique per-write data oids + the GC queue, so a GET "
               "racing an overwrite of the same key never hits a "
               "removed-object window (0 = delete inline)",
               Level.ADVANCED, min=0.0),
        Option("ec_hbm_peak_gibps", float, 763.0,
               "accelerator HBM peak bandwidth in GiB/s (v5e ~819 GB/s "
               "= 763 GiB/s) — the roofline the utilization telemetry "
               "reports achieved device GiB/s against", Level.ADVANCED,
               min=1.0),
        Option("log_to_memory_ring", bool, True, "keep crash ring buffer"),
        Option("debug_default", int, 1, "default subsystem debug level",
               min=0, max=20),
    ]


class ConfigProxy:
    """Thread-safe merged view of the config sources + observer fan-out."""

    def __init__(self, conf_file: str | None = None,
                 overrides: Mapping[str, Any] | None = None):
        self._lock = threading.RLock()
        self._schema: dict[str, Option] = {}
        self._values: dict[str, Any] = {}        # merged non-default values
        self._sources: dict[str, str] = {}       # name -> source tag
        self._observers: dict[str, list[Callable[[str, Any], None]]] = {}
        for opt in global_options():
            self._schema[opt.name] = opt
        if conf_file and os.path.exists(conf_file):
            with open(conf_file) as f:
                for name, value in json.load(f).items():
                    self._apply(name, value, "file")
        for name, opt in self._schema.items():
            env = os.environ.get("CEPH_TPU_" + name.upper())
            if env is not None:
                self._apply(name, env, "env")
        for name, value in (overrides or {}).items():
            self._apply(name, value, "override")

    # -- schema ----------------------------------------------------------
    def register(self, options: list[Option]) -> None:
        with self._lock:
            for opt in options:
                if opt.name not in self._schema:
                    self._schema[opt.name] = opt

    def schema(self) -> dict[str, Option]:
        with self._lock:
            return dict(self._schema)

    # -- access ----------------------------------------------------------
    def _apply(self, name: str, value, source: str):
        opt = self._schema.get(name)
        if opt is None:
            raise KeyError(f"unknown option {name!r}")
        self._values[name] = opt.validate(value)
        self._sources[name] = source

    def get(self, name: str):
        with self._lock:
            if name in self._values:
                return self._values[name]
            return self._schema[name].default

    def __getitem__(self, name: str):
        return self.get(name)

    def set(self, name: str, value, source: str = "runtime") -> None:
        """Runtime set (``ceph config set`` analog); notifies observers."""
        with self._lock:
            opt = self._schema.get(name)
            if opt is None:
                raise KeyError(f"unknown option {name!r}")
            if not opt.runtime and source == "runtime":
                raise PermissionError(f"option {name} requires restart")
            self._apply(name, value, source)
            observers = list(self._observers.get(name, ()))
            value = self._values[name]
        for cb in observers:
            cb(name, value)

    def apply_central(self, values: Mapping[str, Any]) -> None:
        """Apply a central-config-db snapshot (MConfig delivery analog,
        reference mon/MonClient.cc:432). Respects precedence: values set
        from env or explicit overrides outrank the central db."""
        for name, value in values.items():
            if name in self._schema:
                if self._sources.get(name) in ("env", "override"):
                    continue
                self.set(name, value, source="mon")

    def observe(self, name: str, callback: Callable[[str, Any], None]):
        """Hot-reload observer (config_obs.h analog)."""
        with self._lock:
            self._observers.setdefault(name, []).append(callback)

    def show(self) -> dict[str, dict]:
        """``config show`` analog: every option with value + source."""
        with self._lock:
            return {
                name: {
                    "value": self.get(name),
                    "source": self._sources.get(name, "default"),
                    "level": opt.level.value,
                }
                for name, opt in sorted(self._schema.items())
            }
